"""Benchmark: streaming facet->subgrid forward transform throughput.

Runs the full forward pass (every subgrid of the cover) for one or more
catalogue configurations on the available accelerator with the TPU-native
planar backend, checks RMS vs the direct-DFT oracle on sample subgrids,
and reports:

* wall-clock for the whole cover,
* vs_baseline — ratio against the numpy reference backend on the same
  machine (measured on small configs, sample-extrapolated on large ones —
  see `baseline_estimated`),
* tflops / mfu_pct — analytic FLOP count of the matmul-FFT pipeline
  (exact: every op is an einsum of known shape, `swiftly_tpu.utils.flops`)
  divided by wall-clock, and as % of the chip's published peak.

Prints ONE JSON line per configuration; the LAST line is the headline
metric (the north-star large-N config).

Environment knobs:
  BENCH_CONFIGS  comma-separated "name:mode" entries; modes:
                 batched | roundtrip | streamed | roundtrip-streamed
                 (default: 4k batched, 4k round-trip, 32k streamed,
                 32k round-trip-streamed, 64k streamed — headline last)
  BENCH_CONFIG / BENCH_MODE  legacy single-config override
  BENCH_COL_GROUP / BENCH_FACET_GROUP / BENCH_FOLD_GROUP  streamed-path
                 sizing overrides (default: HBM-budget auto)

Modes: "batched" keeps the prepared facet stack resident and runs the
whole cover as one fused program; "roundtrip" additionally feeds every
subgrid back through the fused backward transform and checks the facet
round-trip RMS (the reference demo's end-to-end shape); "streamed" uses
the sampled-DFT column groups with device-resident facets — or, when
the stack exceeds HBM (64k+ on a 16 GiB chip), facet-slab streaming
with exact cross-slab accumulation; "roundtrip-streamed" feeds the
streamed forward's device columns straight into the sampled-residency
backward (adjoint einsum) and verifies the reproduced facets on device.
Streamed accuracy is checked on >= max(100, 2%) oracle subgrids via
device-side residuals (n_rms_samples in the output records the count).
"""

import json
import logging
import os
import sys
import time
import traceback

import numpy as np

log = logging.getLogger("bench")


# Centre-relative source positions (fractions of N) for _bench_sources —
# module-level so the sparse-FoV rescale divisor derives from the SAME
# table (no hand-kept constant to go stale when the spread set changes).
_BENCH_SOURCE_FRACTIONS = [
    (-0.41, -0.37), (-0.23, 0.11), (-0.05, 0.43), (0.02, -0.19),
    (0.17, 0.31), (0.29, -0.45), (0.36, 0.07), (0.44, -0.02),
]


def _bench_source_radius():
    """Max centre-relative RADIUS of the spread source table — the
    sparse-FoV rescale divisor. Derived from the table itself so an
    edit to the fractions can never silently leave a stale divisor that
    lets corner sources escape the covered circle."""
    return max((a * a + b * b) ** 0.5 for a, b in _BENCH_SOURCE_FRACTIONS)


def _bench_sources(N):
    """Point sources SPREAD across the whole image (centre-relative,
    fractions of N), so every subgrid column band carries nontrivial
    signal and the oracle RMS check has power everywhere.

    A single source at the origin leaves far columns at ~1e-17 PSWF-tail
    amplitudes — which is how the r4 128k artifact failed to detect an
    int32 offset-scaling overflow that extracted half the cover's columns
    from the wrong window (see ops.core.scaled_offset).
    """
    return [
        (1.0 + 0.25 * k, int(a * N), int(b * N))
        for k, (a, b) in enumerate(_BENCH_SOURCE_FRACTIONS)
    ]


def _build(backend, params, dtype=None, streamed=False, sparse_fov=None):
    from swiftly_tpu import (
        SwiftlyConfig,
        SwiftlyForward,
        make_full_facet_cover,
        make_full_subgrid_cover,
        make_facet,
        make_sparse_facet_cover,
        sparse_fov_cover_offsets,
    )

    config = SwiftlyConfig(backend=backend, dtype=dtype, **params)
    if sparse_fov:
        # circular-FoV sparse facet cover (the reference's
        # demo_sparse_facet shape): facets exist only where the FoV
        # needs them; sources are scaled into the covered circle so the
        # sparse cover represents the whole sky model exactly
        fov_pixels = int(config.image_size * sparse_fov)
        offsets, masks = sparse_fov_cover_offsets(config, fov_pixels)
        facet_configs = make_sparse_facet_cover(
            config.max_facet_size, offsets, masks
        )
        lim_frac = max(
            sparse_fov / 2
            - config.max_facet_size / (2 * config.image_size),
            4 / config.image_size,
        )
        # rescale by the spread set's max RADIUS so every source lands
        # inside the circle of covered facet CENTRES — bounding
        # per-coordinate instead lets corner sources escape the cover
        # (reported as oracle RMS failures)
        rad = _bench_source_radius()
        sources = [
            (w, int(r * lim_frac / rad), int(c * lim_frac / rad))
            for (w, r, c) in _bench_sources(config.image_size)
        ]
    else:
        facet_configs = make_full_facet_cover(config)
        sources = _bench_sources(config.image_size)
    subgrid_configs = make_full_subgrid_cover(config)
    if streamed:
        from swiftly_tpu.parallel import StreamedForward

        # sparse facet descriptors: point-source facets are zeros plus a
        # few mask-scaled pixels, so hand the streamed executors the
        # pixels (densify() == make_facet(...).real, pinned by tests) —
        # the dense planes are then SYNTHESISED on device, so facet-slab
        # streaming uploads kilobytes per column group instead of the
        # multi-GB stack (decisive through this tunnel's h2d path).
        # BENCH_DENSE_FACETS=1 restores the dense host planes to measure
        # the upload-bound path.
        from swiftly_tpu import make_real_facet, make_sparse_facet

        rdt = np.float32 if dtype is None else np.dtype(dtype)
        if os.environ.get("BENCH_DENSE_FACETS"):
            facet_tasks = [
                (fc, (lambda fc=fc: make_real_facet(
                    config.image_size, fc, sources, dtype=rdt)))
                for fc in facet_configs
            ]
        else:
            facet_tasks = [
                (fc, make_sparse_facet(
                    config.image_size, fc, sources, dtype=rdt))
                for fc in facet_configs
            ]
        col_group = int(os.environ.get("BENCH_COL_GROUP", "0")) or None
        facet_group = int(os.environ.get("BENCH_FACET_GROUP", "0")) or None
        t0 = time.time()
        fwd = StreamedForward(
            config, facet_tasks, residency="device", col_group=col_group,
            facet_group=facet_group,
        )
        log.info("facet data built+laid out in %.1fs (real=%s)",
                 time.time() - t0, fwd._facets_real)
    else:
        facet_tasks = [
            (fc, make_facet(config.image_size, fc, sources))
            for fc in facet_configs
        ]
        fwd = SwiftlyForward(config, facet_tasks, lru_forward=2,
                             queue_size=64)
    return config, fwd, facet_configs, subgrid_configs, sources


def _oracle_sample_stack(config, subgrid_configs, sources, min_n=100,
                         target_pct=2.0, max_bytes=3e8):
    """Device-resident oracle subgrids for >= max(min_n, target_pct%) of
    the cover, spread evenly, + the index map.

    The accuracy check at 32k+ scale: residuals are computed ON DEVICE
    against these uploaded references (d2h on tunnel-attached chips runs
    at ~10 MB/s, so pulling subgrids to compare host-side would dominate
    the benchmark). The stack is capped at `max_bytes` residency: the
    uncapped 2% of the 128k cover was 2.57 GiB of HBM, which alone
    forced the column-group search from G=2 down to the dispatch-bound
    G=1 plan (the r4 128k run's 10.1% MFU); 300 MB still spreads samples
    over every column band, and the multi-point-source model gives every
    band real signal to check."""
    import jax.numpy as jnp

    from swiftly_tpu import make_subgrid

    core0 = config.core
    sg_bytes = subgrid_configs[0].size ** 2 * (
        np.dtype(core0.dtype).itemsize
        * (2 if core0.backend == "planar" else 1)
    )
    n = len(subgrid_configs)
    n_s = min(n, max(min_n, int(n * target_pct / 100)))
    n_s = max(1, min(n_s, int(max_bytes // sg_bytes)))
    stride = max(1, n // n_s)
    idxs = list(range(0, n, stride))
    t0 = time.time()
    core = config.core
    host = []
    for i in idxs:
        ref = make_subgrid(config.image_size, subgrid_configs[i], sources)
        if core.backend == "planar":
            rdt = np.dtype(core.dtype)
            host.append(
                np.stack(
                    [ref.real.astype(rdt), ref.imag.astype(rdt)], axis=-1
                )
            )
        else:
            host.append(np.asarray(ref, dtype=core.dtype))
    stack = jnp.asarray(np.stack(host))
    log.info("oracle sample stack: %d subgrids (%.2f GiB) in %.1fs",
             len(idxs), stack.nbytes / 2**30, time.time() - t0)
    return {i: k for k, i in enumerate(idxs)}, stack


import functools


@functools.lru_cache(maxsize=None)
def _chunk_rms2_fn(Cr, yB):
    """Jitted per-row-chunk |dev - sparse_ref|^2 sum: synthesises the
    reference rows [j0, j0+Cr) by scattering the point-source pixels
    (out-of-chunk pixels drop), so no full [yB, yB] reference plane ever
    materialises next to the live accumulator. Cached so facet-partition
    passes share ONE compile."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(dev, r, c, v, j0):
        chunk = jax.lax.dynamic_slice(
            dev, (j0, jnp.int32(0), jnp.int32(0)), (Cr, yB, 2)
        )
        # rows below the chunk must be remapped to a POSITIVE
        # out-of-bounds index: negative traced indices wrap numpy-style
        # (mode="drop" only discards past-the-end), which double-placed
        # every pixel into the following chunk
        rr = jnp.where((r >= j0) & (r < j0 + Cr), r - j0, Cr)
        ref = jnp.zeros((Cr, yB), chunk.dtype).at[rr, c].add(
            v, mode="drop"
        )
        res_re = chunk[..., 0] - ref
        res_im = chunk[..., 1]
        return jnp.sum(res_re * res_re + res_im * res_im)

    return fn


def _rms2_device(core, got, want):
    """Mean |residual|^2 of one subgrid/facet pair, on device."""
    import jax.numpy as jnp

    res = got - want
    if core.backend == "planar":
        return jnp.mean(jnp.sum(res * res, axis=-1))
    return jnp.mean(jnp.abs(res) ** 2)


def _is_oom(exc) -> bool:
    # one shared classifier (resilience.retry.is_oom) behind every OOM
    # ladder; imported lazily so `import bench` stays jax-free
    from swiftly_tpu.resilience.retry import is_oom

    return is_oom(exc)


def _shrink_streamed_plan(fwd, extra, fold_group=None) -> bool:
    """Halve the streamed working set after an on-chip OOM.

    Order: column group first (the dominant per-dispatch transient), then
    the backward fold group, then force facet-slab streaming. Returns
    False when nothing is left to shrink (the OOM then propagates).
    """
    plan = fwd.last_plan or {}
    G = plan.get("col_group") or 0
    shrunk = False
    if G > 1:
        fwd.col_group = max(1, G // 2)
        shrunk = True
    elif fold_group is not None and fold_group[0] > 1:
        fold_group[0] = max(1, fold_group[0] // 2)
        shrunk = True
    elif (
        plan.get("mode") == "resident" or not plan
    ) and fwd.facet_group != 1:
        # resident facets + minimum group still OOM — or the OOM fired
        # during the resident-stack upload itself, before any plan was
        # recorded: stream facet slabs instead
        for arr in fwd._dev_facets or ():
            arr.delete()
        fwd._dev_facets = None
        fwd.facet_group = 1
        shrunk = True
    if shrunk:
        extra["oom_retries"] = extra.get("oom_retries", 0) + 1
        extra["degraded_plan"] = {
            "col_group": fwd.col_group,
            "facet_group": fwd.facet_group,
            "fold_group": fold_group[0] if fold_group else None,
        }
    return shrunk


def _oom_soft(run, fwd, extra, fold_group=None, retries=2):
    """Run `run()`; on RESOURCE_EXHAUSTED shrink the plan and retry.

    An OOM must yield a slower number plus a warning in the JSON — never
    a dead benchmark (BENCH_r03 was rc=124 from exactly one such OOM).
    """
    import gc

    for attempt in range(retries + 1):
        try:
            return run()
        except Exception as e:
            if not _is_oom(e) or attempt >= retries:
                raise
            log.warning(
                "on-chip OOM (%s); shrinking streamed plan and retrying",
                type(e).__name__,
            )
            if not _shrink_streamed_plan(fwd, extra, fold_group):
                raise
            gc.collect()


def _plan_backward_passes(
    F_total, yB, per_facet_acc, per_facet_rows, fold_group, budget,
    fwd_min=3.3e9, reserve=1.2e9, n_facet_env=0, n_row_env=0,
):
    """Facet x output-row-slab partition plan for the sampled backward.

    Delegates to the unified plan compiler
    (`swiftly_tpu.plan.compiler.plan_backward_passes`, where the
    partition heuristic moved verbatim) — this wrapper keeps the
    historical bench entry point the 128k tests and operator docs name.
    Returns ``(parts, resident_bytes)`` exactly as before.
    """
    from swiftly_tpu.plan import plan_backward_passes

    return plan_backward_passes(
        F_total, yB, per_facet_acc, per_facet_rows, fold_group, budget,
        fwd_min=fwd_min, reserve=reserve,
        n_facet_env=n_facet_env, n_row_env=n_row_env,
    )


def _numpy_baseline_from_parts(params, sources, reps=3):
    """Extrapolate the numpy forward wall-clock from sampled sub-ops.

    At streamed-mode scales (32k+) a full numpy forward pass takes hours
    on one core, so time its three cost centres on small samples and
    scale linearly in op COUNTS (never in config size): facet preparation
    per column block, per-column extraction+preparation, and per-subgrid
    summation/finish.

    Each centre is warmed once (cold first calls carry FFT planning and
    allocator noise — the r4 estimates spread 4x run-to-run) and then
    timed `reps` times; returns ``(low, high)`` totals built from the
    per-centre min / median. Callers report the bracket and use its low
    end for vs_baseline (under-, never over-stating the speedup).
    """
    from swiftly_tpu import (
        SwiftlyConfig,
        make_facet,
        make_full_facet_cover,
        make_full_subgrid_cover,
    )
    from swiftly_tpu.ops import numpy_backend as npk
    from swiftly_tpu.ops.core import prepare_facet_math
    from swiftly_tpu.parallel import batched

    config = SwiftlyConfig(backend="numpy", **params)
    core = config.core
    fcs = make_full_facet_cover(config)
    sgs = make_full_subgrid_cover(config)
    n_facets, yB = len(fcs), fcs[0].size
    m, yN = core.xM_yN_size, core.yN_size
    col_offs0 = sorted({sg.off0 for sg in sgs})

    def sample(fn, scale):
        fn()  # warm: FFT plans, allocator, import side effects
        ts = []
        for _ in range(reps):
            t0 = time.time()
            fn()
            ts.append(time.time() - t0)
        ts.sort()
        return ts[0] * scale, ts[len(ts) // 2] * scale

    facet = make_facet(config.image_size, fcs[0], sources)
    blk = min(256, yB)
    prep_lo, prep_hi = sample(
        lambda: prepare_facet_math(
            npk, core._Fb, yN, facet[:, :blk], fcs[0].off0, 0
        ),
        (yB / blk) * n_facets,
    )

    BF_F = np.zeros((yN, yB), dtype=complex)

    def col_op():
        col = core.extract_from_facet(BF_F, col_offs0[0], 0)
        core.prepare_facet(col, fcs[0].off1, 1)

    col_lo, col_hi = sample(col_op, n_facets * len(col_offs0))

    NMBF_BFs = np.zeros((n_facets, m, yN), dtype=complex)
    offs0 = [fc.off0 for fc in fcs]
    offs1 = [fc.off1 for fc in fcs]
    sg = sgs[0]
    sg_lo, sg_hi = sample(
        lambda: batched.subgrid_from_columns_batch(
            core, NMBF_BFs, offs0, offs1, sg.off0, sg.off1, sg.size,
            (np.ones(sg.size), np.ones(sg.size)),
        ),
        len(sgs),
    )
    return prep_lo + col_lo + sg_lo, prep_hi + col_hi + sg_hi


# Coarse on-chip wall-clock guesses per size class, seconds — the
# projected-cost skip in main() only needs the ORDER OF MAGNITUDE
# (r4/r5 measured: 4k legs ~1-3 s + baseline, 32k streamed ~18 s,
# 32k round trip ~38 s, 64k round trip ~650 s + compiles). Roundtrips
# roughly double the leg; compiles/baselines are folded into the guess.
_LEG_COST_GUESS_S = {
    "1k": 30, "2k": 40, "4k": 60, "8k": 90, "16k": 120,
    "32k": 240, "64k": 900, "128k": 700,
}


def _leg_cost_guess_s(name, mode):
    """Projected wall for one leg (config size class x mode)."""
    base = _LEG_COST_GUESS_S.get(name.split("[")[0], 300)
    return base * (2 if "roundtrip" in mode else 1)


def _cover_kwargs(facet_configs, subgrid_configs):
    """The cover-shape arguments every flops-model call takes."""
    n_cols = len({sg.off0 for sg in subgrid_configs})
    return dict(
        n_facets=len(facet_configs),
        facet_size=facet_configs[0].size,
        n_columns=n_cols,
        subgrids_per_column=len(subgrid_configs) // n_cols,
        subgrid_size=subgrid_configs[0].size,
    )


def _flop_fields(config, facet_configs, subgrid_configs, mode, elapsed,
                 real_facets=False, finish_passes=1, colpass=None):
    """Analytic FLOP count -> tflops / mfu_pct fields.

    `colpass` is the column-pass body the forward executor actually ran
    (its `last_plan["colpass"]` — slab plans resolve from facet_group,
    not the full stack), so the FLOP shape matches the executed program.
    """
    from swiftly_tpu.utils.flops import (
        forward_batched_flops,
        forward_sampled_flops,
        peak_tflops,
    )

    from swiftly_tpu.utils.flops import backward_batched_flops

    core = config.core
    kwargs = _cover_kwargs(facet_configs, subgrid_configs)
    if mode == "streamed":
        flops = forward_sampled_flops(
            core, real_facets=real_facets, finish_passes=finish_passes,
            colpass=colpass, **kwargs,
        )
    elif mode == "roundtrip-streamed":
        from swiftly_tpu.utils.flops import backward_sampled_flops

        flops = forward_sampled_flops(
            core, real_facets=real_facets, finish_passes=finish_passes,
            colpass=colpass, **kwargs,
        ) + backward_sampled_flops(core, **kwargs)
    elif mode == "roundtrip":
        flops = forward_batched_flops(core, **kwargs) + backward_batched_flops(
            core, **kwargs
        )
    else:
        flops = forward_batched_flops(core, **kwargs)
    fields = {"tflops": round(flops / elapsed / 1e12, 2)}
    peak = peak_tflops()
    if peak:
        fields["mfu_pct"] = round(100 * flops / elapsed / 1e12 / peak, 1)
    return fields


def run_one(config_name, mode):
    import jax
    import jax.numpy as jnp

    from swiftly_tpu import SWIFT_CONFIGS, check_subgrid
    from swiftly_tpu.obs import Heartbeat, metrics
    from swiftly_tpu.obs import trace as otrace

    if metrics.enabled():
        metrics.reset()  # one telemetry export per configuration record
    # the leg's root span: everything below (build, warmup, timed pass,
    # baseline) nests under it, so trace_report's critical path covers
    # the whole leg wall. Entered/exited explicitly — the body is not
    # reindented under a `with` — and `leg_wall_s` brackets the span so
    # the artifact's trace block can be checked against it.
    otrace.adopt(0)  # legs are roots, even after a failed leg's leak
    leg_span = otrace.span("bench.leg", cat="bench",
                           config=config_name, mode=mode)
    t_leg0 = time.perf_counter()
    leg_span.__enter__()
    sparse_fov = None
    if mode.endswith("-sparse"):
        # circular-FoV sparse facet cover, composable with the streamed
        # modes (reference scripts/demo_sparse_facet.py:34-181)
        sparse_fov = float(os.environ.get("BENCH_SPARSE_FOV", "0.6"))
        mode = mode[: -len("-sparse")]
    if mode not in ("batched", "roundtrip", "streamed",
                    "roundtrip-streamed", "streamed-partial"):
        raise ValueError(
            f"Unknown bench mode {mode!r} (batched|roundtrip|streamed|"
            "roundtrip-streamed|streamed-partial[-sparse])"
        )

    def force(arr):
        """Force completion via an 8-byte checksum pull — load-bearing:
        the tunnel runtime's block_until_ready returns before the queue
        drains (see run_streamed)."""
        return float(np.asarray(jnp.sum(arr)))

    params = dict(SWIFT_CONFIGS[config_name])
    params.setdefault("fov", 1.0)
    platform = jax.devices()[0].platform
    dtype = jax.numpy.float32

    # --- accelerated run (planar backend) --------------------------------
    streamed_mode = mode in (
        "streamed", "roundtrip-streamed", "streamed-partial"
    )
    config, fwd, facet_configs, subgrid_configs, sources = _build(
        "planar", params, dtype, streamed=streamed_mode,
        sparse_fov=sparse_fov,
    )
    extra = {}
    finish_passes = 1
    real_facets = getattr(fwd, "_facets_real", False)
    mode_label = mode if not sparse_fov else f"{mode}-sparse"
    partial_scale = None
    if sparse_fov:
        extra["sparse_cover"] = {
            "fov_fraction": sparse_fov,
            "n_facets": len(facet_configs),
            "n_facets_dense": (
                -(-config.image_size // config.max_facet_size)
            ) ** 2,
        }

    if mode == "streamed-partial":
        # measured PARTIAL cover: the first BENCH_PARTIAL_COLS subgrid
        # columns through the real full-size (e.g. yN=65536) programs —
        # the measured anchor for estimate_large_config's extrapolation
        # at scales (128k) where a full cover is hours of chip time.
        # Clearly labelled: `partial` records what fraction ran.
        all_offs = sorted({sg.off0 for sg in subgrid_configs})
        n_part = max(1, int(os.environ.get("BENCH_PARTIAL_COLS", "1")))
        n_part = min(n_part, len(all_offs))
        keep = set(all_offs[:n_part])
        n_subgrids_full = len(subgrid_configs)
        subgrid_configs = [sg for sg in subgrid_configs if sg.off0 in keep]
        if fwd.col_group is None:
            fwd.col_group = n_part
        extra["partial"] = {
            "n_columns": n_part,
            "n_columns_full": len(all_offs),
            "n_subgrids_full": n_subgrids_full,
        }
        partial_scale = len(all_offs) / n_part
        mode = "streamed"  # identical execution path from here on

    if mode == "streamed":
        import jax.numpy as jnp

        sample_map, oracle_dev = _oracle_sample_stack(
            config, subgrid_configs, sources
        )
        # the resident oracle stack shrinks the budget the auto-sizers see
        fwd.hbm_headroom = int(oracle_dev.nbytes)

        def run_streamed():
            """Full cover via sampled-DFT column groups; outputs consumed
            on device (device->host bandwidth is not part of the
            transform) and verified on device against the uploaded
            oracle samples.

            Completion is forced through a device-side checksum that
            depends on EVERY column's output, then one 8-byte pull —
            blocking on the last output alone under-reports on runtimes
            whose block_until_ready does not imply whole-queue completion
            (the tunnel-attached TPU here). Records the dispatch-loop
            vs final-drain split (`stream_s` / `drain_s`) so artifacts
            separate streaming from the completion tail."""
            acc = None
            max_rms2 = jnp.zeros((), dtype=jnp.float32)
            t0 = time.time()
            hb = Heartbeat(
                len(subgrid_configs), label=f"{config_name} subgrids",
                interval_s=float(os.environ.get("BENCH_HEARTBEAT_S", "30")),
                log=log,
            )
            for items, out in fwd.stream_columns(
                subgrid_configs, device_arrays=True
            ):
                s = jnp.sum(out)
                acc = s if acc is None else acc + s
                for srow, (i, sgc) in enumerate(items):
                    k = sample_map.get(i)
                    if k is not None:
                        max_rms2 = jnp.maximum(
                            max_rms2,
                            _rms2_device(
                                config.core, out[srow], oracle_dev[k]
                            ),
                        )
                hb.update(len(items))
            hb.finish()
            t1 = time.time()
            float(np.asarray(acc))
            extra["stream_s"] = round(t1 - t0, 2)
            extra["drain_s"] = round(time.time() - t1, 2)
            return float(np.asarray(max_rms2)) ** 0.5

        log.info("streamed: warmup pass (compile + facet upload)")
        t0 = time.time()
        warm_rms = _oom_soft(run_streamed, fwd, extra)
        t_cold = time.time() - t0
        log.info("streamed: warmup done in %.1fs; timed pass", t_cold)
        max_cfg = float(os.environ.get("BENCH_MAX_CONFIG_S", "1800"))
        if os.environ.get("BENCH_SKIP_WARM_PASS") or t_cold > max_cfg:
            # report the cold pass (incl. compiles) rather than paying a
            # second full pass that would starve the configs after this
            # one; flagged honestly
            rms, elapsed = warm_rms, t_cold
            extra["includes_compile"] = True
        else:
            retries_before = extra.get("oom_retries", 0)
            t0 = time.time()
            rms = _oom_soft(run_streamed, fwd, extra)
            elapsed = time.time() - t0
            if extra.get("oom_retries", 0) > retries_before:
                # the timed pass OOM'd and re-ran a shrunk plan: the
                # number includes the failed attempt + its recompiles
                extra["includes_compile"] = True
        log.info("streamed: timed %.1fs", elapsed)
        extra["n_rms_samples"] = len(sample_map)
        extra["rms_sample_pct"] = round(
            100 * len(sample_map) / len(subgrid_configs), 2
        )
        plan = fwd.last_plan or {}
        extra["facets_real"] = fwd._facets_real
        extra["plan"] = plan
        # compiled-plan block for the forward leg too: the same model
        # prices what the executor's sizers chose, so plan coverage is
        # not limited to the roundtrip legs
        from swiftly_tpu.plan import PlanInputs, compile_plan
        from swiftly_tpu.plan import hbm_budget_bytes as _hbm_budget_env

        extra["plan_compiled"] = compile_plan(
            PlanInputs.from_cover(
                config, facet_configs, subgrid_configs,
                hbm_budget=_hbm_budget_env(),
                real_facets=fwd._facets_real,
            ),
            mode="streamed",
        ).artifact_block()
    elif mode == "roundtrip-streamed":
        import jax.numpy as jnp

        from swiftly_tpu.parallel import StreamedBackward

        fold_group = [int(os.environ.get("BENCH_FOLD_GROUP", "2"))]

        # the backward's image-space accumulator + its pending row buffer
        # share the chip with the forward: reserve them out of the budget
        # the forward's auto-sizers see (at 32k this tips the forward into
        # facet-slab streaming, which is the point — the accumulator is
        # the bigger resident and the facets re-stream around it)
        core = config.core
        yB = facet_configs[0].size
        per_el = np.dtype(core.dtype).itemsize * (
            2 if core.backend == "planar" else 1
        )
        F_total = len(facet_configs)
        per_facet_acc = yB * yB * per_el
        per_facet_rows = core.xM_yN_size * yB * per_el

        # Facet x row-slab partitioned backward: the 64k+ accumulator
        # (34 GiB at 64k) cannot fit 16 GiB HBM whole, and ONE 128k
        # facet's accumulator (16.2 GiB) is itself past HBM — but the
        # backward column pass and the adjoint fold both scale with the
        # facets (and the fold's output rows) in the program, so P
        # passes over facet subsets x row slabs do the SAME total
        # backward work. The subgrid stream every pass consumes is
        # persisted ONCE by the spill cache (utils.spill), so the
        # forward runs once and passes 2..P are cache-fed — before the
        # cache, each pass replayed the full forward (~8 x 73 s of the
        # 64k round trip's 703 s).
        from swiftly_tpu.plan import PlanInputs, compile_plan
        from swiftly_tpu.plan import hbm_budget_bytes as _hbm_budget_env
        from swiftly_tpu.plan.model import (
            DEFAULT_FWD_MIN_BYTES,
            DEFAULT_RESERVE_BYTES,
        )

        # the one SWIFTLY_HBM_BUDGET parse (plan.hbm_budget_bytes) —
        # bench used to read the env var next to the streamed
        # executors' own copy
        budget = _hbm_budget_env()
        fwd_min = DEFAULT_FWD_MIN_BYTES  # measured: the 32k roundtrip
        # fwd plan (G=3, slab_depth=2) streams green inside this
        reserve = DEFAULT_RESERVE_BYTES  # fold row-blocks +
        # donation-copy slack
        plan_inputs = PlanInputs.from_cover(
            config, facet_configs, subgrid_configs, hbm_budget=budget,
            real_facets=getattr(fwd, "_facets_real", False),
        )
        # measured-feedback autotune: BENCH_PLAN_HISTORY names artifact
        # globs whose per-stage telemetry refits the model's throughput
        # coefficients (plan.autotune); unset -> static defaults, and
        # the compiled plan is provably the old heuristics' plan
        plan_history = os.environ.get("BENCH_PLAN_HISTORY") or None
        plan_state = {"plan": None}

        def _make_plan():
            # re-planned per run: _oom_soft may have shrunk fold_group
            # (after an OOM the shrunk value is binding — history-based
            # reselection must not grow it back)
            cplan = compile_plan(
                plan_inputs.replace(fold_group=fold_group[0]),
                history=(
                    plan_history.split(",")
                    if plan_history and not extra.get("oom_retries")
                    else None
                ),
                fwd_min=fwd_min, reserve=reserve,
                n_facet_env=int(
                    os.environ.get("BENCH_BWD_FACET_PASSES", "0")
                ),
                n_row_env=int(
                    os.environ.get("BENCH_BWD_ROW_SLABS", "0")
                ),
                allow_spill=os.environ.get("BENCH_SPILL", "1") != "0",
                feed_env=int(
                    os.environ.get("BENCH_BWD_FEED_GROUP", "0")
                ),
            )
            fold_group[0] = cplan.backward.fold_group
            plan_state["plan"] = cplan
            extra["plan_compiled"] = cplan.artifact_block()
            return cplan.backward.parts, cplan.backward.resident_bytes

        def _verify_part(facets_dev, i0, i1, r0, r1):
            """Device-side RMS of reproduced facet (row-slab) [i0:i1) x
            [r0:r1) vs the round trip's own inputs; returns per-facet
            mean |res|^2 over the slab."""
            n = i1 - i0
            Rs = r1 - r0
            if fwd._dev_facets is not None and fwd._facets_real:
                ref = fwd._dev_facets[0]
                res_re = facets_dev[:n, :, :, 0] - ref[i0:i1, r0:r1]
                res_im = facets_dev[:n, :, :, 1]
                return jnp.mean(
                    res_re * res_re + res_im * res_im, axis=(1, 2)
                )
            if getattr(fwd, "_facets_sparse", False):
                # grouped sparse forward: synthesise each reference
                # plane on device (no multi-GB re-upload), in ROW CHUNKS
                # — at 64k the full [yB, yB] ref + residual transients
                # (~6 GiB) next to the live accumulator OOM'd the
                # verification step. Out-of-chunk pixels drop out of the
                # scatter (mode="drop"); each chunk's scalar is pulled
                # before the next dispatch (async dispatch would put all
                # chunks' transients live at once). Row slabs reuse the
                # same program with slab-shifted pixel rows (off-slab
                # rows land outside [0, Rs) and drop).
                yB_full = facets_dev.shape[2]
                n_ch = max(1, int(Rs * yB_full * 12 / 1.2e9))
                while Rs % n_ch:
                    n_ch += 1
                Cr = Rs // n_ch
                chunk_rms2 = _chunk_rms2_fn(Cr, yB_full)
                rms2s = []
                for i in range(i0, i1):
                    _, r, c, v = fwd._sparse_pixels(i, i + 1)
                    r = (r - r0).astype(np.int32)  # slab-relative rows
                    total = 0.0
                    for ci in range(n_ch):
                        total += float(
                            np.asarray(
                                chunk_rms2(
                                    facets_dev[i - i0], r, c, v,
                                    jnp.int32(ci * Cr),
                                )
                            )
                        )
                    rms2s.append(total / (Rs * yB_full))
                return jnp.asarray(rms2s)
            # re-upload per-facet references (grouped forward or
            # complex facets: no resident copy to compare against)
            rms2s = []
            for i in range(i0, i1):
                host_ref = (
                    fwd._facet_data[i]
                    if not fwd._facets_real
                    else np.stack(
                        [fwd._facet_data[i],
                         np.zeros_like(fwd._facet_data[i])],
                        axis=-1,
                    )
                )
                ref = jnp.asarray(host_ref[r0:r1])
                rms2s.append(
                    _rms2_device(config.core, facets_dev[i - i0], ref)
                )
            return jnp.stack(rms2s)

        def run_roundtrip_streamed():
            """StreamedForward -> sampled-residency StreamedBackward,
            entirely on device: forward columns feed the backward's
            adjoint-einsum accumulator, the finished facets are compared
            on device with the round trip's own input facets, and one
            scalar pull forces completion of the whole graph. When the
            accumulator exceeds HBM the backward runs in facet-subset x
            row-slab passes (same total backward work); the subgrid
            stream is persisted ONCE by the spill cache and the passes
            run under the plan's FEED-ONCE/FOLD-MANY schedule
            (`feed_backward_passes`): `feed_group` passes share each
            pass over the stream, so the whole partitioned round trip
            costs 1 forward + (n_feeds - 1) cache-fed feeds instead of
            1 + (n_passes - 1) (counter-asserted via `fwd.passes`; the
            h2d collapse shows in `spill.h2d` bytes). A stream too
            large for the cache budget falls back to forward replay per
            FEED — exact, and the schedule shrinks even that cost."""
            from swiftly_tpu.parallel import feed_backward_passes

            parts, resident = _make_plan()
            cplan = plan_state["plan"]
            feed_q = min(cplan.backward.feed_group, len(parts))
            # the feed's shared accumulators all sit on the chip during
            # the fill feed: the forward's sizers must leave room for
            # every pass in the largest feed chunk, not just one
            fwd.hbm_headroom = int(feed_q * resident + reserve)
            extra["bwd_plan"] = {
                "n_passes": len(parts),
                "n_facet_passes": len({(p[0], p[1]) for p in parts}),
                "n_row_slabs": len({(p[2], p[3]) for p in parts}),
                "feed_group": feed_q,
                "n_feeds": cplan.backward.n_feeds,
            }
            # the spill policy (cache budget, RAM/disk/replay) is the
            # compiled plan's third output — SpillCache no longer prices
            # the stream for itself on this path
            spill = (
                cplan.spill.make_cache() if cplan.spill.use_spill
                else None
            )
            passes0 = feeds0 = h2d0 = 0
            if metrics.enabled():
                exp0 = metrics.export()
                passes0 = (exp0.get("counters") or {}).get(
                    "fwd.passes", 0
                )
                feeds0 = (exp0.get("counters") or {}).get(
                    "bwd.feed_groups", 0
                )
                h2d0 = (
                    (exp0.get("stages") or {}).get("spill.h2d") or {}
                ).get("bytes", 0)
            max_rms2 = 0.0
            extra["pass_s"] = []
            hb = Heartbeat(
                len(subgrid_configs) * len(parts),
                label=f"{config_name} roundtrip subgrids",
                interval_s=float(os.environ.get("BENCH_HEARTBEAT_S", "30")),
                log=log,
            )
            from swiftly_tpu.obs import trace as otrace

            chunks = [
                parts[c0 : c0 + feed_q]
                for c0 in range(0, len(parts), feed_q)
            ]
            for kfeed, chunk in enumerate(chunks):
                t_pass = time.time()
                # the hierarchy's pass level: leg → PASS (one shared
                # feed of feed_group facet x row-slab parts) → feed
                # group → column group → stage
                pass_span = otrace.span(
                    "bwd.pass", cat="bench", feed=kfeed,
                    parts=[list(p) for p in chunk],
                )
                pass_span.__enter__()
                bwds = [
                    StreamedBackward(
                        config, list(facet_configs[i0:i1]),
                        residency="sampled", fold_group=fold_group[0],
                        row_slab=(
                            (r0, r1) if (r0, r1) != (0, yB) else None
                        ),
                    )
                    for i0, i1, r0, r1 in chunk
                ]
                # feed-once/fold-many: ONE pass over the (cached)
                # stream serves every backward in the chunk — group
                # feeding inside (one vmapped column pass + one fold
                # per forward column group per pass); feed 1 records
                # the stream, later feeds are cache-fed
                feed_backward_passes(
                    fwd, subgrid_configs, bwds, spill=spill,
                    progress=hb.update, feed_index=kfeed,
                )
                for bwd, (i0, i1, r0, r1) in zip(bwds, chunk):
                    facets_dev = bwd.finish_device()
                    rms2 = _verify_part(facets_dev, i0, i1, r0, r1)
                    max_rms2 = max(
                        max_rms2, float(np.asarray(jnp.max(rms2)))
                    )
                    del facets_dev
                del bwds
                pass_span.__exit__(None, None, None)
                extra["pass_s"].append(round(time.time() - t_pass, 1))
                if len(chunks) > 1:
                    log.info(
                        "roundtrip feed %d/%d (%d pass(es)) done",
                        kfeed + 1, len(chunks), len(chunk),
                    )
            if spill is not None:
                extra["spill"] = spill.stats()
            if metrics.enabled():
                exp1 = metrics.export()
                extra["forward_passes"] = (
                    exp1.get("counters") or {}
                ).get("fwd.passes", 0) - passes0
                # this run's feed-schedule execution, as deltas (the
                # warmup run shares the registry): feeds run and the
                # cache-fed h2d bytes the schedule actually moved
                extra["feed_groups"] = (
                    exp1.get("counters") or {}
                ).get("bwd.feed_groups", 0) - feeds0
                extra["spill_h2d_bytes"] = (
                    (exp1.get("stages") or {}).get("spill.h2d") or {}
                ).get("bytes", 0) - h2d0
            return max_rms2 ** 0.5

        t0 = time.time()
        warm_rms = _oom_soft(
            run_roundtrip_streamed, fwd, extra, fold_group
        )  # warmup: compile both directions
        t_cold = time.time() - t0
        max_cfg = float(os.environ.get("BENCH_MAX_CONFIG_S", "1800"))
        if os.environ.get("BENCH_SKIP_WARM_PASS") or t_cold > max_cfg:
            rms, elapsed = warm_rms, t_cold
            extra["includes_compile"] = True
        else:
            retries_before = extra.get("oom_retries", 0)
            t0 = time.time()
            rms = _oom_soft(
                run_roundtrip_streamed, fwd, extra, fold_group
            )
            elapsed = time.time() - t0
            if extra.get("oom_retries", 0) > retries_before:
                extra["includes_compile"] = True
        extra["n_rms_samples"] = len(facet_configs)
        extra["rms_check"] = "all facets, device-side vs input facets"
        extra["facets_real"] = fwd._facets_real
        extra["fold_group"] = fold_group[0]
        plan = fwd.last_plan or {}
        extra["plan"] = plan
    elif mode == "roundtrip":
        from swiftly_tpu import backward_all, check_facet

        def run_roundtrip():
            subgrids = fwd.all_subgrids(subgrid_configs)
            facets = backward_all(
                config, facet_configs,
                [(sg, subgrids[i]) for i, sg in enumerate(subgrid_configs)],
            )
            force(facets)
            return facets

        run_roundtrip()  # warmup: compile both fused programs
        t0 = time.time()
        facets = run_roundtrip()
        elapsed = time.time() - t0
        rms = max(
            check_facet(
                config.image_size, fc,
                config.core.as_complex(np.asarray(facets[i])), sources,
            )
            for i, fc in enumerate(facet_configs)
        )
    else:
        # Warmup: compile + run the fused whole-cover program once
        force(fwd.all_subgrids(subgrid_configs))

        # Timed: ONE dispatch (fused scan over columns), ONE host sync —
        # the transform's real device wall-clock, not per-subgrid tunnel
        # latency.
        t0 = time.time()
        results = fwd.all_subgrids(subgrid_configs)
        force(results)
        elapsed = time.time() - t0

        # RMS vs oracle on a few sample subgrids
        rms = max(
            check_subgrid(
                config.image_size, sg, config.core.as_complex(results[i]),
                sources,
            )
            for i, sg in list(enumerate(subgrid_configs))[
                :: max(1, len(subgrid_configs) // 4)
            ]
        )

    # --- numpy reference baseline ----------------------------------------
    log.info("numpy baseline measurement")
    baseline_estimated = streamed_mode
    env_baseline = os.environ.get("BENCH_NUMPY_BASELINE_S")
    if baseline_estimated and env_baseline:
        baseline_source = "operator"
    elif baseline_estimated:
        baseline_source = "estimated"
    else:
        baseline_source = "measured"
    def _estimator_scale():
        """The mode/cover rescale the parts estimator needs to compare
        like with like (shared by the estimated path and the operator-
        supplied provenance check)."""
        scale = 1.0
        if sparse_fov:
            # the parts estimator times the DENSE facet cover; every
            # cost centre scales ~linearly with facet count, so rescale
            # to the sparse cover's
            sc = extra["sparse_cover"]
            scale *= sc["n_facets"] / sc["n_facets_dense"]
        if partial_scale:
            # compare like with like: the numpy estimate covers the full
            # cover, the measured run only 1/partial_scale of its columns
            scale /= partial_scale
        if mode == "roundtrip-streamed":
            # extrapolate the backward leg by the analytic FLOP ratio of
            # the two directions (their op sequences are duals with the
            # same matmul-FFT shapes); flagged baseline_estimated
            from swiftly_tpu.utils.flops import (
                backward_batched_flops as _bb,
                forward_batched_flops as _fb,
            )

            kw = _cover_kwargs(facet_configs, subgrid_configs)
            core = config.core
            scale *= 1.0 + _bb(core, **kw) / _fb(core, **kw)
        return scale

    if baseline_estimated and env_baseline:
        # operator-supplied (e.g. from a prior run of the same config).
        # Provenance is ENFORCED at record time: the estimator bracket
        # is measured anyway (minutes of host time at 64k — the price
        # of an auditable artifact) and recorded NEXT TO the operator
        # figure; a >1.5x disagreement with the bracket warns loudly
        # and stamps `baseline_disagreement` (round-5 flagship
        # artifacts carried hand-typed 600.0/7000.0 baselines ~3.6x off
        # the same round's rehearsal — structurally silent until here).
        numpy_total = float(env_baseline)
        if partial_scale:
            # the supplied figure covers the full cover; the measured
            # run only 1/partial_scale of its columns
            numpy_total /= partial_scale
        try:
            est_lo, est_hi = _numpy_baseline_from_parts(params, sources)
        except Exception:
            log.warning(
                "estimator bracket failed; operator baseline recorded "
                "UNCHECKED", exc_info=True,
            )
        else:
            scale = _estimator_scale()
            est_lo *= scale
            est_hi *= scale
            extra["numpy_baseline_bracket_s"] = [
                round(est_lo, 2), round(est_hi, 2)
            ]
            if numpy_total < est_lo / 1.5 or numpy_total > est_hi * 1.5:
                factor = max(
                    est_lo / max(numpy_total, 1e-9),
                    numpy_total / max(est_hi, 1e-9),
                )
                extra["baseline_disagreement"] = round(factor, 2)
                log.warning(
                    "operator-supplied numpy baseline %.1f s disagrees "
                    "%.1fx with the measured estimator bracket "
                    "[%.1f, %.1f] s — recording both; vs_baseline uses "
                    "the OPERATOR figure, audit it against the bracket",
                    numpy_total, factor, est_lo, est_hi,
                )
    elif baseline_estimated:
        numpy_total, numpy_hi = _numpy_baseline_from_parts(params, sources)
        scale = _estimator_scale()
        numpy_total *= scale
        numpy_hi *= scale
        # vs_baseline uses the LOW end (min-of-reps): under-, never
        # over-states the speedup; the bracket records the spread
        extra["numpy_baseline_bracket_s"] = [
            round(numpy_total, 2), round(numpy_hi, 2)
        ]
    else:
        # Warm one subgrid first so the one-time facet preparation is
        # excluded from the sample, as the planar run's warmup does. Then
        # time ONE FULL FRESH COLUMN: its first subgrid pays the column
        # extraction, the rest share it — the same amortisation the real
        # full-cover run has, so per-subgrid cost is estimated fairly
        # (sampling consecutive subgrids of an already-warm column would
        # exclude extraction entirely; sampling one subgrid per column
        # would charge it S times over).
        cfg_np, fwd_np, fc_np, sg_np, _ = _build("numpy", params)
        fwd_np.get_subgrid_task(sg_np[0])
        col1 = [sg for sg in sg_np if sg.off0 != sg_np[0].off0]
        if col1:
            column = [sg for sg in col1 if sg.off0 == col1[0].off0]
        else:
            # single-column cover: reuse the (already warm) only column —
            # extraction cost is then excluded, a conservative estimate
            column = sg_np[1:] or sg_np
        t0 = time.time()
        tasks_np = [(sg, fwd_np.get_subgrid_task(sg)) for sg in column]
        numpy_total = (time.time() - t0) / len(column) * len(sg_np)
        if mode == "roundtrip":
            from swiftly_tpu import SwiftlyBackward

            n_cols = len({sg.off0 for sg in sg_np})
            bwd_np = SwiftlyBackward(cfg_np, fc_np)
            t0 = time.time()
            bwd_np.add_new_subgrid_tasks(tasks_np)
            numpy_total += (time.time() - t0) / len(column) * len(sg_np)
            # finish() = ONE column fold (a full cover pays K of those)
            # + the final per-facet finishes (paid once); isolate the
            # fold by timing an empty finish (identical final shapes)
            t0 = time.time()
            bwd_np.finish()
            t_fin = time.time() - t0
            bwd_empty = SwiftlyBackward(cfg_np, fc_np)
            t0 = time.time()
            bwd_empty.finish()
            t_fin_empty = time.time() - t0
            t_fold = max(0.0, t_fin - t_fin_empty)
            numpy_total += t_fold * n_cols + t_fin_empty

    leg_span.__exit__(None, None, None)
    leg_wall_s = time.perf_counter() - t_leg0
    if "plan_compiled" in extra:
        # close the loop: the stamped plan carries predicted vs MEASURED
        # wall, which is what bench_compare's mispricing flag and the
        # autotune history read back (sig-fig rounding — a decimal
        # round zeroed sub-0.1 ms smoke legs and dropped the ratio)
        from swiftly_tpu.plan import stamp_measured_wall

        stamp_measured_wall(extra["plan_compiled"], elapsed)
    direction = (
        "forward+backward round-trip"
        if mode in ("roundtrip", "roundtrip-streamed")
        else "forward facet->subgrid"
    )
    if partial_scale:
        extra["extrapolated_full_cover_s"] = round(
            elapsed * partial_scale, 1
        )
    if streamed_mode:
        from swiftly_tpu.utils.profiling import probe_hbm_bytes

        probed = probe_hbm_bytes()
        if probed:
            extra["hbm_probe_gib"] = round(probed / 2**30, 2)
    from swiftly_tpu.obs import run_manifest

    result = {
        "metric": f"{config_name} {direction} wall-clock "
                  f"({len(subgrid_configs)} subgrids, planar f32, "
                  f"{mode_label}, {platform})",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(numpy_total / elapsed, 2),
        "rms_vs_dft_oracle": float(f"{rms:.3e}"),
        "numpy_baseline_s": round(numpy_total, 2),
        "baseline_estimated": baseline_estimated,
        "baseline_source": baseline_source,
        "n_subgrids": len(subgrid_configs),
    }
    result.update(extra)
    result.update(
        _flop_fields(
            config, facet_configs, subgrid_configs, mode, elapsed,
            real_facets=real_facets, finish_passes=finish_passes,
            colpass=(extra.get("plan") or {}).get("colpass"),
        )
    )
    # provenance: every record is self-describing (device, git SHA, env
    # knobs, config hash, baseline pedigree) — VERDICT r5's unauditable-
    # artifact findings are structurally impossible with the stamp
    result["manifest"] = run_manifest(
        baseline_source=baseline_source,
        params={"config": config_name, "mode": mode_label, **params},
    )
    if metrics.enabled():
        result["telemetry"] = metrics.export()
        if "plan_compiled" in result:
            _stamp_plan_accuracy(result)
    if otrace.enabled():
        from swiftly_tpu.obs import summarize_trace

        summary = summarize_trace(
            otrace.export(), root_id=getattr(leg_span, "id", None)
        )
        summary["leg_wall_s"] = round(leg_wall_s, 6)
        result["trace"] = summary
    return result


def _trace_path_from_argv(default="BENCH_trace.json"):
    """The Chrome-trace output path for this invocation, or None.

    ``--trace [PATH]`` (PATH optional — defaults to ``BENCH_trace.json``
    next to the other artifacts) turns the span tracer on for the run;
    ``SWIFTLY_TRACE=1`` + ``SWIFTLY_TRACE_PATH`` are the env twins the
    manifest records.
    """
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        if nxt and not nxt.startswith("--"):
            return nxt
        return os.environ.get("SWIFTLY_TRACE_PATH") or default
    from swiftly_tpu.obs import trace as otrace

    if otrace.enabled():  # SWIFTLY_TRACE=1 at process start
        return otrace.path() or os.environ.get(
            "SWIFTLY_TRACE_PATH"
        ) or default
    return None


def _maybe_enable_trace():
    """Enable the span tracer when ``--trace``/``SWIFTLY_TRACE`` asks
    for it; returns the output path (None = tracing off)."""
    path = _trace_path_from_argv()
    if path:
        from swiftly_tpu.obs import trace as otrace

        otrace.enable(path)
    return path


def _stamp_plan_accuracy(record, dump_path=None):
    """Close the plan-accuracy loop for one leg: join the stamped
    ``plan_compiled`` block against the leg's telemetry into a
    ``plan_accuracy`` block (obs.ledger), append it to the persisted
    calibration history (``SWIFTLY_CALIBRATION_HISTORY``; ``0``
    disables), and — when CALIBRATED stages mispriced beyond the
    threshold — land ``plan.mispriced`` flight-recorder events plus a
    post-mortem dump. Returns the block (also stamped into the
    record)."""
    from swiftly_tpu.obs import ledger as oledger

    block = oledger.plan_accuracy_block(
        record.get("plan_compiled"),
        record.get("telemetry"),
        manifest=record.get("manifest"),
    )
    record["plan_accuracy"] = block
    try:
        oledger.append_history(block)
    except OSError as exc:
        log.warning("calibration history append failed: %s", exc)
    threshold = float(os.environ.get("BENCH_PLAN_THRESHOLD", "2.0"))
    mispriced = oledger.record_mispricing(
        block, threshold=threshold,
        dump_path=dump_path or os.environ.get(
            "BENCH_PLAN_PM_OUT", "BENCH_plan_postmortem.jsonl"
        ),
    )
    if mispriced:
        log.warning(
            "calibrated plan mispriced beyond x%g: %s", threshold,
            ", ".join(f"{n} (x{r:g})" for n, r in mispriced),
        )
    return block


def _maybe_enable_recorder():
    """Flight recorder ON by default for drills (``SWIFTLY_RECORDER=0``
    opts out); returns the recorder module when recording, else None.
    The ring is reset so the post-mortem window is this drill's, not a
    previous leg's."""
    if os.environ.get("SWIFTLY_RECORDER", "1") in ("", "0"):
        return None
    from swiftly_tpu.obs import recorder as orecorder

    orecorder.reset()
    orecorder.enable()
    return orecorder


def _zipf_workload(subgrid_configs, n_requests, seed, zipf_s=1.1):
    """A synthetic serving trace: requests zipf-distributed over
    subgrid COLUMNS (a shuffled popularity ranking, p ∝ 1/rank^s),
    uniform within a column — the ragged-demand shape the coalescing
    scheduler exists for (a few hot columns coalesce into dense
    batches; the tail arrives as singletons).

    :return: (requested configs list, the hottest column's off0)
    """
    rng = np.random.default_rng(seed)
    cols = sorted({sg.off0 for sg in subgrid_configs})
    by_col = {}
    for sg in subgrid_configs:
        by_col.setdefault(sg.off0, []).append(sg)
    order = rng.permutation(len(cols))
    ranks = np.empty(len(cols), dtype=int)
    ranks[order] = np.arange(len(cols))
    p = 1.0 / (ranks + 1.0) ** zipf_s
    p /= p.sum()
    picks = rng.choice(len(cols), size=n_requests, p=p)
    reqs = []
    for c in picks:
        col = by_col[cols[c]]
        reqs.append(col[rng.integers(len(col))])
    return reqs, cols[int(np.argmax(p))]


def serve_bench(smoke_mode=False):
    """`bench.py --serve [--smoke]`: the on-demand serving leg.

    Replays a zipf-over-columns workload through
    `swiftly_tpu.serve.SubgridService` (bounded admission queue →
    locality-aware coalescing scheduler → stacked column programs) and
    stamps the latency-SLO block into a BENCH-style artifact:
    p50/p99 latency, throughput, shed rate, coalesce-hit rate, retry/
    quarantine counts — the harness every future PR regresses serving
    tail latency against.

    The leg is also the serving fault drill: one burst overflows the
    admission queue (sheds recorded, clients get structured rejects),
    a cache feed seeded from the hottest column serves hits until a
    FORCED EVICTION makes later lookups fall back to recomputation, a
    fault injector fails one coalesced batch (its requests retry singly
    to success), and one POISONED request (malformed mask) is
    quarantined without wedging the column behind it. Every served
    result is verified BIT-IDENTICAL against per-request
    `get_subgrid_task` on a fresh forward.

    With ``--smoke`` the leg validates the artifact schema
    (`obs.validate_serve_artifact`) plus the drill outcomes and exits
    nonzero on any problem — wired into tier-1 via
    tests/test_bench_smoke.py.
    """
    import jax

    from swiftly_tpu import api as _api
    from swiftly_tpu.obs import metrics, run_manifest, validate_serve_artifact
    from swiftly_tpu.models import SWIFT_CONFIGS
    from swiftly_tpu.serve import (
        AdmissionQueue,
        CoalescingScheduler,
        SubgridService,
    )
    from swiftly_tpu.models.config import SubgridConfig
    from swiftly_tpu.parallel.streamed import CachedColumnFeed
    from swiftly_tpu.utils import enable_compilation_cache
    from swiftly_tpu.utils.spill import SpillCache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    out_path = os.environ.get("BENCH_SERVE_OUT", "BENCH_serve.json")
    if smoke_mode:
        os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
        metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    name = os.environ.get("BENCH_SERVE_CONFIG", "1k[1]-n512-256")
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "276"))
    seed = int(os.environ.get("BENCH_SERVE_SEED", "1234"))
    zipf_s = float(os.environ.get("BENCH_SERVE_ZIPF_S", "1.1"))
    max_depth = int(os.environ.get("BENCH_SERVE_DEPTH", "64"))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "32"))
    slo_ms = float(os.environ.get("BENCH_SERVE_SLO_MS", "30000"))

    params = dict(SWIFT_CONFIGS[name])
    params.setdefault("fov", 1.0)
    dtype = jax.numpy.float32
    platform = jax.devices()[0].platform
    config, fwd, facet_configs, subgrid_configs, sources = _build(
        "planar", params, dtype
    )
    workload, hot_off0 = _zipf_workload(
        subgrid_configs, n_requests, seed, zipf_s
    )

    # cache feed seeded from the hottest column, recorded through the
    # SAME stacked program the batcher uses — feed hits therefore stay
    # bit-identical to per-request compute. Mid-run the cache is
    # force-evicted: later hot-column lookups raise and the service
    # falls back to recomputation (the spill-replay degrade contract).
    hot_col = [sg for sg in subgrid_configs if sg.off0 == hot_off0]
    stacked = fwd.get_subgrid_tasks(hot_col)
    spill = SpillCache(budget_bytes=2**30)
    spill.begin_fill(tag=("serve-seed", name, len(hot_col)))
    spill.put(
        [list(enumerate(hot_col))],
        np.stack([np.asarray(r) for r in stacked])[None],
    )
    spill.end_fill()
    feed = CachedColumnFeed(spill)

    inject_state = {"armed": 0, "fired": 0}

    def injector(reqs, attempt):
        if attempt == 0 and inject_state["armed"] > 0:
            inject_state["armed"] -= 1
            inject_state["fired"] += 1
            raise RuntimeError("injected transient device failure")

    service = SubgridService(
        fwd,
        queue=AdmissionQueue(max_depth=max_depth),
        scheduler=CoalescingScheduler(
            max_batch=max_batch, urgency_s=0.05
        ),
        cache_feed=feed,
        max_retries=2,
        slo_ms=slo_ms,
        fault_injector=injector,
    )

    if not smoke_mode:
        # move the bucket-shape compiles off the latency path: the
        # power-of-two batch buckets plus the single-request program
        b = 1
        while b <= min(max_batch, len(hot_col) * 2):
            fwd.get_subgrid_tasks([hot_col[0]] * b)
            b *= 2
        fwd.get_subgrid_task(hot_col[0])

    rng = np.random.default_rng(seed + 1)
    tracked = []
    # burst 0 intentionally overflows the admission queue (depth
    # max_depth against a 1.5x burst): sheds are part of the drill
    bursts = [workload[: int(max_depth * 1.5)]]
    rest = workload[int(max_depth * 1.5):]
    burst_n = int(os.environ.get("BENCH_SERVE_BURST", "20"))
    bursts += [
        rest[i : i + burst_n] for i in range(0, len(rest), burst_n)
    ]
    poisoned = SubgridConfig(
        hot_off0, hot_col[0].off1, hot_col[0].size,
        np.ones(hot_col[0].size + 3), None,
    )
    from swiftly_tpu.obs import trace as otrace

    serve_span = otrace.span("bench.serve", cat="bench", config=name)
    t0 = time.time()
    serve_span.__enter__()
    for k, burst in enumerate(bursts):
        if k == 2:
            spill.reset()  # forced eviction: feed index now dangles
        if k == 3:
            inject_state["armed"] = 1  # fail the next coalesced batch
        for sg in burst:
            tracked.append(
                (
                    sg,
                    service.submit(
                        sg,
                        priority=int(rng.integers(0, 4)),
                        deadline_s=(
                            None if rng.integers(0, 7) else 120.0
                        ),
                    ),
                )
            )
        if k == 3:
            tracked.append((poisoned, service.submit(poisoned)))
        while service.pump_once():
            pass
    serve_span.__exit__(None, None, None)
    wall = time.time() - t0

    # bit-identity audit: every served result vs per-request
    # get_subgrid_task on a FRESH forward (fresh LRU, fresh queue)
    _config2, fwd_ref, _fc2, _sg2, _src2 = _build("planar", params, dtype)
    ref_cache = {}
    checked = mismatches = 0
    for sg, req in tracked:
        res = req.result
        if res is None or not res.ok:
            continue
        key = (sg.off0, sg.off1)
        if key not in ref_cache:
            ref_cache[key] = np.asarray(fwd_ref.get_subgrid_task(sg))
        checked += 1
        if not np.array_equal(np.asarray(res.data), ref_cache[key]):
            mismatches += 1

    stats = service.stats()
    n_cols = len({sg.off0 for sg in subgrid_configs})
    record = {
        "metric": (
            f"{name} on-demand subgrid serving "
            f"({stats['n_requests']} zipf requests over {n_cols} "
            f"columns, planar f32, {platform})"
        ),
        "value": round(wall, 4),
        "unit": "s",
        "throughput_rps": round(stats["n_served"] / wall, 2) if wall else 0.0,
        **stats,
        "bit_identical": {"checked": checked, "mismatches": mismatches},
        "fault_drill": {
            "forced_evictions": feed.evicted,
            "injected_failures": inject_state["fired"],
            "poisoned_quarantined": stats["n_quarantined"],
            "queue_drained": len(service.queue) == 0,
        },
        "cache_feed": {
            "indexed": len(feed),
            "hits": feed.hits,
            "misses": feed.misses,
            "evicted": feed.evicted,
        },
        "zipf": {"s": zipf_s, "n_columns": n_cols, "seed": seed},
        "includes_compile": smoke_mode,
        "n_subgrids_cover": len(subgrid_configs),
        "dispatch_path": _api.last_dispatch_path(),
        "manifest": run_manifest(
            params={"config": name, "mode": "serve", **params},
        ),
    }
    if metrics.enabled():
        record["telemetry"] = metrics.export()
    if trace_path:
        from swiftly_tpu.obs import summarize_trace

        summary = summarize_trace(
            otrace.export(), root_id=getattr(serve_span, "id", None)
        )
        summary["leg_wall_s"] = round(wall, 6)
        record["trace"] = summary
        otrace.save(trace_path)
        otrace.disable()

    problems = validate_serve_artifact(record)
    if smoke_mode:
        # drill outcomes: schema alone is not proof the paths ran
        if stats["n_served"] < 200:
            problems.append(f"served {stats['n_served']} < 200 requests")
        if mismatches or checked < stats["n_served"]:
            problems.append(
                f"bit-identity audit failed: {mismatches} mismatches, "
                f"{checked}/{stats['n_served']} checked"
            )
        if not stats["shed_rate"] > 0:
            problems.append("overload burst shed nothing (shed_rate == 0)")
        if not stats["coalesce_hit_rate"] > 0:
            problems.append("no coalesced requests (hit_rate == 0)")
        if not stats["cache_hits"]:
            problems.append("cache feed served no hits")
        if not stats["cache_fallbacks"]:
            problems.append(
                "forced eviction produced no cache->compute fallback"
            )
        if not inject_state["fired"] or not stats["retries"]:
            problems.append(
                f"injected failure did not exercise the retry path "
                f"(fired={inject_state['fired']}, "
                f"retries={stats['retries']})"
            )
        if stats["n_quarantined"] != 1:
            problems.append(
                f"expected exactly 1 quarantined (poisoned) request, "
                f"got {stats['n_quarantined']}"
            )
        if len(service.queue) != 0:
            problems.append(f"queue wedged: {len(service.queue)} pending")
        telemetry = record.get("telemetry") or {}
        t_stages = telemetry.get("stages") or {}
        if not {"serve.batch", "serve.request"} <= set(t_stages):
            problems.append(
                f"missing serve stages in telemetry: {sorted(t_stages)}"
            )
        elif "p50_s" not in t_stages["serve.request"]:
            problems.append("serve.request stage missing p50_s")
        # request journeys: every served request's queue/compute/
        # transfer segments must SUM to its end-to-end latency (they
        # are contiguous timestamp diffs — the p99 decomposition
        # contract), and the stats block aggregates them
        n_journeys = n_bad = 0
        for _sg, req in tracked:
            res = req.result
            if res is None or not res.ok:
                continue
            if not res.journey:
                n_bad += 1
                continue
            n_journeys += 1
            total = sum(res.journey.values())
            if abs(total - res.latency_s) > 1e-6 + 1e-4 * res.latency_s:
                n_bad += 1
        if not n_journeys or n_bad:
            problems.append(
                f"journey decomposition failed: {n_journeys} journeys, "
                f"{n_bad} missing/not summing to end-to-end latency"
            )
        if not stats.get("journey"):
            problems.append("stats missing journey decomposition block")
        if trace_path:
            from swiftly_tpu.obs import validate_trace_artifact

            problems.extend(validate_trace_artifact(record))
            tr_j = (record.get("trace") or {}).get("journeys") or {}
            if not tr_j.get("n_requests"):
                problems.append("trace holds no serve.journey spans")
        gm = (telemetry.get("gauges_max") or {})
        if "serve.queue_depth_peak" not in gm:
            problems.append(
                "gauges_max missing serve.queue_depth_peak watermark"
            )
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2)
    if smoke_mode:
        metrics.disable()
        print(
            json.dumps(
                {
                    "serve_smoke": "ok" if not problems else "failed",
                    "config": name,
                    "artifact": out_path,
                    "n_served": stats["n_served"],
                    "p99_ms": stats["p99_ms"],
                    "shed_rate": stats["shed_rate"],
                    "coalesce_hit_rate": stats["coalesce_hit_rate"],
                    "problems": problems,
                }
            ),
            flush=True,
        )
        return 0 if not problems else 1
    print(json.dumps(record), flush=True)
    return 0 if not problems else 1


def _lat_quantile_ms(latencies_s, q):
    """Latency quantile in ms over a list of seconds-samples."""
    if not latencies_s:
        return 0.0
    lat = sorted(latencies_s)
    return round(lat[min(len(lat) - 1, int(q * len(lat)))] * 1e3, 3)


def _vis_build(params, kernel, dtype):
    """Forward + cover for the visibility leg.

    Differs from `_build` in one load-bearing way: the sky model is
    band-limited into the degrid kernel's accuracy band and GRID-
    CORRECTED (`vis.kernel.VisKernel.correct_sources`) before facets
    are built, so degridded samples approximate the TRUE visibilities
    of the returned RAW sources — the direct-DFT oracle the leg audits
    against (`vis.oracle.vis_oracle`).
    """
    from swiftly_tpu import (
        SwiftlyConfig,
        SwiftlyForward,
        make_facet,
        make_full_facet_cover,
        make_full_subgrid_cover,
    )

    config = SwiftlyConfig(backend="planar", dtype=dtype, **params)
    N = config.image_size
    maxc = max(
        max(abs(a), abs(b)) for a, b in _BENCH_SOURCE_FRACTIONS
    )
    # 0.9 of the band edge: the kernel fit's error grows toward the
    # band boundary, so the margin keeps the measured oracle RMS well
    # inside DEGRID_TOLERANCE instead of brushing it
    scale = 0.9 * kernel.band / 2.0 / maxc
    raw = [
        (w, int(x * scale), int(y * scale))
        for (w, x, y) in _bench_sources(N)
    ]
    corrected = kernel.correct_sources(raw, N)
    facet_configs = make_full_facet_cover(config)
    tasks = [
        (fc, make_facet(N, fc, corrected)) for fc in facet_configs
    ]
    fwd = SwiftlyForward(config, tasks, lru_forward=2, queue_size=64)
    return config, fwd, facet_configs, make_full_subgrid_cover(config), raw


def _vis_zipf_uv(subgrid_configs, n_samples, seed, zipf_s, margin, N):
    """Zipf-over-(u, v) sample workload: columns ranked zipf (shuffled
    popularity, p ∝ 1/rank^s), samples uniform inside a column subgrid's
    interior (``margin`` pixels in from the span edge, so the kernel
    footprint lands in-cover), plus a 10% uniform-over-the-grid tail
    whose off-cover samples exercise the structured shed path.

    :return: ([n, 2] uv array, hottest column's off0)
    """
    rng = np.random.default_rng(seed)
    cols = sorted({sg.off0 for sg in subgrid_configs})
    by_col = {}
    for sg in subgrid_configs:
        by_col.setdefault(sg.off0, []).append(sg)
    order = rng.permutation(len(cols))
    ranks = np.empty(len(cols), dtype=int)
    ranks[order] = np.arange(len(cols))
    p = 1.0 / (ranks + 1.0) ** zipf_s
    p /= p.sum()
    n_spread = n_samples // 10
    n_zipf = n_samples - n_spread
    uv = np.empty((n_samples, 2))
    picks = rng.choice(len(cols), size=n_zipf, p=p)
    for i, c in enumerate(picks):
        col = by_col[cols[c]]
        sg = col[rng.integers(len(col))]
        half = sg.size / 2.0 - margin
        uv[i] = (
            sg.off0 + rng.uniform(-half, half),
            sg.off1 + rng.uniform(-half, half),
        )
    uv[n_zipf:] = rng.uniform(0, N, size=(n_spread, 2))
    return uv, cols[int(np.argmax(p))]


def vis_bench(smoke_mode=False):
    """`bench.py --vis [--smoke]`: the visibility-serving leg.

    Replays a zipf-over-(u, v) workload through
    `swiftly_tpu.vis.VisibilityService` (sample batches split by owning
    subgrid, coalesced by column through the serve admission/scheduling
    machinery, answered by one degrid dispatch per touched subgrid off
    cache-fed or computed rows) and stamps the ``vis`` artifact block:
    latency quantiles, shed/coalesce/cache rates, served-sample
    throughput — AUDITED for accuracy, not just speed: every served
    sample is compared against the direct-DFT oracle
    (`vis.oracle.vis_oracle`, rel RMS within the kernel's stamped
    tolerance), the degrid/grid adjoint dot-product identity is
    asserted, and the gridded batch round-trips into
    `parallel.streamed.StreamedBackward.add_subgrid_group`.

    Drills folded into the replay: an admission-queue overload burst
    (structured "depth" sheds), a FORCED spill eviction (later hot-
    column lookups fall back to recomputation), a boundary-straddling
    batch shed with ``outside_cover``, and a facet update after which
    the version-pinned `vis.VisGridder` REFUSES stale-era batches and
    the service serves compute-path only (the dropped feed's rows
    belong to the superseded stack). Served cache-path samples are
    verified BIT-IDENTICAL against direct `vis.degrid.degrid_batch` on
    rows from a fresh forward. A small `serve.SubgridService` burst on
    the same forward anchors the throughput contract: served samples/s
    must be >= 10x the subgrid-serving request rate (the whole point
    of serving samples instead of rows).

    With ``--smoke`` the leg validates the artifact schema
    (`obs.validate_vis_artifact`) plus the drill outcomes and exits
    nonzero on any problem — wired into tier-1 via
    tests/test_bench_smoke.py.
    """
    import jax

    from swiftly_tpu import api as _api
    from swiftly_tpu.models import SWIFT_CONFIGS
    from swiftly_tpu.obs import metrics, run_manifest, validate_vis_artifact
    from swiftly_tpu.parallel import StreamedBackward
    from swiftly_tpu.parallel.streamed import CachedColumnFeed
    from swiftly_tpu.plan import price_vis
    from swiftly_tpu.serve import (
        AdmissionQueue,
        CoalescingScheduler,
        SubgridService,
    )
    from swiftly_tpu.utils import enable_compilation_cache
    from swiftly_tpu.utils.spill import SpillCache
    from swiftly_tpu.vis import (
        ADJOINT_TOLERANCE,
        VisGridder,
        VisibilityService,
        degrid_batch,
        grid_batch,
        vis_kernel,
        vis_oracle,
    )

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    out_path = os.environ.get("BENCH_VIS_OUT", "BENCH_vis.json")
    if smoke_mode:
        os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
        metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    name = os.environ.get("BENCH_VIS_CONFIG", "")
    n_samples = int(os.environ.get("BENCH_VIS_SAMPLES", "2000"))
    seed = int(os.environ.get("BENCH_VIS_SEED", "1234"))
    zipf_s = float(os.environ.get("BENCH_VIS_ZIPF_S", "1.1"))
    max_depth = int(os.environ.get("BENCH_VIS_DEPTH", "64"))
    max_batch = int(os.environ.get("BENCH_VIS_MAX_BATCH", "16"))
    slo_ms = float(os.environ.get("BENCH_VIS_SLO_MS", "30000"))
    n_serve = int(os.environ.get("BENCH_VIS_SERVE_REQUESTS", "24"))

    if name:
        params = dict(SWIFT_CONFIGS[name])
        params.setdefault("fov", 1.0)
    else:
        # smoke-scale geometry (the tests' known-good small set: real
        # PSWF margin between yB and yN, so served rows carry signal)
        name = "vis-n256"
        params = dict(W=8.0, fov=1.0, N=256, yB_size=96, yN_size=128,
                      xA_size=56, xM_size=64)
    kernel = vis_kernel()
    platform = jax.devices()[0].platform
    config, fwd, facet_configs, subgrid_configs, sources = _vis_build(
        params, kernel, jax.numpy.float32
    )
    N = config.image_size
    uv_all, hot_off0 = _vis_zipf_uv(
        subgrid_configs, n_samples, seed, zipf_s,
        kernel.support + 1, N,
    )
    cols_sorted = sorted({sg.off0 for sg in subgrid_configs})
    hot_col = [sg for sg in subgrid_configs if sg.off0 == hot_off0]

    # cache feed seeded from the hottest column through the SAME
    # per-subgrid program the compute fallback uses — feed hits stay
    # bit-identical to fallback recompute. Mid-run the spill is
    # force-evicted: later hot-column lookups raise and the service
    # falls back to recomputation (the spill-replay degrade contract).
    hot_rows = [np.asarray(fwd.get_subgrid_task(sg)) for sg in hot_col]
    spill = SpillCache(budget_bytes=2**30)
    spill.begin_fill(tag=("vis-seed", name, len(hot_col)))
    spill.put([list(enumerate(hot_col))], np.stack(hot_rows)[None])
    spill.end_fill()
    feed = CachedColumnFeed(spill)

    service = VisibilityService(
        fwd,
        subgrid_configs=subgrid_configs,
        kernel=kernel,
        cache_feed=feed,
        queue=AdmissionQueue(max_depth=max_depth),
        scheduler=CoalescingScheduler(
            max_batch=max_batch, urgency_s=0.05
        ),
        slo_ms=slo_ms,
    )

    from swiftly_tpu.obs import trace as otrace

    rng = np.random.default_rng(seed + 1)
    burst = max(16, n_samples // 12)
    bursts = [
        uv_all[i : i + burst] for i in range(0, len(uv_all), burst)
    ]
    # in-cover point on the hottest subgrid: the overload drill's
    # repeated target (same owning subgrid -> coalesced singles)
    hot_pt = np.array(
        [[hot_col[0].off0 + 0.3, hot_col[0].off1 + 0.3]]
    )
    # a kernel footprint straddling the border between the first two
    # columns can be answered by neither side's row: structured shed
    border = (cols_sorted[0] + cols_sorted[1]) / 2.0
    uv_outside = np.array(
        [[border + 0.25, hot_off0], [border - 0.25, hot_off0]]
    )

    tracked = []
    vis_span = otrace.span("bench.vis", cat="bench", config=name)
    t0 = time.time()
    vis_span.__enter__()
    # overload drill: 1.5x the admission depth as single-sample
    # submissions with no pump between them — past max_depth they shed
    # with the queue's structured "depth" reason; the admitted ones
    # coalesce (one subgrid) into max_batch-sized degrid dispatches
    for _ in range(int(max_depth * 1.5)):
        tracked.append((hot_pt, service.submit(hot_pt)))
    while service.pump_once():
        pass
    outside_handle = None
    pending = 0
    for k, b in enumerate(bursts):
        if k == 1:
            outside_handle = service.serve(uv_outside)
        if k == 3:
            spill.reset()  # forced eviction: feed index now dangles
        tracked.append(
            (b, service.submit(b, priority=int(rng.integers(0, 4))))
        )
        pending += 1
        # drain every second burst so concurrent batches overlap on the
        # hot columns (the coalescing the scheduler exists for)
        if pending >= 2 or k == len(bursts) - 1:
            while service.pump_once():
                pass
            pending = 0
    vis_span.__exit__(None, None, None)
    wall = time.time() - t0
    stats_run = service.stats()

    # accuracy audit: every served sample vs the direct-DFT oracle of
    # the RAW (band-limited, uncorrected) sky model
    served_uv, served_vis = [], []
    for uv_b, h in tracked:
        m = np.isfinite(h.data)
        if m.any():
            served_uv.append(np.atleast_2d(uv_b)[m])
            served_vis.append(h.data[m])
    served_uv = np.concatenate(served_uv)
    served_vis = np.concatenate(served_vis)
    oracle = vis_oracle(sources, served_uv, N)
    degrid_rms = float(
        np.sqrt(np.mean(np.abs(served_vis - oracle) ** 2))
        / max(np.sqrt(np.mean(np.abs(oracle) ** 2)), 1e-30)
    )

    # bit-identity audit: served samples vs direct degrid_batch on rows
    # from a FRESH forward (fresh LRU/queue; per-lane einsum
    # independence makes batch shape irrelevant to the bits)
    _c2, fwd_ref, _fc2, _sg2, _src2 = _vis_build(
        params, kernel, jax.numpy.float32
    )
    ref_rows = {}
    checked = mismatches = 0
    for uv_b, h in tracked:
        owners, _shed = service.cover.map_samples(np.atleast_2d(uv_b))
        for key, entry in owners.items():
            got = h.data[entry["idx"]]
            m = np.isfinite(got)
            if not m.any():
                continue
            if key not in ref_rows:
                ref_rows[key] = np.asarray(
                    fwd_ref.get_subgrid_task(service.cover.config(*key))
                )
            ref = degrid_batch(
                ref_rows[key], entry["iu0"], entry["iv0"],
                kernel.weights(entry["fu"], dtype=np.float64),
                kernel.weights(entry["fv"], dtype=np.float64),
            )
            checked += int(m.sum())
            mismatches += int(np.sum(got[m] != ref[m]))

    # adjoint audit: < degrid(G), y > == < G, grid(y) > over a fresh
    # in-cover batch (the dot-product identity pinning grid as the
    # exact adjoint; float32 accumulation noise only)
    rng_adj = np.random.default_rng(seed + 5)
    half = hot_col[0].size / 2.0 - kernel.support - 1
    uv_adj = np.stack(
        [
            hot_off0 + rng_adj.uniform(-half, half, size=64),
            hot_col[0].off1 + rng_adj.uniform(-half, half, size=64),
        ],
        axis=1,
    )
    owners_adj, _ = service.cover.map_samples(uv_adj)
    lhs = rhs = 0.0 + 0.0j
    for key, entry in owners_adj.items():
        sg = service.cover.config(*key)
        row = ref_rows.get(key)
        if row is None:
            row = np.asarray(fwd_ref.get_subgrid_task(sg))
        plane = row[..., 0] + 1j * row[..., 1]
        cu = kernel.weights(entry["fu"], dtype=np.float64)
        cv = kernel.weights(entry["fv"], dtype=np.float64)
        d = degrid_batch(row, entry["iu0"], entry["iv0"], cu, cv)
        y = (
            rng_adj.normal(size=d.size)
            + 1j * rng_adj.normal(size=d.size)
        )
        ar, ai = grid_batch(
            sg.size, entry["iu0"], entry["iv0"], cu, cv, y
        )
        lhs += np.vdot(d, y)
        rhs += np.vdot(plane, ar + 1j * ai)
    adjoint_rel = float(abs(lhs - rhs) / max(abs(lhs), 1e-30))

    # gridding round-trip: accumulate every served sample through the
    # version-pinned gridder and ingest the emitted columns into the
    # backward's add_subgrid_group form (residency="sampled")
    gridder = VisGridder(
        service.cover, kernel,
        stream_version=service.stream_version,
        version_of=lambda: service.stream_version,
    )
    gridder.add_batch(served_uv, served_vis)
    col_sg_lists, stack = gridder.emit(planar=True)
    bwd = StreamedBackward(config, facet_configs, residency="sampled")
    bwd.add_subgrid_group(col_sg_lists, jax.numpy.asarray(stack))
    ingested = True

    # facet-update drill: version gates must hold — the pinned gridder
    # refuses the next batch outright, the dropped feed's rows are
    # unreachable (hits frozen), and post-update serving is compute-path
    pre_update_hits = service.stats()["cache_hits"]
    service.post_facet_update()
    stale_refused = False
    try:
        gridder.add_batch(served_uv[:4], served_vis[:4])
    except LookupError:
        stale_refused = True
    post_handle = service.serve(hot_pt)
    post_compute_only = all(
        r.result is not None and r.result.ok
        and r.result.path == "compute"
        for r in post_handle.children
    )
    post_hits_delta = service.stats()["cache_hits"] - pre_update_hits

    # throughput anchor: a subgrid-serving burst on the SAME forward —
    # the rate a row-granular client would get; served samples/s must
    # beat it 10x or visibility serving has no reason to exist
    serve_reqs, _hot2 = _zipf_workload(
        subgrid_configs, n_serve, seed + 7, zipf_s
    )
    serve_svc = SubgridService(
        fwd,
        queue=AdmissionQueue(max_depth=max_depth),
        scheduler=CoalescingScheduler(max_batch=1, urgency_s=0.05),
    )
    t1 = time.time()
    serve_tracked = [serve_svc.submit(sg) for sg in serve_reqs]
    while serve_svc.pump_once():
        pass
    serve_wall = time.time() - t1
    serve_stats = serve_svc.stats()
    serve_rps = (
        serve_stats["n_served"] / serve_wall if serve_wall else 0.0
    )
    samples_per_s = (
        stats_run["n_served_samples"] / wall if wall else 0.0
    )
    serve_ratio = samples_per_s / serve_rps if serve_rps else 0.0

    stats = service.stats()
    n_cols = len(cols_sorted)
    hit_rate = stats["cache_hits"] / max(1, stats["n_batches"])
    plan = price_vis(
        n_samples=stats["n_samples"],
        subgrid_size=config.max_subgrid_size,
        support=kernel.support,
        cache_hit_rate=hit_rate,
        include_grid=True,
    )
    vis_block = {
        **stats,
        "throughput_ksamples_s": round(samples_per_s / 1e3, 4),
        "degrid_rms": degrid_rms,
        "kernel": kernel.as_dict(),
        "adjoint": {
            "rel_err": adjoint_rel,
            "tolerance": ADJOINT_TOLERANCE,
        },
        "grid": {
            "n_gridded": gridder.n_gridded,
            "n_shed": gridder.n_shed,
            "batches": gridder.batches,
            "columns": len(col_sg_lists),
            "ingested": ingested,
            "stale_refused": stale_refused,
        },
        "serve_baseline": {
            "n_requests": n_serve,
            "n_served": serve_stats["n_served"],
            "wall_s": round(serve_wall, 4),
            "rps": round(serve_rps, 3),
            "samples_per_s": round(samples_per_s, 2),
            "ratio": round(serve_ratio, 2),
        },
        "version_gate": {
            "facet_updates": stats["facet_updates"],
            "gridder_refused": stale_refused,
            "post_update_cache_hits_delta": post_hits_delta,
            "post_update_compute_only": post_compute_only,
        },
        "plan": plan.as_dict(),
    }
    record = {
        "metric": (
            f"{name} visibility serving ({stats['n_samples']} zipf "
            f"(u,v) samples over {n_cols} columns, planar f32, "
            f"{platform})"
        ),
        "value": round(wall, 4),
        "unit": "s",
        "throughput_rps": round(stats["n_served"] / wall, 2) if wall else 0.0,
        "vis": vis_block,
        "bit_identical": {"checked": checked, "mismatches": mismatches},
        "cache_feed": {
            "indexed": len(feed),
            "hits": feed.hits,
            "misses": feed.misses,
            "evicted": feed.evicted,
        },
        "zipf": {"s": zipf_s, "n_columns": n_cols, "seed": seed},
        "includes_compile": True,
        "n_subgrids_cover": len(subgrid_configs),
        "dispatch_path": _api.last_dispatch_path(),
        "plan_compiled": {
            "predicted": {"stages": plan.as_dict()["predicted"]},
            "coeffs_source": plan.coeffs_source,
            "config": name,
            "mode": "vis",
        },
        "manifest": run_manifest(
            params={"config": name, "mode": "vis", **params},
        ),
    }
    if metrics.enabled():
        record["telemetry"] = metrics.export()
        _stamp_plan_accuracy(record)
    if trace_path:
        from swiftly_tpu.obs import summarize_trace

        summary = summarize_trace(
            otrace.export(), root_id=getattr(vis_span, "id", None)
        )
        summary["leg_wall_s"] = round(wall, 6)
        record["trace"] = summary
        otrace.save(trace_path)
        otrace.disable()

    problems = validate_vis_artifact(record)
    if smoke_mode:
        # drill outcomes: schema alone is not proof the paths ran
        total = stats["n_samples"]
        if stats["n_served_samples"] < 0.5 * total:
            problems.append(
                f"served {stats['n_served_samples']}/{total} samples "
                "(< 50%)"
            )
        if not checked or mismatches:
            problems.append(
                f"bit-identity audit failed: {mismatches} mismatches, "
                f"{checked} checked"
            )
        if not stats["shed_reasons"].get("outside_cover"):
            problems.append("no outside_cover sheds (spread tail + "
                            "boundary drill both missed)")
        if outside_handle is None or outside_handle.status != "shed" \
                or outside_handle.shed_reason != "outside_cover":
            problems.append(
                "boundary-straddling batch was not shed outside_cover "
                f"(got {outside_handle!r})"
            )
        if not stats["shed_reasons"].get("depth"):
            problems.append(
                "overload burst shed nothing with the 'depth' reason"
            )
        if not stats["cache_hits"]:
            problems.append("cache feed served no hits")
        if not stats["cache_fallbacks"]:
            problems.append(
                "forced eviction produced no cache->compute fallback"
            )
        if not stats["coalesce_hit_rate"] > 0:
            problems.append("no coalesced sample slices (hit_rate == 0)")
        if serve_ratio < 10.0:
            problems.append(
                f"served-sample throughput only {serve_ratio:.1f}x the "
                "subgrid-serving request rate (contract: >= 10x)"
            )
        if not stale_refused:
            problems.append(
                "stale-pinned gridder accepted a post-update batch"
            )
        if post_hits_delta or not post_compute_only:
            problems.append(
                f"post-facet-update serving touched the dropped feed "
                f"(hits delta {post_hits_delta}, compute_only="
                f"{post_compute_only})"
            )
        if len(service.queue) != 0:
            problems.append(f"queue wedged: {len(service.queue)} pending")
        telemetry = record.get("telemetry") or {}
        t_stages = telemetry.get("stages") or {}
        if not {"vis.degrid", "vis.row_fetch", "vis.grid"} <= set(t_stages):
            problems.append(
                f"missing vis stages in telemetry: {sorted(t_stages)}"
            )
        if "vis.queue_depth_peak" not in (
            telemetry.get("gauges_max") or {}
        ):
            problems.append(
                "gauges_max missing vis.queue_depth_peak watermark"
            )
        if not stats.get("journey"):
            problems.append("stats missing journey decomposition block")
        if trace_path:
            from swiftly_tpu.obs import validate_trace_artifact

            problems.extend(validate_trace_artifact(record))
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2)
    if smoke_mode:
        metrics.disable()
        print(
            json.dumps(
                {
                    "vis_smoke": "ok" if not problems else "failed",
                    "config": name,
                    "artifact": out_path,
                    "n_served_samples": stats["n_served_samples"],
                    "p99_ms": stats["p99_ms"],
                    "shed_rate": stats["shed_rate"],
                    "degrid_rms": round(degrid_rms, 6),
                    "adjoint_rel_err": round(adjoint_rel, 9),
                    "serve_ratio": round(serve_ratio, 2),
                    "throughput_ksamples_s": round(
                        samples_per_s / 1e3, 4
                    ),
                    "problems": problems,
                }
            ),
            flush=True,
        )
        return 0 if not problems else 1
    print(json.dumps(record), flush=True)
    return 0 if not problems else 1


def fleet_bench(smoke_mode=False):
    """`bench.py --fleet [--smoke]`: the self-healing serve-fleet drill.

    Runs ``BENCH_FLEET_REPLICAS`` (default 3) `SubgridService` replicas
    — threads, one prepared forward each, one simulated chip per
    replica — behind the `swiftly_tpu.serve.ServeFleet` rendezvous
    column router with health leases and per-replica circuit breakers,
    and replays the SAME zipf-over-columns workload through four
    phases:

    1. **before** — a clean window; its p99 is the recovery baseline;
    2. **kill** — the same workload submitted as a burst, then a
       deterministic ``fleet.replica.kill`` fault (`WorkerKilled` in a
       replica pump — simulated chip death) lands mid-stream: the
       victim's lease misses beats → suspect → probe fails → revoked;
       its breaker trips open; its queued + in-flight requests fail
       over to the survivors with the backoff ladder (laggards past
       the p99 budget are hedged). ZERO requests may be lost;
    3. **after** — the victim is restored (fresh pump over its warm
       forward); the breaker goes half-open, probe requests close it,
       and the window's p99 must recover to <= 1.5x the *before* p99;
    4. **overload** — injected ``fleet.route`` faults are survived by
       the route retry, then the brownout ladder is drilled with a
       forced queue-share signal: rung 1 sheds priority-0 submissions
       with a structured ``retry_after_s``, rung 2 degrades every
       replica to per-request dispatch, then hysteresis steps back
       down. (The signal is forced so the drill is deterministic; the
       organic signal path is pinned by tests/test_fleet.py.)
    5. **autoscale** — a sustained zipf burst under a forced-high
       journey signal must scale the fleet out through the
       `serve.FleetAutoscaler` (each newcomer serves a `cache` fabric
       feed VIEW — an L1 over the one resident stream, never a copy),
       then a forced-low signal drains the extras back through the
       zero-loss retire path; a final clean window pins p99 where the
       *before* phase left it.

    The whole fleet serves ONE recorded subgrid stream through the
    shared cache fabric (`cache.SharedStreamTier` over the
    `delta.IncrementalForward` recording): per-replica hot-row L1s
    (sized by `plan.price_cache_tier`'s break-even) over a single
    versioned spill-backed L2 — the artifact's ``cache`` block asserts
    exactly one resident stream copy and a >= 10x QPS-equivalent over
    the timed single-service compute baseline.

    Since the control tower (PR 15) the drill also exercises the fleet
    observability plane: every replica, the cache fabric, the
    autoscaler and the fleet itself register as tower sources; the
    flight recorder is ON by default (``SWIFTLY_RECORDER=0`` opts out)
    and the kill's post-mortem bundle is stamped + dumped next to the
    artifact; two declarative SLOs ride the supervisor tick and the
    forced brownout ladder must open (then close) the burn-rate alert.
    The artifact's ``fleet_telemetry`` and ``alerts`` blocks are
    validated by `obs.validate_fleet_telemetry_artifact` /
    `obs.validate_alerts_artifact`.

    Every served result is audited BIT-IDENTICAL against per-request
    `get_subgrid_task` on a fresh forward — failover, hedging and the
    cache fabric must never change an answer. The artifact's ``fleet``
    and ``cache`` blocks (validated by `obs.validate_fleet_artifact`)
    record per-replica QPS, the failover/hedge/brownout/autoscale
    counters, fabric hit/miss/dedup stats, the victim's full breaker
    cycle and the p99 before/during/after windows; with ``--smoke``
    the drill outcomes are asserted and the leg exits nonzero on any
    problem (wired into tier-1 via tests/test_bench_smoke.py).
    """
    import jax

    from swiftly_tpu import (
        SwiftlyConfig,
        SwiftlyForward,
        make_facet,
        make_full_facet_cover,
        make_full_subgrid_cover,
    )
    from swiftly_tpu.models import SWIFT_CONFIGS
    from swiftly_tpu.obs import (
        metrics,
        run_manifest,
        validate_fleet_artifact,
    )
    from swiftly_tpu.resilience import FaultPlan, faults
    from swiftly_tpu.serve import (
        AdmissionQueue,
        CoalescingScheduler,
        FleetAutoscaler,
        ServeFleet,
        SubgridService,
    )
    from swiftly_tpu.utils import enable_compilation_cache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    orecorder = _maybe_enable_recorder()
    out_path = os.environ.get("BENCH_FLEET_OUT", "BENCH_fleet.json")
    if smoke_mode:
        os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
        metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    name = os.environ.get("BENCH_FLEET_CONFIG", "1k[1]-n512-256")
    n_replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", "3"))
    per_phase = int(os.environ.get("BENCH_FLEET_PHASE_REQUESTS", "72"))
    seed = int(os.environ.get("BENCH_FLEET_SEED", "1234"))
    zipf_s = float(os.environ.get("BENCH_FLEET_ZIPF_S", "1.1"))
    max_depth = int(os.environ.get("BENCH_FLEET_DEPTH", "256"))
    max_batch = int(os.environ.get("BENCH_FLEET_MAX_BATCH", "16"))

    params = dict(SWIFT_CONFIGS[name])
    params.setdefault("fov", 1.0)
    dtype = jax.numpy.float32
    platform = jax.devices()[0].platform
    config = SwiftlyConfig(backend="planar", dtype=dtype, **params)
    facet_configs = make_full_facet_cover(config)
    subgrid_configs = make_full_subgrid_cover(config)
    sources = _bench_sources(config.image_size)
    # ONE facet data set, N independent prepared forwards (replica =
    # simulated chip: own facet upload, own column LRU, own queue); the
    # in-process + persistent XLA caches make the repeat compiles cheap
    facet_tasks = [
        (fc, make_facet(config.image_size, fc, sources))
        for fc in facet_configs
    ]

    def replica_factory(rid, feed):
        fwd = SwiftlyForward(
            config, facet_tasks, lru_forward=2, queue_size=64
        )
        return SubgridService(
            fwd,
            queue=AdmissionQueue(max_depth=max_depth),
            scheduler=CoalescingScheduler(max_batch=max_batch),
            max_retries=2,
            cache_feed=feed,
        )

    # admission costing from the unified plan compiler: the fleet's
    # per-request / per-column byte model is the compiled plan's serve
    # block (no cap here — the drill's phases must admit everything;
    # the pricing lands in the artifact's admission stats)
    from swiftly_tpu.plan import PlanInputs, compile_plan, price_cache_tier

    plan_inputs = PlanInputs.from_cover(
        config, facet_configs, subgrid_configs, max_batch=max_batch,
    )
    fleet_plan = compile_plan(plan_inputs, mode="streamed")

    # ONE recorded stream for the whole fleet: record the subgrid
    # stream once through the incremental engine, then front it with
    # the shared cache fabric — each replica gets a hot-row L1 VIEW
    # over the single resident spill-backed L2, sized by the plan
    # compiler's priced break-even
    from swiftly_tpu.delta import IncrementalForward
    from swiftly_tpu.utils.spill import SpillCache, spill_budget_bytes

    engine = IncrementalForward(
        config, facet_tasks,
        SpillCache(budget_bytes=spill_budget_bytes()),
    )
    engine.record(subgrid_configs)
    l1_env = int(os.environ.get("BENCH_FLEET_L1_ROWS", "0"))
    cache_plan = price_cache_tier(
        plan_inputs, replicas=n_replicas,
        l1_rows=l1_env or None, zipf_s=zipf_s,
    )
    fabric = engine.fabric(l1_rows=cache_plan.l1_rows)

    fleet = ServeFleet(
        replica_factory, n_replicas,
        lease_interval_s=0.02, miss_suspect=3, miss_revoke=6,
        breaker_threshold=3, breaker_reopen_s=0.3,
        breaker_max_reopen_s=4.0, half_open_probes=2,
        hedge_min_s=0.05,
        # brownout is drilled explicitly in the overload phase; an
        # impossible share keeps it out of the kill/recovery windows
        brownout_share=2.0, brownout_min_depth=8,
        brownout_escalate_s=0.1,
        failover_backoff_s=0.01, seed=seed,
        request_bytes=fleet_plan.serve.request_bytes,
        column_bytes=fleet_plan.serve.column_bytes,
        fabric=fabric, drain_timeout_s=20.0,
    )
    # declarative SLOs on the control tower: the forced brownout ladder
    # in the overload phase must OPEN the burn-rate alert (fast AND
    # slow windows burning) and the step-down must CLOSE it — the alert
    # lifecycle is a drill outcome, asserted under --smoke. The shed
    # SLO stays quiet (the drill sheds a dozen of hundreds): one alert
    # that fires and one that doesn't is the schema's smoke test.
    from swiftly_tpu.obs import SLO

    fleet.tower.set_slos([
        # windows sized to the drill: the ladder holds rung >= 1 for
        # brownout_escalate_s (0.1s) before rung 2, so a 0.2s slow
        # window is >= half-breached by the time rung 2 lands
        SLO("brownout_engaged", "fleet.brownout_level", 0.5,
            direction="above", fast_s=0.05, slow_s=0.2, burn=0.4),
        SLO("shed_storm", "fleet.shed_rate", 0.5,
            direction="above", fast_s=0.5, slow_s=2.0, burn=0.5),
    ])

    # one shared workload per phase (same seed -> identical request
    # multiset), so the before/during/after p99 windows are comparable
    workload, hot_off0 = _zipf_workload(
        subgrid_configs, per_phase, seed, zipf_s
    )
    # move the bucket-shape compiles AND the per-replica lazy facet
    # preparation off every phase's latency path (each replica's
    # forward prepares its facet stack on first dispatch — unwarmed,
    # that lands in the *before* window and poisons the p99 baseline)
    hot_col = [sg for sg in subgrid_configs if sg.off0 == hot_off0]
    for replica in fleet.replicas.values():
        warm_fwd = replica.service.fwd
        b = 1
        while b <= max_batch:
            warm_fwd.get_subgrid_tasks([hot_col[0]] * b)
            b *= 2
        warm_fwd.get_subgrid_task(hot_col[0])

    # single-service compute baseline: one replica-shaped service with
    # NO cache feed, timed over a slice of the same zipf workload — the
    # honest denominator for the fabric's QPS-equivalence claim
    solo = replica_factory(-1, None)
    solo.serve(workload[:2], priority=1)  # warm its dispatch path
    solo_n = min(24, len(workload))
    t_solo = time.time()
    solo_reqs = solo.serve(workload[:solo_n], priority=1)
    solo_wall = time.time() - t_solo
    solo_ok = sum(
        1 for r in solo_reqs if r.result is not None and r.result.ok
    )
    single_service_qps = (solo_ok / solo_wall) if solo_wall else 0.0

    from swiftly_tpu.obs import trace as otrace

    fleet_span = otrace.span("bench.fleet", cat="bench", config=name)
    t0 = time.time()
    fleet_span.__enter__()
    fleet.start()
    tracked = []

    def run_phase(label, drain_timeout=180.0):
        phase = []
        for sg in workload:
            fr = fleet.submit(sg, priority=1)
            phase.append((sg, fr))
            tracked.append((sg, fr))
        if not fleet.drain(timeout=drain_timeout):
            log.error("phase %s did not drain", label)
        oks = [
            fr.result.latency_s
            for _sg, fr in phase
            if fr.result is not None and fr.result.ok
        ]
        return phase, oks

    # -- phase 1: the clean baseline window -------------------------------
    _phase_a, lat_before = run_phase("before")
    p99_before = _lat_quantile_ms(lat_before, 0.99)

    # -- phase 2: kill mid-workload ---------------------------------------
    # burst FIRST so every replica holds queued work, THEN arm the
    # deterministic kill: the 4th fleet.replica.kill site call after
    # install (every replica pump iterates the shared site) raises
    # WorkerKilled in whichever pump reaches it — the drill is
    # victim-agnostic by design (any of the N must fail over cleanly,
    # with its queued + in-flight burst share stranded mid-serve)
    kill_plan = FaultPlan(
        [{"site": "fleet.replica.kill", "kind": "kill", "at": 3}],
        seed=seed,
    )
    phase_b = []
    for sg in workload:
        fr = fleet.submit(sg, priority=1)
        phase_b.append((sg, fr))
        tracked.append((sg, fr))
    with faults.active(kill_plan):
        if not fleet.drain(timeout=300.0):
            log.error("kill phase did not drain")
    lat_during = [
        fr.result.latency_s
        for _sg, fr in phase_b
        if fr.result is not None and fr.result.ok
    ]
    p99_during = _lat_quantile_ms(lat_during, 0.99)
    victims = [
        rid for rid, r in fleet.replicas.items() if r.dead
    ]
    victim = victims[0] if victims else None
    # the fabric makes the kill window cache-fast: the burst drains in
    # tens of milliseconds, well inside the monitor's miss_revoke
    # horizon — wait for DETECTION (missed heartbeats -> revocation,
    # which trips the breaker) before restoring, or the drill restores
    # a victim the health plane never got to condemn
    if victim is not None:
        deadline = time.time() + 10.0
        while (
            not fleet.replica(victim).lease.revoked
            and time.time() < deadline
        ):
            time.sleep(0.005)
    # the black box earns its keep HERE: snapshot the recorder window
    # while the kill's event tail (fault injection, replica death,
    # lease revocation, breaker trip, failovers) is the recent past
    kill_post_mortem = (
        orecorder.post_mortem(
            "WorkerKilled", reason=f"replica {victim} killed mid-burst"
        )
        if orecorder is not None else None
    )

    # -- phase 3: restore + recovery window -------------------------------
    if victim is not None:
        fleet.restore_replica(victim)
    _phase_c, lat_after = run_phase("after")
    p99_after = _lat_quantile_ms(lat_after, 0.99)
    # drive the victim's breaker through half-open probes to closed:
    # keep offering its preferred columns until the cycle completes
    if victim is not None:
        victim_cols = [
            sg for sg in subgrid_configs
            if fleet.preferred_replica(sg.off0) == victim
        ] or hot_col
        deadline = time.time() + 10.0
        i = 0
        while (
            fleet.replica(victim).breaker.state != "closed"
            and time.time() < deadline
        ):
            sg = victim_cols[i % len(victim_cols)]
            i += 1
            fr = fleet.submit(sg, priority=1)
            tracked.append((sg, fr))
            fleet.drain(timeout=30.0)
            time.sleep(0.02)

    # -- phase 4: overload — route faults + the brownout ladder -----------
    route_plan = FaultPlan(
        [{"site": "fleet.route", "kind": "ioerror", "every": 3,
          "times": 4}],
        seed=seed,
    )
    with faults.active(route_plan):
        for sg in workload[:24]:
            fr = fleet.submit(sg, priority=1)
            tracked.append((sg, fr))
        fleet.drain(timeout=60.0)
    # brownout: force the journey queue-share signal (deterministic
    # drill of the LADDER; the organic signal path is unit-tested) and
    # shed a priority-0 burst at the door
    fleet.queue_share = lambda window=256: 0.95  # instance override
    fleet.brownout_min_depth = 0
    fleet.brownout_share = 0.5
    deadline = time.time() + 5.0
    while fleet.brownout_level < 1 and time.time() < deadline:
        time.sleep(0.005)
    brownout_shed = [
        fleet.submit(sg, priority=0) for sg in workload[:12]
    ]
    while fleet.brownout_level < 2 and time.time() < deadline:
        time.sleep(0.005)
    level_max = fleet.brownout_level
    per_request_dispatch = all(
        r.service.scheduler.max_batch == 1
        for r in fleet.replicas.values()
    ) if level_max >= 2 else False
    # restore the organic signal AND the impossible threshold so the
    # step-down path is deterministic (hysteresis walks 2 -> 1 -> 0)
    del fleet.queue_share
    fleet.brownout_share = 2.0
    fleet.brownout_min_depth = 8
    deadline = time.time() + 5.0
    while fleet.brownout_level > 0 and time.time() < deadline:
        time.sleep(0.005)
    batch_restored = all(
        r.service.scheduler.max_batch == max_batch
        for r in fleet.replicas.values()
    )

    # -- phase 5: sustained zipf + autoscaler (scale out, drain back) -----
    # the elastic drill: a sustained burst under a forced-high journey
    # signal must scale the fleet out (each newcomer is a fabric feed
    # VIEW — an L1, not a stream copy), then a forced-low signal must
    # drain the extra replicas back through the zero-loss path. The
    # signals are forced for determinism, exactly like the brownout
    # rungs above; the organic paths are pinned by tests/test_fleet.py.
    fleet.drain(timeout=60.0)
    scaler = FleetAutoscaler(
        fleet, min_replicas=n_replicas, max_replicas=n_replicas + 2,
        up_share=0.55, down_share=0.15, min_queue_depth=2,
        hold_ticks=2, cooldown_s=0.2,
    )
    fleet.autoscaler = scaler
    fleet.queue_share = lambda window=256: 0.9  # instance override
    as_phase = []
    t_as = time.time()
    for _rep in range(3):
        for sg in workload:
            fr = fleet.submit(sg, priority=1)
            as_phase.append((sg, fr))
            tracked.append((sg, fr))
    deadline = time.time() + 15.0
    while (
        fleet._counts["scale_outs"] < 1 and time.time() < deadline
    ):
        time.sleep(0.005)
    if not fleet.drain(timeout=120.0):
        log.error("autoscale phase did not drain")
    as_wall = time.time() - t_as
    # drain back: forced-low signal, empty queue -> the autoscaler
    # retires the newcomers one cooldown at a time
    fleet.queue_share = lambda window=256: 0.0
    deadline = time.time() + 20.0
    while (
        len(fleet.replicas) > n_replicas and time.time() < deadline
    ):
        time.sleep(0.01)
    del fleet.queue_share
    as_ok = sum(
        1 for _sg, fr in as_phase
        if fr.result is not None and fr.result.ok
    )
    autoscale_phase_rps = (as_ok / as_wall) if as_wall else 0.0
    # post-churn clean window: the same request multiset as the
    # *before* phase — elastic churn must leave p99 where it found it
    _phase_e, lat_elastic = run_phase("elastic_after")
    p99_elastic = _lat_quantile_ms(lat_elastic, 0.99)

    fleet.drain(timeout=60.0)
    wall = time.time() - t0
    stats = fleet.stats(wall_s=wall)
    # tower blocks BEFORE stop(): the replica sources are still
    # registered, so the fleet totals cover every serving source
    fleet_telemetry = fleet.tower.fleet_telemetry()
    alerts_block = fleet.tower.alerts_block()
    fleet.stop()
    fleet_span.__exit__(None, None, None)

    # -- bit-identity audit: every served result vs a FRESH deterministic
    # reference for ITS serving path — failover/hedging/dedup must never
    # change answers. Cache-path rows come from the recorded stream
    # (the streamed column-group program), compute-path results from the
    # stacked per-request program; the two differ in reduction order at
    # float noise, so each path is audited BIT-identical against its own
    # freshly re-run program, and a cross-program allclose guard catches
    # wrong-row serving (an index/L1 mix-up is an O(1) relative error,
    # not an O(1e-10) reduction-order one)
    fwd_ref = SwiftlyForward(config, facet_tasks, lru_forward=2,
                             queue_size=64)
    ref_engine = IncrementalForward(
        config, facet_tasks,
        SpillCache(budget_bytes=spill_budget_bytes()),
    )
    ref_engine.record(subgrid_configs)
    stream_ref = ref_engine.feed()
    ref_cache = {}
    checked = mismatches = cross_mismatches = 0
    for sg, fr in tracked:
        res = fr.result
        if res is None or not res.ok:
            continue
        key = (sg.off0, sg.off1)
        if key not in ref_cache:
            srow = stream_ref.lookup(sg)
            ref_cache[key] = (
                np.asarray(fwd_ref.get_subgrid_task(sg)),
                None if srow is None else np.asarray(srow),
            )
        compute_ref, cache_ref = ref_cache[key]
        expected = (
            cache_ref
            if res.path == "cache" and cache_ref is not None
            else compute_ref
        )
        got = np.asarray(res.data)
        checked += 1
        if not np.array_equal(got, expected):
            mismatches += 1
        if not np.allclose(got, compute_ref, rtol=1e-4, atol=1e-8):
            cross_mismatches += 1

    n_ok = sum(
        1 for _sg, fr in tracked
        if fr.result is not None and fr.result.ok
    )
    zero_lost = n_ok == len(tracked)
    victim_cycle = (
        [t["to"] for t in stats["breakers"][str(victim)]["transitions"]]
        if victim is not None else []
    )
    n_cols = len({sg.off0 for sg in subgrid_configs})
    shed_hints = [
        r.result.retry_after_s
        for r in brownout_shed
        if r.result is not None and r.result.retry_after_s is not None
    ]
    cache_stats = fabric.stats()
    qps_ratio = (
        autoscale_phase_rps / single_service_qps
        if single_service_qps else 0.0
    )
    record = {
        "metric": (
            f"{name} self-healing serve fleet "
            f"({len(tracked)} zipf requests over {n_cols} columns, "
            f"{n_replicas} replicas + cache fabric, kill+restore+"
            f"autoscale drill, planar f32, {platform})"
        ),
        "value": round(wall, 4),
        "unit": "s",
        "throughput_rps": (
            round(stats["served"] / wall, 2) if wall else 0.0
        ),
        "p50_ms": stats["p50_ms"],
        "p99_ms": stats["p99_ms"],
        "n_requests": stats["requests"],
        "n_served": stats["served"],
        "n_shed": stats["shed"],
        "bit_identical": {
            "checked": checked,
            "mismatches": mismatches,
            "cross_program_mismatches": cross_mismatches,
        },
        "fleet": {
            "n_replicas": n_replicas,
            "victim": victim,
            "replica_deaths": len(victims),
            "restores": stats["restores"],
            "failovers": stats["failovers"],
            "reroutes": stats["reroutes"],
            "hedges": stats["hedges"],
            "hedge_wins": stats["hedge_wins"],
            "route_faults": stats["route_faults"],
            "zero_lost": zero_lost,
            "p99_before_ms": p99_before,
            "p99_during_ms": p99_during,
            "p99_after_ms": p99_after,
            "p99_recovery_ratio": (
                round(p99_after / p99_before, 3) if p99_before else None
            ),
            "breaker_cycle": victim_cycle,
            "admission": stats["admission"],
            "breakers": stats["breakers"],
            "health_transitions": stats["health"]["transitions"],
            "zombie_beats": stats["health"]["zombie_beats"],
            "brownout": {
                **stats["brownout"],
                "level_max": level_max,
                "per_request_dispatch": per_request_dispatch,
                "batch_restored": batch_restored,
                "retry_after_hints": [
                    round(h, 4) for h in shed_hints[:8]
                ],
            },
            "per_replica": stats["per_replica"],
            "stream_copies": stats["stream_copies"],
            "scale_outs": stats["scale_outs"],
            "drains": stats["drains"],
            "retired": stats["retired"],
            "autoscale": stats.get("autoscale"),
            "p99_elastic_ms": p99_elastic,
        },
        "cache": {
            **cache_stats,
            "plan": {
                "l1_rows": cache_plan.l1_rows,
                "break_even_l1_rows": cache_plan.break_even_l1_rows,
                "expected_wall_s": round(cache_plan.expected_wall_s, 9),
                "coeffs_source": cache_plan.coeffs_source,
            },
            "single_service_qps": round(single_service_qps, 2),
            "autoscale_phase_rps": round(autoscale_phase_rps, 2),
            "qps_equivalent_ratio": round(qps_ratio, 2),
        },
        "zipf": {"s": zipf_s, "n_columns": n_cols, "seed": seed},
        "fleet_telemetry": fleet_telemetry,
        "alerts": alerts_block,
        "n_subgrids_cover": len(subgrid_configs),
        "manifest": run_manifest(
            params={"config": name, "mode": "fleet", **params},
        ),
    }
    if orecorder is not None:
        pm_path = os.path.splitext(out_path)[0] + "_postmortem.jsonl"
        orecorder.dump(
            pm_path, "WorkerKilled",
            reason=f"replica {victim} killed mid-burst",
        )
        record["post_mortem"] = dict(
            kill_post_mortem
            or orecorder.post_mortem("drill_complete")
        )
        record["post_mortem"]["dump_path"] = pm_path
    if metrics.enabled():
        record["telemetry"] = metrics.export()
    if trace_path:
        from swiftly_tpu.obs import summarize_trace

        summary = summarize_trace(
            otrace.export(), root_id=getattr(fleet_span, "id", None)
        )
        summary["leg_wall_s"] = round(wall, 6)
        record["trace"] = summary
        otrace.save(trace_path)
        otrace.disable()

    from swiftly_tpu.obs import (
        validate_alerts_artifact,
        validate_fleet_telemetry_artifact,
    )

    problems = validate_fleet_artifact(record)
    problems.extend(validate_fleet_telemetry_artifact(record))
    problems.extend(validate_alerts_artifact(record))
    if smoke_mode:
        # drill outcomes: the schema passing is not proof the fleet
        # actually healed
        if len(victims) != 1:
            problems.append(
                f"expected exactly 1 replica death, got {victims}"
            )
        if not zero_lost:
            problems.append(
                f"lost requests: {len(tracked) - n_ok} of "
                f"{len(tracked)} not served"
            )
        if mismatches or checked != n_ok:
            problems.append(
                f"bit-identity audit failed: {mismatches} mismatches, "
                f"{checked}/{n_ok} checked"
            )
        if cross_mismatches:
            problems.append(
                f"cross-program audit failed: {cross_mismatches} "
                "cache-path results diverge from per-request compute "
                "beyond reduction-order noise (wrong-row serving)"
            )
        if stats["failovers"] < 1:
            problems.append("the kill produced no failover")
        for state in ("open", "half_open", "closed"):
            if state not in victim_cycle:
                problems.append(
                    f"victim breaker never reached {state!r} "
                    f"(cycle: {victim_cycle})"
                )
        if p99_before and p99_after > 1.5 * p99_before:
            problems.append(
                f"p99 did not recover: {p99_after}ms after vs "
                f"{p99_before}ms before (> 1.5x)"
            )
        if not any(
            h["owner"] == victim and h["to"] == "revoked"
            for h in stats["health"]["transitions"]
        ):
            problems.append("victim lease was never revoked")
        if stats["route_faults"] < 1:
            problems.append(
                "injected fleet.route faults never fired/retried"
            )
        if stats["brownout"]["sheds"] < 1 or not shed_hints:
            problems.append(
                "brownout rung 1 shed nothing (or sheds carried no "
                "retry_after_s hint)"
            )
        if level_max < 2 or not per_request_dispatch:
            problems.append(
                f"brownout never reached per-request dispatch "
                f"(level_max={level_max})"
            )
        if not batch_restored:
            problems.append(
                "brownout recovery did not restore max_batch"
            )
        # cache fabric + autoscale drill outcomes
        if cache_stats["resident_stream_copies"] != 1:
            problems.append(
                f"fabric reports {cache_stats['resident_stream_copies']}"
                " resident stream copies, not 1"
            )
        if stats["stream_copies"] != 1:
            problems.append(
                f"fleet reports stream_copies={stats['stream_copies']}"
                " with a fabric attached"
            )
        if len(fleet.replicas) < 3:
            problems.append(
                f"fleet ended with {len(fleet.replicas)} replicas "
                "(need >= 3 sharing the one resident stream)"
            )
        if cache_stats["hit_ratio"] < 0.5:
            problems.append(
                f"fabric hit_ratio {cache_stats['hit_ratio']} < 0.5: "
                "the drill should serve mostly from the shared cache"
            )
        if stats["scale_outs"] < 1:
            problems.append(
                "autoscaler never scaled out under the sustained burst"
            )
        if stats["drains"] < 1:
            problems.append(
                "autoscaler never drained the scaled-out replica back"
            )
        if len(fleet.replicas) != n_replicas:
            problems.append(
                f"fleet did not drain back to {n_replicas} replicas "
                f"(has {len(fleet.replicas)})"
            )
        if qps_ratio < 10.0:
            problems.append(
                f"autoscale-phase throughput is only {qps_ratio:.1f}x "
                "the single-service compute QPS (need >= 10x)"
            )
        if p99_before and p99_elastic > 1.5 * p99_before:
            problems.append(
                f"p99 not held through elastic churn: {p99_elastic}ms "
                f"vs {p99_before}ms before (> 1.5x)"
            )
        # control-tower drill outcomes: the forced ladder must have
        # burned the brownout SLO open and the step-down closed it,
        # and the kill's post-mortem must tell the failure story
        if alerts_block["opened"] < 1:
            problems.append(
                "SLO burn-rate alert never opened under the forced "
                f"brownout ladder: {alerts_block}"
            )
        if alerts_block["open"]:
            problems.append(
                f"alerts still open at drill end: {alerts_block['open']}"
            )
        if not any(
            e["slo"] == "brownout_engaged" for e in alerts_block["events"]
        ):
            problems.append(
                "the brownout_engaged SLO never appears in the alert "
                f"event log: {alerts_block['events']}"
            )
        if orecorder is not None:
            pm_kinds = record["post_mortem"]["by_kind"]
            pm_names = [
                e["name"] for e in record["post_mortem"]["events"]
            ]
            if not any(
                n.startswith("fault.injected.fleet.replica.kill")
                for n in pm_names
            ):
                problems.append(
                    "kill post-mortem tail missing the injected "
                    f"fleet.replica.kill fault: {pm_names}"
                )
            if "fleet.replica_death" not in pm_names:
                problems.append(
                    "kill post-mortem tail missing the replica death "
                    f"event: {pm_names}"
                )
            for kind in ("fault", "fleet", "lease"):
                if not pm_kinds.get(kind):
                    problems.append(
                        f"kill post-mortem recorded no {kind!r} "
                        f"events: {pm_kinds}"
                    )
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2)
    if smoke_mode:
        metrics.disable()
        print(
            json.dumps(
                {
                    "fleet_smoke": "ok" if not problems else "failed",
                    "config": name,
                    "artifact": out_path,
                    "n_served": stats["served"],
                    "victim": victim,
                    "failovers": stats["failovers"],
                    "p99_before_ms": p99_before,
                    "p99_after_ms": p99_after,
                    "breaker_cycle": victim_cycle,
                    "stream_copies": stats["stream_copies"],
                    "hit_ratio": cache_stats["hit_ratio"],
                    "scale_outs": stats["scale_outs"],
                    "drains": stats["drains"],
                    "qps_equivalent_ratio": round(qps_ratio, 2),
                    "alerts_opened": alerts_block["opened"],
                    "alerts_open": len(alerts_block["open"]),
                    "recorder_events": (
                        record["post_mortem"]["n_events"]
                        if orecorder is not None else 0
                    ),
                    "problems": problems,
                }
            ),
            flush=True,
        )
        return 0 if not problems else 1
    print(json.dumps(record), flush=True)
    return 0 if not problems else 1


def procfleet_bench(smoke_mode=False):
    """`bench.py --procfleet [--smoke]`: the process-fleet SIGKILL drill.

    Runs ``BENCH_PROCFLEET_WORKERS`` (default 3, 2 under ``--smoke``)
    replicas as REAL OS processes behind `serve.ProcessFleet` — each a
    spawned worker hosting a `SubgridService` over its own prepared
    forward, speaking `serve.ipc`'s versioned length-prefixed frames,
    serving the parent's recorded stream through the shared spill
    directory (`SpillCache.export_manifest` → `SharedSpillReader` under
    the unchanged `CachedColumnFeed` gates) — and lands two REAL
    ``SIGKILL -9``s:

    1. **before** — a clean zipf window; its p99 is the baseline.
    2. **kill** — the same workload as a burst; mid-burst the hot
       column's preferred worker is SIGKILLed. Its silent socket misses
       lease beats → suspect → revoked; the breaker trips open; queued
       + in-flight requests fail over to the survivors. ZERO requests
       may be lost, and ``failover_ms`` (revocation → last failed-over
       request served) is the artifact's headline value.
    3. **restart** — the supervisor restarts the victim with capped
       backoff; its breaker is NOT reset — victim-preferred traffic
       drives the half-open probe path until the cycle reads
       open → half_open → closed; a clean window pins p99 recovery.
    4. **mid-L2-read kill** — a ``CONTROL`` frame arms a dwell inside
       the second victim's next `SharedSpillReader.get_row` (the worker
       announces the held mmap read via a flag file), and the SIGKILL
       lands INSIDE that window: the failed-over row re-served by a
       survivor must be bit-identical — entry files are immutable and
       renamed into place, so a worker killed mid-read can never leave
       a torn row for a survivor to observe.

    Before any of that, fleet start exercises startup hygiene against
    fabricated wreckage of a "crashed" previous run: a stale socket
    file is swept and a live decoy worker process (cmdline-marker
    matched, never pid alone) is reaped.

    Every served result is audited BIT-IDENTICAL against its serving
    path's reference — cache rows vs the parent's own recorded stream
    (the exact bytes the workers mmap), compute results vs per-request
    `get_subgrid_task` on a fresh forward — plus a cross-program
    allclose guard against wrong-row serving.

    The distributed observability plane runs throughout: every worker
    ships cumulative TELEMETRY frames on the heartbeat cadence into a
    `ControlTower` (``fleet_telemetry`` totals sum exactly across
    processes, surviving the deaths through the retired-generation
    ledger), traces its half of every request so
    `ProcessFleet.merged_trace` emits ONE timeline across all pids
    (clocks aligned via the HELLO offset estimates, ±rtt/2), and
    persists its flight-recorder ring as a crash-safe black box — the
    artifact's post-mortem shows each SIGKILL victim's OWN last events
    (the L2 dwell it held, the request in flight), exhumed by the
    supervisor. The artifact's ``procfleet`` block is validated by
    `obs.validate_procfleet_artifact`; with ``--smoke`` the drill
    outcomes are asserted and the leg exits nonzero on any problem
    (wired into tier-1 via tests/test_bench_smoke.py).
    """
    import signal
    import subprocess
    import tempfile

    import jax

    from swiftly_tpu import (
        SwiftlyConfig,
        SwiftlyForward,
        make_facet,
        make_full_facet_cover,
        make_full_subgrid_cover,
    )
    from swiftly_tpu.delta import IncrementalForward
    from swiftly_tpu.models import SWIFT_CONFIGS
    from swiftly_tpu.obs import (
        ControlTower,
        metrics,
        run_manifest,
        validate_procfleet_artifact,
    )
    from swiftly_tpu.obs import trace as otrace
    from swiftly_tpu.serve import ProcessFleet, make_worker_spec
    from swiftly_tpu.serve.fleet import _rendezvous_score
    from swiftly_tpu.utils import enable_compilation_cache
    from swiftly_tpu.utils.spill import SpillCache, spill_budget_bytes

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    if not otrace.enabled():
        # the merged cross-process timeline needs the router's tracer
        # live even when --trace didn't ask for an export on disk
        otrace.enable()
    orecorder = _maybe_enable_recorder()
    out_path = os.environ.get("BENCH_PROCFLEET_OUT", "BENCH_procfleet.json")
    if smoke_mode:
        os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
        metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    name = os.environ.get("BENCH_PROCFLEET_CONFIG", "1k[1]-n512-256")
    n_workers = int(os.environ.get(
        "BENCH_PROCFLEET_WORKERS", "2" if smoke_mode else "3"))
    per_phase = int(os.environ.get(
        "BENCH_PROCFLEET_PHASE_REQUESTS", "16" if smoke_mode else "48"))
    seed = int(os.environ.get("BENCH_PROCFLEET_SEED", "1234"))
    zipf_s = float(os.environ.get("BENCH_PROCFLEET_ZIPF_S", "1.1"))
    max_depth = int(os.environ.get("BENCH_PROCFLEET_DEPTH", "256"))
    max_batch = int(os.environ.get("BENCH_PROCFLEET_MAX_BATCH", "16"))
    dwell_s = float(os.environ.get("BENCH_PROCFLEET_DWELL_S", "1.5"))

    params = dict(SWIFT_CONFIGS[name])
    params.setdefault("fov", 1.0)
    platform = jax.devices()[0].platform
    config = SwiftlyConfig(backend="planar", dtype=jax.numpy.float32,
                           **params)
    facet_configs = make_full_facet_cover(config)
    subgrid_configs = make_full_subgrid_cover(config)
    sources = _bench_sources(config.image_size)
    facet_tasks = [
        (fc, make_facet(config.image_size, fc, sources))
        for fc in facet_configs
    ]

    # ONE recorded stream in the parent; its exported manifest is the
    # cross-process L2 every worker serves through the spill directory
    # (disk-backed: export_manifest forces every entry to its atomic
    # on-disk form for the workers to mmap)
    spill = SpillCache(budget_bytes=spill_budget_bytes(),
                       spill_dir=tempfile.gettempdir())
    engine = IncrementalForward(config, facet_tasks, spill)
    engine.record(subgrid_configs)

    spec = make_worker_spec(
        params, sources, max_depth=max_depth, max_batch=max_batch,
    )

    # fabricate the wreckage of a "crashed" previous fleet so start()'s
    # hygiene sweep has something real to clean: a run dir owned by a
    # dead pid holding a stale socket file and a pidfile pointing at a
    # LIVE decoy process whose cmdline carries the worker marker — the
    # sweep must remove the socket and SIGKILL the decoy (marker match,
    # never pid alone)
    run_root = os.path.join(
        tempfile.gettempdir(), f"swiftly_procfleet_bench_{os.getpid()}")
    stale_dir = os.path.join(run_root, "run-stale-crashed")
    os.makedirs(stale_dir, exist_ok=True)
    open(os.path.join(stale_dir, "worker-0.g1.sock"), "w").close()
    with open(os.path.join(stale_dir, "fleet.pid"), "w") as fh:
        fh.write("999999")  # long-dead owner pid
    decoy = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(600)",
         "swiftly_tpu.serve.procfleet", "--worker"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    # wait for the exec: until then /proc/<pid>/cmdline still shows THIS
    # process's argv and the sweep would (rightly) refuse to signal it
    from swiftly_tpu.serve.procfleet import _cmdline_matches

    decoy_deadline = time.monotonic() + 10.0
    while (not _cmdline_matches(decoy.pid)
           and time.monotonic() < decoy_deadline):
        time.sleep(0.01)
    with open(os.path.join(stale_dir, "worker-0.pid"), "w") as fh:
        fh.write(str(decoy.pid))

    fleet = ProcessFleet(
        spec, n_workers, stream_spill=spill, run_root=run_root,
        lease_interval_s=0.02, miss_suspect=3, miss_revoke=6,
        breaker_threshold=3, breaker_reopen_s=0.3,
        breaker_max_reopen_s=4.0, half_open_probes=2,
        restart_backoff_s=0.2, restart_backoff_max_s=2.0,
        boot_deadline_s=240.0, worker_trace=True,
    )
    # the distributed observability plane: per-worker TELEMETRY
    # sources + fleet signals/SLOs under one control tower, ticked by
    # the fleet's own supervisor
    tower = ControlTower()
    fleet.register_tower(tower)

    workload, hot_off0 = _zipf_workload(
        subgrid_configs, per_phase, seed, zipf_s
    )

    fleet_span = otrace.span("bench.procfleet", cat="bench", config=name)
    t0 = time.time()
    fleet_span.__enter__()
    tracked = []
    try:
        fleet.start()
        # the decoy must be dead (it is our child: reap the zombie)
        try:
            decoy.wait(timeout=10.0)
            decoy_reaped = True
        except Exception:
            decoy_reaped = False
        orphans = {
            "orphans_reaped": fleet.counts["orphans_reaped"],
            "stale_sockets_swept": fleet.counts["stale_sockets_swept"],
            "decoy_reaped": decoy_reaped,
        }

        def run_phase(label, drain_timeout=120.0):
            phase = []
            for sg in workload:
                fr = fleet.submit(sg, priority=1)
                phase.append((sg, fr))
                tracked.append((sg, fr))
            if not fleet.drain(timeout_s=drain_timeout):
                log.error("phase %s did not drain", label)
            oks = [
                fr.result.latency_s
                for _sg, fr in phase
                if fr.result is not None and fr.result.ok
            ]
            return phase, oks

        # -- phase 1: clean baseline window -------------------------------
        _phase_a, lat_before = run_phase("before")
        p99_before = _lat_quantile_ms(lat_before, 0.99)

        # -- phase 2: SIGKILL -9 mid-burst --------------------------------
        # the victim is the hot column's preferred worker, so the burst's
        # head is queued/in-flight ON the victim when the kill lands
        victim = max(
            range(n_workers), key=lambda r: _rendezvous_score(hot_off0, r))
        phase_b = []
        burst_head = max(2, len(workload) // 3)
        for sg in workload[:burst_head]:
            fr = fleet.submit(sg, priority=1)
            phase_b.append((sg, fr))
            tracked.append((sg, fr))
        killed_pid = fleet.kill_worker(victim, signal.SIGKILL)
        for sg in workload[burst_head:]:
            fr = fleet.submit(sg, priority=1)
            phase_b.append((sg, fr))
            tracked.append((sg, fr))
        if not fleet.drain(timeout_s=120.0):
            log.error("kill phase did not drain")
        lat_during = [
            fr.result.latency_s
            for _sg, fr in phase_b
            if fr.result is not None and fr.result.ok
        ]
        p99_during = _lat_quantile_ms(lat_during, 0.99)
        # wait for DETECTION: the silent socket must miss enough beats
        # for the lease to revoke (trips the breaker, stamps the death)
        deadline = time.time() + 15.0
        while time.time() < deadline:
            w = fleet.worker(victim)
            if w.lease is not None and w.lease.revoked or w.dead:
                break
            time.sleep(0.005)
        # wait for EXHUMATION: _on_revoked digs up the victim's black
        # box and folds its tail into the parent's recorder — the dump
        # below must show the victim's own story, not just the silence
        deadline = time.time() + 10.0
        while (time.time() < deadline
               and fleet.counts["blackbox_exhumed"] < 1):
            time.sleep(0.005)
        kill_post_mortem = (
            orecorder.post_mortem(
                "WorkerSIGKILLed",
                reason=f"worker {victim} pid {killed_pid} killed -9",
            )
            if orecorder is not None else None
        )

        # -- phase 3: supervised restart + half-open → closed -------------
        deadline = time.time() + 60.0
        while time.time() < deadline:
            w = fleet.worker(victim)
            if w.ready and not w.dead and w.generation >= 2:
                break
            time.sleep(0.01)
        victim_cols = [
            sg for sg in subgrid_configs
            if max(range(n_workers),
                   key=lambda r: _rendezvous_score(sg.off0, r)) == victim
        ] or list(subgrid_configs)
        deadline = time.time() + 20.0
        i = 0
        while (
            fleet.worker(victim).breaker.state != "closed"
            and time.time() < deadline
        ):
            sg = victim_cols[i % len(victim_cols)]
            i += 1
            fr = fleet.submit(sg, priority=1)
            tracked.append((sg, fr))
            fleet.drain(timeout_s=30.0)
            time.sleep(0.02)
        _phase_c, lat_after = run_phase("after")
        p99_after = _lat_quantile_ms(lat_after, 0.99)

        # -- phase 4: SIGKILL while the victim holds an L2 read -----------
        fleet.drain(timeout_s=60.0)
        fleet.wait_ready(60.0)
        victim2 = next(
            r for r in range(n_workers) if r != victim)
        col2 = next(
            sg for sg in subgrid_configs
            if max(range(n_workers),
                   key=lambda r: _rendezvous_score(sg.off0, r)) == victim2)
        flag = fleet.dwell_flag_path(victim2)
        try:
            os.unlink(flag)
        except OSError:
            pass
        fleet.set_control(victim2, dwell_l2_s=dwell_s)
        time.sleep(0.05)  # let the worker ack the CONTROL frame
        fr2 = fleet.submit(col2, priority=1)
        tracked.append((col2, fr2))
        killed_mid_read = False
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if os.path.exists(flag):
                # the worker is INSIDE get_row with the row mmapped
                fleet.kill_worker(victim2, signal.SIGKILL)
                killed_mid_read = True
                break
            time.sleep(0.002)
        if not fleet.drain(timeout_s=60.0):
            log.error("mid-L2-read kill phase did not drain")
        res2 = fr2.result
        row_ref = engine.feed().lookup(col2)
        row_bit_identical = bool(
            res2 is not None and res2.ok and row_ref is not None
            and np.array_equal(np.asarray(res2.data), np.asarray(row_ref))
        )
        mid_l2_kill = {
            "killed_mid_read": killed_mid_read,
            "row_bit_identical": row_bit_identical,
            "dwell_s": dwell_s,
            "victim": victim2,
            "served_by_path": None if res2 is None else res2.path,
        }
        # wait for the SECOND exhumation (victim2's black box holds
        # the dwell + in-flight request the kill interrupted), then
        # capture the post-mortem that must show them
        deadline = time.time() + 15.0
        while (time.time() < deadline
               and fleet.counts["blackbox_exhumed"] < 2):
            time.sleep(0.005)
        final_post_mortem = (
            orecorder.post_mortem(
                "WorkerSIGKILLedMidL2Read",
                reason=f"worker {victim2} killed -9 inside an L2 read",
            )
            if orecorder is not None else None
        )
        # let victim2's restart land so stop() drains a whole fleet
        deadline = time.time() + 60.0
        while time.time() < deadline:
            w2 = fleet.worker(victim2)
            if w2.ready and not w2.dead:
                break
            time.sleep(0.01)

        fleet.drain(timeout_s=60.0)
        wall = time.time() - t0
        stats = fleet.stats(wall_s=wall)
        lost = fleet.lost_requests()
        fleet_telemetry = tower.fleet_telemetry()
        alerts_block = tower.alerts_block()
        # merge the fleet's timelines while the run dir still exists
        # (workers atomically publish on the heartbeat cadence,
        # throttled to one save per 0.5s — give the tail one beat)
        time.sleep(0.6)
        try:
            merged = fleet.merged_trace()
        except Exception:
            log.exception("cross-process trace merge failed")
            merged = None
    finally:
        try:
            fleet.stop(drain=True)
        except Exception:
            log.exception("fleet stop failed")
        if decoy.poll() is None:  # hygiene sweep failed: don't leak it
            decoy.kill()
            decoy.wait(timeout=5.0)
        import shutil as _shutil

        _shutil.rmtree(run_root, ignore_errors=True)
    fleet_span.__exit__(None, None, None)

    # -- bit-identity audit: every served result vs ITS path's fresh
    # reference. Cache rows must equal the parent's own recorded stream
    # (the workers mmap those exact bytes through the exported
    # manifest); compute results must equal per-request
    # get_subgrid_task on a fresh forward; the cross-program allclose
    # guard catches wrong-row serving either way.
    fwd_ref = SwiftlyForward(config, facet_tasks, lru_forward=2,
                             queue_size=64)
    stream_ref = engine.feed()
    ref_cache = {}
    checked = mismatches = cross_mismatches = 0
    for sg, fr in tracked:
        res = fr.result
        if res is None or not res.ok:
            continue
        key = (sg.off0, sg.off1)
        if key not in ref_cache:
            srow = stream_ref.lookup(sg)
            ref_cache[key] = (
                np.asarray(fwd_ref.get_subgrid_task(sg)),
                None if srow is None else np.asarray(srow),
            )
        compute_ref, cache_ref = ref_cache[key]
        expected = (
            cache_ref
            if res.path == "cache" and cache_ref is not None
            else compute_ref
        )
        got = np.asarray(res.data)
        checked += 1
        if not np.array_equal(got, expected):
            mismatches += 1
        if not np.allclose(got, compute_ref, rtol=1e-4, atol=1e-8):
            cross_mismatches += 1

    n_ok = sum(
        1 for _sg, fr in tracked
        if fr.result is not None and fr.result.ok
    )
    victim_cycle = [
        t["to"] for t in stats["breakers"][victim]["transitions"]
    ]

    # -- distributed observability plane: trace merge + black box -----
    merged_path = None
    trace_merge = None
    if merged is not None:
        merged_path = (
            os.path.splitext(out_path)[0] + "_merged_trace.json")
        with open(merged_path, "w") as fh:
            json.dump(merged, fh)
        meta = merged.get("otherData") or {}
        router_pid = os.getpid()
        cross_requests = sum(
            1 for ev in merged.get("traceEvents") or []
            if isinstance(ev, dict) and ev.get("ph") == "X"
            and (ev.get("args") or {}).get("xpid") == router_pid
        )
        trace_merge = {
            "n_processes": meta.get("n_processes"),
            "pids": meta.get("pids"),
            "n_spans": meta.get("n_spans"),
            "clock_offsets": meta.get("clock_offsets"),
            "cross_process_requests": cross_requests,
            "merged_trace_path": merged_path,
        }

    def _victim_event(pm, rid, name):
        """Did the rid's OWN `name` event (exhumed from its black box,
        `[worker-<rid> ...]`-prefixed) reach this post-mortem tail?"""
        return any(
            isinstance(e, dict) and e.get("name") == name
            and f"[worker-{rid} " in str(e.get("detail", ""))
            for e in ((pm or {}).get("events") or [])
        )

    victim_events_in_pm = bool(
        _victim_event(final_post_mortem, victim2, "proc.l2_dwell")
        or _victim_event(kill_post_mortem, victim, "proc.request")
    )
    n_cols = len({sg.off0 for sg in subgrid_configs})
    failover_ms = stats["failover_ms"]
    record = {
        "metric": (
            f"{name} process-fleet SIGKILL drill "
            f"({len(tracked)} zipf requests over {n_cols} columns, "
            f"{n_workers} worker processes, kill+restart+mid-L2-read "
            f"kill, planar f32, {platform})"
        ),
        "value": round(wall, 4),
        "unit": "s",
        "throughput_rps": (
            round(stats["served"] / wall, 2) if wall else 0.0
        ),
        "p50_ms": stats["p50_ms"],
        "p99_ms": stats["p99_ms"],
        "n_requests": stats["requests"],
        "n_served": stats["served"],
        "n_shed": stats["shed"],
        "bit_identical": {
            "checked": checked,
            "mismatches": mismatches,
            "cross_program_mismatches": cross_mismatches,
        },
        "procfleet": {
            "n_workers": n_workers,
            "victim": victim,
            "victim_pid": killed_pid,
            "worker_deaths": stats["worker_deaths"],
            "restarts": stats["restarts"],
            "failovers": stats["failovers"],
            "reroutes": stats["reroutes"],
            "lost_requests": lost,
            "failover_ms": failover_ms,
            "failover_episodes": stats["failover_episodes"],
            "p99_before_ms": p99_before,
            "p99_during_ms": p99_during,
            "p99_after_ms": p99_after,
            "p99_recovery_ratio": (
                round(p99_after / p99_before, 3) if p99_before else None
            ),
            "breaker_cycle": victim_cycle,
            "breakers": {
                str(rid): b for rid, b in stats["breakers"].items()
            },
            "health_transitions": stats["health"]["transitions"],
            "per_worker": stats["per_worker"],
            "orphans": orphans,
            "mid_l2_kill": mid_l2_kill,
            "wire": {
                "heartbeats": stats["heartbeats"],
            },
            "telemetry": stats["telemetry"],
            "clock_offsets": stats["clock_offsets"],
            "trace_merge": trace_merge,
            "black_box": {
                **stats["black_box"],
                "victim_events_in_post_mortem": victim_events_in_pm,
            },
        },
        "fleet_telemetry": fleet_telemetry,
        "alerts": alerts_block,
        "zipf": {"s": zipf_s, "n_columns": n_cols, "seed": seed},
        "n_subgrids_cover": len(subgrid_configs),
        "manifest": run_manifest(
            params={"config": name, "mode": "procfleet", **params},
        ),
    }
    if orecorder is not None:
        pm_path = os.path.splitext(out_path)[0] + "_postmortem.jsonl"
        orecorder.dump(
            pm_path, "WorkerSIGKILLed",
            reason=f"worker {victim} pid {killed_pid} killed -9",
        )
        record["post_mortem"] = dict(
            final_post_mortem
            or kill_post_mortem
            or orecorder.post_mortem("drill_complete")
        )
        record["post_mortem"]["dump_path"] = pm_path
    if metrics.enabled():
        record["telemetry"] = metrics.export()
    if trace_path:
        from swiftly_tpu.obs import summarize_trace

        summary = summarize_trace(
            otrace.export(), root_id=getattr(fleet_span, "id", None)
        )
        summary["leg_wall_s"] = round(wall, 6)
        record["trace"] = summary
        otrace.save(trace_path)
        otrace.disable()

    problems = validate_procfleet_artifact(record)
    if smoke_mode:
        # drill outcomes: schema passing is not proof the fleet survived
        if lost != 0:
            problems.append(f"lost requests: {lost}")
        if n_ok != len(tracked):
            problems.append(
                f"{len(tracked) - n_ok} of {len(tracked)} requests "
                "not served ok"
            )
        if mismatches or checked != n_ok:
            problems.append(
                f"bit-identity audit failed: {mismatches} mismatches, "
                f"{checked}/{n_ok} checked"
            )
        if cross_mismatches:
            problems.append(
                f"cross-program audit failed: {cross_mismatches} "
                "results diverge from per-request compute beyond "
                "reduction-order noise (wrong-row serving)"
            )
        if stats["worker_deaths"] < 2:
            problems.append(
                f"expected 2 real worker deaths (mid-burst + mid-L2-"
                f"read), got {stats['worker_deaths']}"
            )
        if stats["restarts"] < 1:
            problems.append("supervisor never restarted a dead worker")
        if stats["failovers"] < 1:
            problems.append("the SIGKILL produced no failover")
        for state in ("open", "half_open", "closed"):
            if state not in victim_cycle:
                problems.append(
                    f"victim breaker never reached {state!r} "
                    f"(cycle: {victim_cycle})"
                )
        if not any(
            h["owner"] == victim and h["to"] == "revoked"
            for h in stats["health"]["transitions"]
        ):
            problems.append("victim lease was never revoked")
        if not killed_mid_read:
            problems.append(
                "the dwell flag never appeared: the second kill did "
                "not land inside an L2 read"
            )
        if not row_bit_identical:
            problems.append(
                "the mid-L2-read kill's failed-over row is not "
                "bit-identical to the recorded stream"
            )
        if orphans["orphans_reaped"] < 1 or not orphans["decoy_reaped"]:
            problems.append(
                f"startup hygiene did not reap the decoy orphan: "
                f"{orphans}"
            )
        if orphans["stale_sockets_swept"] < 1:
            problems.append(
                "startup hygiene did not sweep the stale socket"
            )
        if stats["heartbeats"] < 10:
            problems.append(
                f"suspiciously few heartbeats on the wire: "
                f"{stats['heartbeats']}"
            )
        if p99_before and p99_after > 3.0 * p99_before:
            problems.append(
                f"p99 did not recover: {p99_after}ms after vs "
                f"{p99_before}ms before (> 3x)"
            )
        # observability-plane outcomes: the victim's OWN story must
        # survive the kill, and one timeline must span the fleet
        if not _victim_event(final_post_mortem, victim2,
                             "proc.l2_dwell"):
            problems.append(
                "the mid-L2-read victim's own proc.l2_dwell event "
                "never reached the parent's post-mortem (black box "
                "lost the dwell)"
            )
        if not _victim_event(final_post_mortem, victim2,
                             "proc.request"):
            problems.append(
                "the mid-L2-read victim's in-flight proc.request "
                "never reached the parent's post-mortem"
            )
        if trace_merge is None:
            problems.append("cross-process trace merge produced "
                            "nothing")
        else:
            if (trace_merge["n_processes"] or 0) < 2:
                problems.append(
                    f"merged timeline spans "
                    f"{trace_merge['n_processes']!r} process(es), "
                    "expected >= 2"
                )
            if trace_merge["cross_process_requests"] < 1:
                problems.append(
                    "no request span crossed a process boundary in "
                    "the merged timeline"
                )
        if len(stats["clock_offsets"]) < n_workers:
            problems.append(
                f"clock offsets estimated for only "
                f"{len(stats['clock_offsets'])} of {n_workers} workers"
            )
        cov = stats["telemetry"]["coverage"]
        if not isinstance(cov, (int, float)) or cov < 0.5:
            problems.append(
                f"telemetry coverage {cov!r}: TELEMETRY frames "
                "vouch for less than half the workers' live time"
            )
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2)
    if smoke_mode:
        metrics.disable()
        print(
            json.dumps(
                {
                    "procfleet_smoke": "ok" if not problems else "failed",
                    "config": name,
                    "artifact": out_path,
                    "n_served": stats["served"],
                    "lost_requests": lost,
                    "victim": victim,
                    "failover_ms": failover_ms,
                    "worker_deaths": stats["worker_deaths"],
                    "restarts": stats["restarts"],
                    "breaker_cycle": victim_cycle,
                    "killed_mid_read": killed_mid_read,
                    "row_bit_identical": row_bit_identical,
                    "orphans_reaped": orphans["orphans_reaped"],
                    "stale_sockets_swept": orphans["stale_sockets_swept"],
                    "heartbeats": stats["heartbeats"],
                    "telemetry_frames": stats["telemetry"]["frames"],
                    "telemetry_coverage": stats["telemetry"]["coverage"],
                    "blackbox_exhumed": stats["blackbox_exhumed"],
                    "merged_processes": (
                        None if trace_merge is None
                        else trace_merge["n_processes"]),
                    "cross_process_requests": (
                        None if trace_merge is None
                        else trace_merge["cross_process_requests"]),
                    "problems": problems,
                }
            ),
            flush=True,
        )
        return 0 if not problems else 1
    print(json.dumps(record), flush=True)
    return 0 if not problems else 1


def _ensure_mesh_devices(n):
    """>= 2 devices for the mesh leg: build a virtual CPU mesh when the
    process has none (`__graft_entry__._ensure_devices`, which refuses
    to tear down a live TPU/GPU backend — on real multi-chip hardware
    the existing devices are used as-is)."""
    import __graft_entry__ as ge

    try:
        ge._ensure_devices(max(2, int(n)))
    except RuntimeError:
        pass  # a real accelerator backend is already up: use it
    import jax

    return len(jax.devices())


def mesh_bench(smoke_mode=False):
    """`bench.py --mesh [--smoke]`: the mesh-streamed engine leg.

    Runs the SAME spill-cached, facet-partitioned streamed round trip
    twice — once on the single-chip engine, once on the mesh-streamed
    engine (`swiftly_tpu.mesh`) with the facet stack sharded over every
    device — and stamps a ``mesh`` artifact block: the executed layout
    (shards, padding), the plan's ICI collective bytes, scaling
    efficiency vs single-chip, the reduction-order match audit
    (per-facet math is identical; only the forward collective's
    facet-sum order differs — asserted within BENCH_MESH_TOL, default
    5e-5 relative, docs/multichip.md), and an HLO audit showing the
    facet-axis collective in the lowered streamed column pass: the
    all-reduce under psum, the 2(n-1) collective-permute pipeline under
    SWIFTLY_MESH_COLLECTIVE=ring. The executed collective is stamped
    in the artifact and must MATCH the planned one
    (``plan_compiled.mesh.collective``); under ring the leg also times
    a psum baseline on the same geometry and records the ring-vs-psum
    wall ratio. Both paths are warmed (compile + first dispatch)
    before timing — BENCH_MESH_WARM=0 restores the cold wall. The
    compiled plan's `MeshLayout` is consumed by the engine, so the
    stamped ``plan_compiled.mesh.status`` is ``"bound"``. Validated by
    `obs.validate_mesh_artifact`.

    On CPU run under ``XLA_FLAGS=--xla_force_host_platform_device_count
    =8`` (the leg builds the virtual mesh itself when the backend is
    not initialised yet); ``BENCH_MESH_DEVICES`` overrides the device
    count, ``BENCH_MESH_CONFIG`` the config.
    """
    from swiftly_tpu.utils import enable_compilation_cache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    n_req = int(os.environ.get("BENCH_MESH_DEVICES", "8"))
    n_av = _ensure_mesh_devices(n_req)  # before any other jax use
    problems = []
    if n_av < 2:
        print(
            json.dumps(
                {
                    "mesh_smoke" if smoke_mode else "mesh": "failed",
                    "problems": [
                        f"mesh leg needs >= 2 devices, found {n_av}; on "
                        "CPU set XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8"
                    ],
                }
            ),
            flush=True,
        )
        return 1
    from swiftly_tpu.obs import (
        metrics,
        run_manifest,
        validate_mesh_artifact,
        validate_plan_accuracy_artifact,
        validate_plan_artifact,
    )

    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    out_path = os.environ.get("BENCH_MESH_OUT", "BENCH_mesh.json")
    metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
    name = os.environ.get(
        "BENCH_MESH_CONFIG",
        "1k[1]-n512-256" if smoke_mode else "4k[1]-n2k-512",
    )
    import re

    import jax
    import jax.numpy as jnp

    from swiftly_tpu import SWIFT_CONFIGS
    from swiftly_tpu.mesh import (
        MeshStreamedBackward,
        MeshStreamedForward,
        make_facet_mesh,
    )
    from swiftly_tpu.parallel import StreamedBackward
    from swiftly_tpu.plan import PlanInputs, compile_plan
    from swiftly_tpu.utils.spill import SpillCache

    platform = jax.devices()[0].platform
    params = dict(SWIFT_CONFIGS[name])
    params.setdefault("fov", 1.0)
    config, fwd, facet_configs, subgrid_configs, _sources = _build(
        "planar", params, jnp.float32, streamed=True
    )
    F = len(facet_configs)
    half = max(1, F // 2)
    subsets = [(0, half), (half, F)] if F > 1 else [(0, F)]
    fold_group = int(os.environ.get("BENCH_FOLD_GROUP", "2"))

    def _passes_counter():
        return (metrics.export().get("counters") or {}).get(
            "fwd.passes", 0
        )

    # feed-once/fold-many parity: the mesh backward consumes the SAME
    # schedule helper as the single-chip leg (one shared feed per chunk
    # of `feed_group` facet-subset passes). Default 1 keeps the
    # cache-fed feed exercised under sharding (a single shared feed
    # would never re-read the cache).
    feed_group_env = max(
        1, int(os.environ.get("BENCH_BWD_FEED_GROUP", "1"))
    )

    def roundtrip(fwd_exec, make_bwd):
        """Spill-cached facet-partitioned round trip: ONE forward pass
        records the stream, every later facet-subset FEED is cache-fed
        (identical shape to `run_one`'s roundtrip-streamed leg,
        including the feed-once/fold-many schedule)."""
        from swiftly_tpu.parallel import feed_backward_passes

        spill = SpillCache(budget_bytes=2e9)
        parts = []
        t0 = time.time()
        for kfeed, c0 in enumerate(
            range(0, len(subsets), feed_group_env)
        ):
            chunk = subsets[c0 : c0 + feed_group_env]
            bwds = [make_bwd(i0, i1) for i0, i1 in chunk]
            feed_backward_passes(
                fwd_exec, subgrid_configs, bwds, spill=spill,
                feed_index=kfeed,
            )
            parts.extend(np.asarray(bwd.finish()) for bwd in bwds)
        wall = time.time() - t0
        return np.concatenate(parts, axis=0), wall, spill

    # warm both engines before timing: the first round trip carries
    # compile + first-dispatch cost, which used to land inside the
    # mesh wall and skew scaling_efficiency (BENCH_MESH_WARM=0 keeps
    # the cold wall for compile-cost studies)
    warm = os.environ.get("BENCH_MESH_WARM", "1") != "0"

    def measured_roundtrip(fwd_exec, make_bwd):
        if warm:
            roundtrip(fwd_exec, make_bwd)
        p0 = _passes_counter()
        out, wall, spill = roundtrip(fwd_exec, make_bwd)
        return out, wall, spill, _passes_counter() - p0

    # -- single-chip reference (the engine every prior PR measured) ------
    log.info("mesh leg: single-chip reference round trip (%s)", name)
    ref, wall_single, _spill1, single_passes = measured_roundtrip(
        fwd,
        lambda i0, i1: StreamedBackward(
            config, list(facet_configs[i0:i1]), residency="sampled",
            fold_group=fold_group,
        ),
    )

    # -- mesh-streamed run: the compiled layout, bound by the engine -----
    n_shards = min(n_av, F)
    plan = compile_plan(
        PlanInputs.from_cover(
            config, facet_configs, subgrid_configs, n_devices=n_shards,
            real_facets=getattr(fwd, "_facets_real", False),
            fold_group=fold_group,
        ),
        mode="roundtrip-streamed",
    )
    mesh = make_facet_mesh(n_devices=plan.mesh.facet_shards)
    facet_tasks = list(zip(facet_configs, fwd._facet_data))
    mfwd = MeshStreamedForward(
        config, facet_tasks, layout=plan.mesh, mesh=mesh
    )
    executed_collective = getattr(mfwd, "collective", "psum")
    planned_collective = getattr(plan.mesh, "collective", "psum")
    if executed_collective != planned_collective:
        problems.append(
            f"executed collective {executed_collective!r} != planned "
            f"{planned_collective!r} (plan_compiled.mesh.collective) — "
            "the env changed between compile and run"
        )
    log.info(
        "mesh leg: mesh-streamed round trip over %d shard(s) (%s)",
        mfwd.facet_shards, executed_collective,
    )

    def _mesh_bwd(i0, i1):
        return MeshStreamedBackward(
            config, list(facet_configs[i0:i1]), mesh=mesh,
            fold_group=fold_group,
        )

    got, wall_mesh, spill2, mesh_passes = measured_roundtrip(
        mfwd, _mesh_bwd
    )
    if mesh_passes != 1:
        problems.append(
            f"mesh round trip ran {mesh_passes} forward pass(es); the "
            "spill-cached plan must run exactly 1 (later passes "
            "cache-fed under sharding)"
        )

    # -- reduction-order match audit -------------------------------------
    scale = float(np.max(np.abs(ref))) or 1.0
    max_abs = float(np.max(np.abs(got - ref)))
    rms = float(np.sqrt(np.mean((got - ref) ** 2)))
    tol = float(os.environ.get("BENCH_MESH_TOL", "5e-5")) * scale
    if not max_abs <= tol:
        problems.append(
            f"mesh facets diverge from single-chip by {max_abs:.3e} "
            f"(> reduction-order tolerance {tol:.3e})"
        )

    # -- HLO audit: the facet-axis collective in the streamed stage ------
    from swiftly_tpu.parallel.streamed import _column_pass_fwd_sharded

    core = config.core
    xA = params["xA_size"]
    F_probe = mfwd.facet_shards
    colfn = _column_pass_fwd_sharded(core, mesh, xA)
    probe = (
        jnp.zeros(
            (F_probe, core.xM_yN_size, params["yB_size"], 2),
            dtype=core.dtype,
        ),
        jnp.zeros(F_probe, dtype=int),
        jnp.zeros(F_probe, dtype=int),
        jnp.zeros((3, 2), dtype=int),
        jnp.ones((3, xA), dtype=core.dtype),
        jnp.ones((3, xA), dtype=core.dtype),
    )
    hlo = colfn.lower(*probe).compile().as_text()
    n_all_reduce = len(re.findall(r"all-reduce(?:-start)?\(", hlo))
    n_permute = len(
        re.findall(r"collective-permute(?:-start)?\(", hlo)
    )
    if executed_collective == "ring":
        if not n_permute:
            problems.append(
                "ring collective requested but no collective-permute "
                "in the lowered streamed column pass (likely HLO "
                "text-format drift — see "
                "__graft_entry__.dryrun_multichip)"
            )
    elif not n_all_reduce:
        problems.append(
            "no all-reduce in the lowered streamed column pass (likely "
            "HLO text-format drift — see __graft_entry__.dryrun_multichip)"
        )

    # -- ring-vs-psum baseline: same geometry, blocking collective -------
    # Recorded whenever ring executed: the overlap claim is a RATIO
    # claim, so the artifact carries the psum wall it beat (or didn't —
    # CPU-simulated permutes share one memory bus, so the ratio is a
    # trend anchor there, meaningful on real ICI like the SE itself).
    collective_baseline = None
    if executed_collective == "ring":
        log.info("mesh leg: psum baseline round trip (same geometry)")
        prev_env = os.environ.get("SWIFTLY_MESH_COLLECTIVE")
        os.environ["SWIFTLY_MESH_COLLECTIVE"] = "psum"
        try:
            _, wall_psum, _, _ = measured_roundtrip(mfwd, _mesh_bwd)
        finally:
            if prev_env is None:
                del os.environ["SWIFTLY_MESH_COLLECTIVE"]
            else:
                os.environ["SWIFTLY_MESH_COLLECTIVE"] = prev_env
        collective_baseline = {
            "collective": "psum",
            "mesh_wall_s": round(wall_psum, 4),
            "scaling_efficiency": round(
                (wall_single / wall_psum) / mfwd.facet_shards, 4
            ),
            # > 1.0 = ring round trip beat the blocking psum
            "ring_vs_psum": round(wall_psum / wall_mesh, 4),
        }

    mesh_block = {
        "n_devices": int(n_av),
        "facet_shards": int(mfwd.facet_shards),
        "n_facets": F,
        "padded_facets": int(mfwd.stack.n_total),
        "collective_bytes": int(plan.mesh.collective_bytes_total),
        "single_chip_wall_s": round(wall_single, 4),
        "mesh_wall_s": round(wall_mesh, 4),
        # speedup per shard: 1.0 = linear scaling (CPU-simulated meshes
        # sit far below 1 — the number is the sentinel's trend anchor,
        # meaningful on real ICI)
        "scaling_efficiency": round(
            (wall_single / wall_mesh) / mfwd.facet_shards, 4
        ),
        "collective": executed_collective,
        "match": {
            "max_abs_diff": max_abs,
            "rms_diff": rms,
            "tolerance": tol,
            "within_tolerance": bool(max_abs <= tol),
            "bit_identical": bool(max_abs == 0.0),
        },
        "hlo": {
            "all_reduce": n_all_reduce,
            "collective_permute": n_permute,
            "stage": "fwd.column_pass",
        },
        "spill": spill2.stats(),
        "forward_passes": mesh_passes,
    }
    if collective_baseline is not None:
        mesh_block["collective_baseline"] = collective_baseline
    record = {
        "metric": f"{name} mesh-streamed round-trip wall-clock "
                  f"({len(subgrid_configs)} subgrids, planar f32, "
                  f"mesh-streamed, {platform})",
        "value": round(wall_mesh, 4),
        "unit": "s",
        "n_subgrids": len(subgrid_configs),
        "single_chip_wall_s": round(wall_single, 4),
        "single_chip_forward_passes": single_passes,
        "mesh": mesh_block,
        # the engine bound the layout above, so the stamped status is
        # "bound" — the acceptance contract validate_mesh_artifact checks
        "plan_compiled": plan.artifact_block(measured_wall_s=wall_mesh),
    }
    record["manifest"] = run_manifest(
        baseline_source=None,
        params={"config": name, "mode": "mesh-streamed", **params},
    )
    record["telemetry"] = metrics.export()
    # per-stage predicted-vs-measured reconciliation — the mesh leg is
    # where the plan's collective pricing (mesh.psum / mesh.ring_step)
    # meets its measured stage
    _stamp_plan_accuracy(record)
    problems.extend(validate_plan_accuracy_artifact(record))
    if trace_path:
        from swiftly_tpu.obs import summarize_trace
        from swiftly_tpu.obs import trace as otrace

        record["trace"] = summarize_trace(otrace.export())
        otrace.save(trace_path)
        otrace.disable()
    problems.extend(validate_mesh_artifact(record))
    problems.extend(validate_plan_artifact(record))
    import json as _json

    with open(out_path, "w") as fh:
        _json.dump(record, fh, indent=2)
    metrics.disable()
    print(
        json.dumps(
            {
                "mesh_smoke" if smoke_mode else "mesh": (
                    "ok" if not problems else "failed"
                ),
                "config": name,
                "artifact": out_path,
                "facet_shards": mesh_block["facet_shards"],
                "collective": executed_collective,
                "scaling_efficiency": mesh_block["scaling_efficiency"],
                **(
                    {"ring_vs_psum": collective_baseline["ring_vs_psum"]}
                    if collective_baseline
                    else {}
                ),
                "max_abs_diff": max_abs,
                "all_reduce": n_all_reduce,
                "collective_permute": n_permute,
                "problems": problems,
            }
        ),
        flush=True,
    )
    return 0 if not problems else 1


def _delta_mutate(tasks, idxs, scale):
    """A content-bearing mutation of the facets at ``idxs``: scale the
    sparse descriptor's pixel values (a sky-model amplitude change —
    the K-of-J update the incremental engine exists for)."""
    from swiftly_tpu.ops.oracle import SparseRealFacet

    out = list(tasks)
    for i in idxs:
        fc, f = tasks[i]
        out[i] = (
            fc,
            SparseRealFacet(
                f.size, f.rows, f.cols,
                np.asarray(f.vals) * np.float32(scale),
            ),
        )
    return out


def delta_bench(smoke_mode=False):
    """`bench.py --delta [--smoke]`: the incremental re-transform leg.

    Records the full subgrid stream once (`delta.IncrementalForward`),
    then mutates K of the J facets (BENCH_DELTA_K, default "1,3") and
    times the incremental update — delta stream restricted to the K
    changed facets, cached stream patched in place — against the timed
    full re-record. Asserts: the engine took the PATCH path (its
    `plan.plan_delta` pricing agrees), the patched stream matches a
    fresh full recompute of the new stack within the documented f32
    sum-reorder tolerance (BENCH_DELTA_TOL, default 1e-4 relative —
    docs/incremental.md), and `SWIFTLY_DELTA_EXACT`-style updates
    (``exact=True``) are BIT-identical to the fresh recompute. Stamps a
    ``delta`` artifact block {changed_facets, patched_columns,
    speedup_vs_full, max_abs_diff, plan, match, exact} validated by
    `obs.validate_delta_artifact`; `scripts/delta_drill.py` is the
    operator entry.
    """
    from swiftly_tpu.utils import enable_compilation_cache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    from swiftly_tpu.obs import (
        metrics,
        run_manifest,
        validate_delta_artifact,
    )

    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    out_path = os.environ.get("BENCH_DELTA_OUT", "BENCH_delta.json")
    metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
    name = os.environ.get(
        "BENCH_DELTA_CONFIG",
        "1k[1]-n512-256" if smoke_mode else "4k[1]-n2k-512",
    )
    import jax
    import jax.numpy as jnp

    from swiftly_tpu import (
        SWIFT_CONFIGS,
        SwiftlyConfig,
        make_full_facet_cover,
        make_full_subgrid_cover,
        make_sparse_facet,
    )
    from swiftly_tpu.delta import FacetDeltaLedger, IncrementalForward
    from swiftly_tpu.parallel import StreamedForward
    from swiftly_tpu.utils.spill import SpillCache

    platform = jax.devices()[0].platform
    problems = []
    params = dict(SWIFT_CONFIGS[name])
    params.setdefault("fov", 1.0)
    config = SwiftlyConfig(backend="planar", dtype=jnp.float32, **params)
    facet_configs = make_full_facet_cover(config)
    subgrid_configs = make_full_subgrid_cover(config)
    sources = _bench_sources(config.image_size)
    facet_tasks = [
        (fc, make_sparse_facet(config.image_size, fc, sources,
                               dtype=np.float32))
        for fc in facet_configs
    ]
    F = len(facet_configs)
    # only content-bearing facets make a real delta (scaling an empty
    # descriptor is content-identical and the ledger rightly ignores it)
    content = [
        j for j, (_, f) in enumerate(facet_tasks)
        if np.asarray(f.vals).size
    ]
    if not content:
        problems.append("no facet carries source pixels; nothing to mutate")
    ks = sorted({
        max(1, min(int(k), max(1, F - 1), len(content)))
        for k in os.environ.get("BENCH_DELTA_K", "1,3").split(",")
    })

    from swiftly_tpu.utils.spill import spill_budget_bytes

    spill = SpillCache(budget_bytes=spill_budget_bytes())
    engine = IncrementalForward(
        config, facet_tasks, spill, ledger=FacetDeltaLedger()
    )
    log.info("delta leg: warmup record (%s, %d facets)", name, F)
    engine.record(subgrid_configs)  # compile + layout warmup
    log.info("delta leg: timed full record")
    t0 = time.time()
    engine.record(subgrid_configs)
    wall_full = time.time() - t0

    def fresh_reference(tasks):
        """A fresh full stream of ``tasks`` into its own cache — the
        ground truth the patched stream is audited against."""
        ref = SpillCache(budget_bytes=spill_budget_bytes())
        rfwd = StreamedForward(config, tasks, residency="device")
        for _ in rfwd.stream_column_groups(subgrid_configs, spill=ref):
            pass
        return ref

    def audit(ref):
        mx = sc = 0.0
        for k in range(len(spill)):
            a = np.asarray(spill.get(k))
            b = np.asarray(ref.get(k))
            mx = max(mx, float(np.max(np.abs(a - b))))
            sc = max(sc, float(np.max(np.abs(b))))
        return mx, sc or 1.0

    legs = []
    scale_step = 1.5
    # under SWIFTLY_DELTA_EXACT=1 (delta_drill --exact) every update
    # replays by contract, and the audit tightens to bit-identity
    exact_env = os.environ.get("SWIFTLY_DELTA_EXACT") == "1"
    for kk in ks:
        idxs = content[:kk]
        # warm update: compiles the K-facet delta pass (a fresh
        # StreamedForward per update shares the lru-cached jits)
        scale_step += 0.25
        engine.update(_delta_mutate(engine.facet_tasks, idxs, scale_step))
        scale_step += 0.25
        tasks2 = _delta_mutate(engine.facet_tasks, idxs, scale_step)
        t0 = time.time()
        report = engine.update(tasks2)
        wall_patch = time.time() - t0
        if exact_env:
            if report["mode"] != "replay":
                problems.append(
                    f"K={kk} exact-mode update took mode "
                    f"{report['mode']!r}; SWIFTLY_DELTA_EXACT=1 must "
                    "force the full replay"
                )
        elif report["mode"] != "patch":
            problems.append(
                f"K={kk} update took mode {report['mode']!r} "
                f"(reason {report['reason']!r}); the drill must "
                "exercise the patch path"
            )
        mx, sc = audit(fresh_reference(engine.facet_tasks))
        tol = (
            0.0
            if exact_env
            else float(os.environ.get("BENCH_DELTA_TOL", "1e-4")) * sc
        )
        if not mx <= tol:
            problems.append(
                f"K={kk} patched stream diverges from fresh recompute "
                f"by {mx:.3e} (> f32 sum-reorder tolerance {tol:.3e})"
            )
        legs.append(
            {
                "k": kk,
                "changed_facets": list(report["changed_facets"]),
                "patched_columns": report["patched_columns"],
                "patched_entries": report["patched_entries"],
                "patch_wall_s": round(wall_patch, 4),
                "full_wall_s": round(wall_full, 4),
                "speedup_vs_full": round(wall_full / wall_patch, 2),
                "match": {
                    "max_abs_diff": mx,
                    "tolerance": tol,
                    "within_tolerance": bool(mx <= tol),
                    "bit_identical": bool(mx == 0.0),
                },
                "stream_version": report["stream_version"],
                "plan": report["plan"],
            }
        )
        log.info(
            "delta leg: K=%d patch %.3fs vs full %.3fs (%.1fx), "
            "max|diff| %.3e", kk, wall_patch, wall_full,
            wall_full / wall_patch, mx,
        )

    # exactness escape hatch: an exact update re-records and must be
    # BIT-identical to an independent fresh stream of the same stack
    exact_block = None
    if os.environ.get("BENCH_DELTA_EXACT_CHECK", "1") == "1" and content:
        tasks3 = _delta_mutate(engine.facet_tasks, content[:1], 0.8)
        rep3 = engine.update(tasks3, exact=True)
        ref3 = fresh_reference(engine.facet_tasks)
        bit = all(
            np.array_equal(
                np.asarray(spill.get(k)), np.asarray(ref3.get(k))
            )
            for k in range(len(spill))
        )
        exact_block = {"mode": rep3["mode"], "bit_identical": bool(bit)}
        if rep3["mode"] != "replay" or not bit:
            problems.append(
                f"exact update must replay bit-identically, got "
                f"{exact_block}"
            )

    head = legs[0] if legs else {}
    delta_block = {
        "n_facets": F,
        "changed_facets": head.get("changed_facets", []),
        "patched_columns": head.get("patched_columns", 0),
        "patched_entries": head.get("patched_entries", 0),
        "speedup_vs_full": head.get("speedup_vs_full", 0.0),
        "max_abs_diff": (head.get("match") or {}).get("max_abs_diff"),
        "match": head.get("match"),
        "plan": head.get("plan"),
        "exact": exact_block,
        "exact_mode": exact_env,
        "legs": legs,
        "spill": spill.stats(),
    }
    record = {
        "metric": f"{name} incremental K-facet update wall-clock "
                  f"({len(subgrid_configs)} subgrids, planar f32, "
                  f"delta, {platform})",
        "value": head.get("patch_wall_s", 0.0),
        "unit": "s",
        "n_subgrids": len(subgrid_configs),
        "full_record_wall_s": round(wall_full, 4),
        "delta": delta_block,
    }
    record["manifest"] = run_manifest(
        baseline_source=None,
        params={"config": name, "mode": "delta", **params},
    )
    record["telemetry"] = metrics.export()
    if trace_path:
        from swiftly_tpu.obs import summarize_trace
        from swiftly_tpu.obs import trace as otrace

        record["trace"] = summarize_trace(otrace.export())
        otrace.save(trace_path)
        otrace.disable()
    problems.extend(validate_delta_artifact(record))
    import json as _json

    with open(out_path, "w") as fh:
        _json.dump(record, fh, indent=2)
    metrics.disable()
    print(
        json.dumps(
            {
                "delta_smoke" if smoke_mode else "delta": (
                    "ok" if not problems else "failed"
                ),
                "config": name,
                "artifact": out_path,
                "speedup_vs_full": delta_block["speedup_vs_full"],
                "patched_columns": delta_block["patched_columns"],
                "max_abs_diff": delta_block["max_abs_diff"],
                "problems": problems,
            }
        ),
        flush=True,
    )
    return 0 if not problems else 1


# Relative-RMS error budgets asserted by `bench.py --precision` — the
# code twin of the table in docs/accuracy.md ("Precision error budget").
# Relative RMS = abs RMS x N^2 (the unit-source scaling of accuracy.md;
# the bench's multi-source model with amplitudes up to 2.75 and a
# max-over-samples RMS measures ~2e-5 at the `highest` f32 floor).
# Budgets carry ~15x headroom over the measured floor so they trip on a
# real precision regression (`high`'s bf16x3 passes sit ~63x above the
# floor on TPU; a LOST `highest` flag therefore lands near ~1.3e-3,
# well past the 3e-4 budget) but never on run-to-run noise. On CPU both
# settings execute true f32 matmuls and land at the `highest` floor.
PRECISION_RMS_BUDGET_REL = {
    "highest": 3e-4,
    "high": 3e-2,
    "default": 3e-2,
}


def precision_child():
    """`bench.py --precision-child`: one precision setting, one process.

    `SWIFTLY_PRECISION` is baked into the lowered programs at TRACE
    time (ops.planar_backend), so each setting must run in its own
    interpreter — the parent (`precision_bench`) sets the env and
    spawns this, which streams the forward cover once warm + once
    timed and prints a single JSON line with the wall and the
    max-over-samples RMS vs the direct-DFT oracle.
    """
    import jax
    import jax.numpy as jnp

    from swiftly_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    name = os.environ.get("BENCH_PRECISION_CONFIG", "1k[1]-n512-256")
    from swiftly_tpu import SWIFT_CONFIGS

    params = dict(SWIFT_CONFIGS[name])
    params.setdefault("fov", 1.0)
    config, fwd, facet_configs, subgrid_configs, sources = _build(
        "planar", params, jnp.float32, streamed=True
    )
    sample_map, oracle_dev = _oracle_sample_stack(
        config, subgrid_configs, sources
    )

    def run_pass():
        max_rms2 = jnp.zeros((), dtype=jnp.float32)
        acc = None
        for items, out in fwd.stream_columns(
            subgrid_configs, device_arrays=True
        ):
            s = jnp.sum(out)
            acc = s if acc is None else acc + s
            for srow, (i, _sgc) in enumerate(items):
                k = sample_map.get(i)
                if k is not None:
                    max_rms2 = jnp.maximum(
                        max_rms2,
                        _rms2_device(config.core, out[srow], oracle_dev[k]),
                    )
        float(np.asarray(acc))
        return float(np.asarray(max_rms2)) ** 0.5

    run_pass()  # warm: compile + facet upload
    t0 = time.time()
    rms = run_pass()
    wall = time.time() - t0
    print(
        json.dumps(
            {
                "precision": os.environ.get(
                    "SWIFTLY_PRECISION", "highest"
                ).lower(),
                "config": name,
                "wall_s": round(wall, 4),
                "rms_vs_dft_oracle": float(f"{rms:.3e}"),
                "n_subgrids": len(subgrid_configs),
                "platform": jax.devices()[0].platform,
            }
        ),
        flush=True,
    )
    return 0


def precision_bench(smoke_mode=False):
    """`bench.py --precision [--smoke]`: the mixed-precision leg.

    Runs the streamed forward under each `SWIFTLY_PRECISION` setting
    (BENCH_PRECISION_SETTINGS, default "highest,high") in a SUBPROCESS
    each — the knob is baked in at trace time — and asserts every
    measured RMS against the explicit error budget table
    (`PRECISION_RMS_BUDGET_REL`, documented in docs/accuracy.md).
    The artifact's headline wall and ``rms_vs_dft_oracle`` come from
    the ``highest`` leg so `scripts/bench_compare.py` tracks both
    (wall and RMS lower-is-better).
    """
    import subprocess

    from swiftly_tpu.obs import run_manifest, validate_artifact

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    name = os.environ.get(
        "BENCH_PRECISION_CONFIG",
        "1k[1]-n512-256" if smoke_mode else "4k[1]-n2k-512",
    )
    out_path = os.environ.get(
        "BENCH_PRECISION_OUT", "BENCH_precision.json"
    )
    settings = [
        s.strip().lower()
        for s in os.environ.get(
            "BENCH_PRECISION_SETTINGS", "highest,high"
        ).split(",")
        if s.strip()
    ]
    from swiftly_tpu import SWIFT_CONFIGS

    params = dict(SWIFT_CONFIGS[name])
    n_img = params["N"]
    problems = []
    legs = []
    for setting in settings:
        budget_rel = PRECISION_RMS_BUDGET_REL.get(setting)
        if budget_rel is None:
            problems.append(
                f"no error budget for SWIFTLY_PRECISION={setting!r} "
                "(docs/accuracy.md table)"
            )
            continue
        env = dict(os.environ)
        env["SWIFTLY_PRECISION"] = setting
        env["BENCH_PRECISION_CONFIG"] = name
        log.info("precision leg: %s (subprocess)", setting)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--precision-child"],
            capture_output=True, text=True, env=env,
            timeout=float(os.environ.get("BENCH_PRECISION_TIMEOUT_S",
                                         "600")),
        )
        line = (proc.stdout.strip().splitlines() or [""])[-1]
        try:
            child = json.loads(line)
        except ValueError:
            problems.append(
                f"precision child {setting!r} emitted no JSON "
                f"(rc={proc.returncode}): "
                f"{(proc.stderr or '').strip()[-300:]}"
            )
            continue
        rel = child["rms_vs_dft_oracle"] * n_img * n_img
        leg = {
            **child,
            "rms_relative": float(f"{rel:.3e}"),
            "budget_relative": budget_rel,
            "within_budget": bool(rel <= budget_rel),
        }
        legs.append(leg)
        if not leg["within_budget"]:
            problems.append(
                f"SWIFTLY_PRECISION={setting}: relative RMS {rel:.3e} "
                f"over the documented budget {budget_rel:.1e} "
                "(docs/accuracy.md)"
            )
    head = next(
        (l for l in legs if l["precision"] == "highest"),
        legs[0] if legs else None,
    )
    if head is None:
        problems.append("no precision leg produced a measurement")
        head = {"wall_s": 0.0, "rms_vs_dft_oracle": 0.0, "platform": "?"}
    record = {
        "metric": f"{name} forward facet->subgrid wall-clock "
                  f"(SWIFTLY_PRECISION={head.get('precision', '?')}, "
                  f"planar f32, streamed, {head['platform']})",
        "value": head["wall_s"],
        "unit": "s",
        "rms_vs_dft_oracle": head["rms_vs_dft_oracle"],
        "precision": {
            "budget_relative": PRECISION_RMS_BUDGET_REL,
            "legs": legs,
        },
    }
    record["manifest"] = run_manifest(
        baseline_source=None,
        params={"config": name, "mode": "precision", **params},
    )
    problems.extend(validate_artifact(record, require_baseline=False))
    import json as _json

    with open(out_path, "w") as fh:
        _json.dump(record, fh, indent=2)
    print(
        json.dumps(
            {
                "precision_smoke" if smoke_mode else "precision": (
                    "ok" if not problems else "failed"
                ),
                "config": name,
                "artifact": out_path,
                "legs": [
                    {
                        "precision": l["precision"],
                        "wall_s": l["wall_s"],
                        "rms_relative": l["rms_relative"],
                        "within_budget": l["within_budget"],
                    }
                    for l in legs
                ],
                "problems": problems,
            }
        ),
        flush=True,
    )
    return 0 if not problems else 1


def smoke():
    """Fast schema-validation leg (`bench.py --smoke`, wired into the
    tier-1 tests): run the 1k round trip with telemetry ON, write the
    BENCH-style artifact plus the JSONL event log, and validate what was
    emitted — full run manifest present, `baseline_source` set, >= 6
    distinct engine stage names, per-stage wall/MFU summary. Schema
    drift fails HERE, in seconds on CPU, not months later in an
    unauditable artifact."""
    from swiftly_tpu.obs import metrics, validate_artifact
    from swiftly_tpu.utils import enable_compilation_cache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    out_path = os.environ.get("BENCH_SMOKE_OUT", "BENCH_smoke.json")
    jsonl_path = os.environ.get(
        "SWIFTLY_METRICS_JSONL", out_path + "l"
    )
    # placeholder roofline so the MFU arithmetic is exercised on CPU
    # (recorded in the manifest's env capture; a real run sets a
    # measured value or runs on a device with a published peak)
    os.environ.setdefault("SWIFTLY_PEAK_TFLOPS", "1.0")
    # force a 2-pass facet-partitioned backward so the spill-cache path
    # (fill + cache-fed pass) and its artifact fields are exercised on
    # CPU — the single-pass plan would never touch the cache. Feed
    # group pinned to 1 (per-pass feeding) for the same reason: CPU's
    # unlimited budget would share ONE feed across both passes and the
    # cache-fed h2d path (prefetch hits, spill.h2d) would never run
    os.environ.setdefault("BENCH_BWD_FACET_PASSES", "2")
    os.environ.setdefault("BENCH_BWD_FEED_GROUP", "1")
    # calibration history lands next to the smoke artifact unless the
    # operator pointed SWIFTLY_CALIBRATION_HISTORY elsewhere (0 = off)
    os.environ.setdefault(
        "SWIFTLY_CALIBRATION_HISTORY",
        os.path.join(
            os.path.dirname(os.path.abspath(out_path)),
            "BENCH_calibration.jsonl",
        ),
    )
    metrics.enable(jsonl_path)
    name = os.environ.get("BENCH_SMOKE_CONFIG", "1k[1]-n512-256")
    record = run_one(name, "roundtrip-streamed")
    problems = validate_artifact(record)
    telemetry = record.get("telemetry") or {}
    stages = telemetry.get("stages") or {}
    engine_stages = {
        s for s in stages if s.startswith(("fwd.", "bwd."))
    }
    if len(engine_stages) < 6:
        problems.append(
            f"expected >= 6 engine stage names, got {sorted(engine_stages)}"
        )
    for s, entry in stages.items():
        for field in ("count", "total_s", "mean_s", "p99_s"):
            if field not in entry:
                problems.append(f"stage {s} missing {field}")
    if not (telemetry.get("total") or {}).get("mfu_pct"):
        problems.append("telemetry total missing mfu_pct")
    # spill-cache schema: the 2-pass backward must have filled the cache
    # on pass 1 and fed pass 2 from it — exactly ONE forward pass
    # (the tentpole's cost model, counter-asserted), spill stats in the
    # artifact, and prefetch hits recorded
    spill_block = record.get("spill") or {}
    if not spill_block:
        problems.append("roundtrip-streamed artifact missing spill stats")
    else:
        for field in ("entries", "complete", "ram_bytes", "writes"):
            if field not in spill_block:
                problems.append(f"spill stats missing {field}")
        if not spill_block.get("complete"):
            problems.append(f"spill cache incomplete: {spill_block}")
    counters = telemetry.get("counters") or {}
    if record.get("forward_passes") != 1:
        problems.append(
            "cache-fed round trip must execute exactly 1 forward pass, "
            f"got forward_passes={record.get('forward_passes')} "
            f"(fwd.passes counter={counters.get('fwd.passes')})"
        )
    if not counters.get("spill.prefetch_hits"):
        problems.append(
            f"no spill prefetch hits in counters {sorted(counters)}"
        )
    # unified-plan schema: every roundtrip-streamed artifact now stamps
    # the compiled plan (inputs hash, pass grid, spill policy, predicted
    # vs measured wall) — drift fails here, on CPU, in seconds
    from swiftly_tpu.obs import validate_plan_artifact

    problems.extend(validate_plan_artifact(record))
    pc = record.get("plan_compiled") or {}
    bwd_plan = record.get("bwd_plan") or {}
    if (pc.get("backward") or {}).get("n_passes") != bwd_plan.get(
        "n_passes"
    ):
        problems.append(
            f"compiled plan n_passes {pc.get('backward')} disagrees "
            f"with the executed bwd_plan {bwd_plan}"
        )
    if "measured_wall_s" not in pc:
        problems.append("plan_compiled missing measured_wall_s")
    # colpass pedigree: the compiled plan resolves the same forward
    # column-pass body the executor binds (env + platform at both
    # sites), so a silent divergence — e.g. a plan priced for pallas
    # while the stream ran einsum — fails here, on CPU, in seconds
    executed_colpass = (record.get("plan") or {}).get("colpass")
    planned_colpass = (pc.get("forward") or {}).get("colpass")
    if executed_colpass != planned_colpass:
        problems.append(
            f"executed plan.colpass {executed_colpass!r} != compiled "
            f"plan_compiled.forward.colpass {planned_colpass!r}"
        )
    if not (pc.get("forward") or {}).get("colpass_candidates"):
        problems.append(
            "plan_compiled.forward missing the ranked "
            "colpass_candidates table"
        )
    # feed-once/fold-many schema: the executed schedule must match the
    # compiled one, the shared-feed stage must have been recorded, and
    # the h2d byte collapse must be exactly what the schedule promises
    # ((n_feeds - 1) x the recorded stream) — asserted from telemetry,
    # not inferred
    if (pc.get("backward") or {}).get("feed_group") != bwd_plan.get(
        "feed_group"
    ):
        problems.append(
            f"compiled plan feed_group {pc.get('backward')} disagrees "
            f"with the executed bwd_plan {bwd_plan}"
        )
    n_feeds = bwd_plan.get("n_feeds") or 0
    if record.get("feed_groups") != n_feeds:
        problems.append(
            f"executed feed_groups {record.get('feed_groups')} != "
            f"planned n_feeds {n_feeds}"
        )
    if "bwd.feed_group" not in stages:
        problems.append("telemetry missing the bwd.feed_group stage")
    # plan-accuracy ledger schema: every smoke run stamps the per-stage
    # predicted-vs-measured reconciliation, and the join must cover at
    # least 80% of the plan-priced stage wall — uncovered stages are
    # listed by name, so a timer falling out of the mapping fails HERE
    from swiftly_tpu.obs import validate_plan_accuracy_artifact

    problems.extend(validate_plan_accuracy_artifact(record))
    pa = record.get("plan_accuracy") or {}
    coverage = pa.get("coverage")
    if not isinstance(coverage, (int, float)) or coverage < 0.8:
        problems.append(
            f"plan_accuracy coverage {coverage!r} < 0.8 of plan-priced "
            f"stage wall (uncovered: {pa.get('uncovered')})"
        )
    stream_bytes = (record.get("spill") or {}).get("ram_bytes", 0) + (
        record.get("spill") or {}
    ).get("disk_bytes", 0)
    if stream_bytes and n_feeds:
        want = (n_feeds - 1) * stream_bytes
        if record.get("spill_h2d_bytes") != want:
            problems.append(
                f"spill.h2d moved {record.get('spill_h2d_bytes')} "
                f"bytes; the feed schedule promises (n_feeds-1) x "
                f"stream = {want}"
            )
    import json as _json

    with open(jsonl_path) as fh:
        jsonl_stages = {
            r["name"]
            for r in map(_json.loads, fh)
            if r.get("kind") == "stage"
        }
    if len({s for s in jsonl_stages if s.startswith(("fwd.", "bwd."))}) < 6:
        problems.append(
            f"JSONL event log has stage names {sorted(jsonl_stages)}, "
            "expected >= 6 engine stages"
        )
    if trace_path:
        problems.extend(_check_smoke_trace(record, trace_path))
    with open(out_path, "w") as fh:
        _json.dump(record, fh, indent=2)
    metrics.disable()
    print(
        json.dumps(
            {
                "smoke": "ok" if not problems else "failed",
                "config": name,
                "artifact": out_path,
                "jsonl": jsonl_path,
                "trace": trace_path,
                "n_engine_stages": len(engine_stages),
                "problems": problems,
            }
        ),
        flush=True,
    )
    return 0 if not problems else 1


def _check_smoke_trace(record, trace_path):
    """Save + validate the smoke leg's timeline: structurally valid
    Chrome trace JSON (Perfetto-loadable), a trace block whose schema
    passes `validate_trace_artifact`, a critical path rooted at
    `bench.leg` whose wall matches the measured leg wall within 5%,
    and the engine stage vocabulary present as spans."""
    from swiftly_tpu.obs import report as oreport
    from swiftly_tpu.obs import trace as otrace
    from swiftly_tpu.obs import validate_trace_artifact

    problems = list(validate_trace_artifact(record))
    otrace.save(trace_path)
    otrace.disable()
    trace = oreport.load_trace(trace_path)
    problems += [
        f"trace file: {p}" for p in oreport.validate_trace_events(trace)
    ]
    tr = record.get("trace") or {}
    wall, leg_wall = tr.get("wall_s"), tr.get("leg_wall_s")
    if not wall or not leg_wall or abs(wall - leg_wall) > 0.05 * leg_wall:
        problems.append(
            f"critical-path root wall {wall} != measured leg wall "
            f"{leg_wall} within 5%"
        )
    if (tr.get("critical_path") or [{}])[0].get("name") != "bench.leg":
        problems.append(
            f"critical path does not start at bench.leg: "
            f"{tr.get('critical_path')}"
        )
    span_names = {
        s["name"] for s in oreport.build_tree(trace).values()
    }
    want = {"bench.leg", "fwd.column_group", "bwd.sampled_fold",
            "spill.write", "spill.read"}
    if not want <= span_names:
        problems.append(
            f"trace missing engine spans {sorted(want - span_names)}"
        )
    return problems


def run_chaos_drill(config_name, fault_plan=None, fold_group=2,
                    col_group=2):
    """The kill-and-resume chaos drill (`bench.py --chaos`, also driven
    by scripts/chaos_drill.py).

    1. Run a facet-partitioned sampled streamed backward UNDISTURBED
       (pass 1 records the subgrid stream into the spill cache, pass 2
       is cache-fed) — the reference facets, computed with NO fault
       plan installed (the clean path must stay hook-free).
    2. Re-run under an injected fault schedule: transient spill-read
       and h2d/d2h transfer IOErrors (the retry layer must absorb
       them), per-group checkpoint autosave, a bit-flipped newest
       checkpoint generation (restore must fall back a generation), and
       a worker death mid-pass-2 (`WorkerKilled` tears through every
       isolation layer).
    3. RESUME: fresh backward, restore from the surviving generation,
       skip the processed groups, finish.
    4. Assert the chaos run's facets are BIT-IDENTICAL to the
       undisturbed run's, and stamp the resilience block (faults
       injected/survived, retries, degradations, resume count) into a
       BENCH-style artifact validated by `obs.validate_resilience_artifact`.

    Bit-identity holds because every fold is deterministic and the
    ledger/autosave tick lands at column-GROUP boundaries only: the
    resumed feed re-dispatches exactly the fold programs the killed run
    would have, on a CRC-verified bit-exact accumulator.
    """
    import shutil
    import tempfile

    import jax.numpy as jnp

    from swiftly_tpu import SWIFT_CONFIGS
    from swiftly_tpu.obs import metrics
    from swiftly_tpu.parallel import StreamedBackward
    from swiftly_tpu.resilience import (
        FaultPlan,
        WorkerKilled,
        degrade,
        faults,
    )
    from swiftly_tpu.utils.checkpoint import (
        checkpoint_generations,
        restore_streamed_backward_state,
    )
    from swiftly_tpu.utils.spill import SpillCache

    params = dict(SWIFT_CONFIGS[config_name])
    params.setdefault("fov", 1.0)
    config, fwd, facet_configs, subgrid_configs, _sources = _build(
        "planar", params, jnp.float32, streamed=True
    )
    # deterministic column-group count: the fault schedule is indexed by
    # site call number, so the drill pins the group size instead of
    # letting the auto-sizer pick per-host values
    fwd.col_group = col_group
    n_cols = len({sg.off0 for sg in subgrid_configs})
    n_groups = -(-n_cols // col_group)
    if n_groups < 3:
        raise ValueError(
            f"chaos drill needs >= 3 column groups for its schedule "
            f"(kill after 2 autosaves); {config_name} with "
            f"col_group={col_group} has {n_groups}"
        )
    F = len(facet_configs)
    half = max(1, F // 2)
    subsets = [(0, half), (half, F)] if F > 1 else [(0, F)]

    work_dir = tempfile.mkdtemp(prefix="chaos_drill_")
    ck_paths = [
        os.path.join(work_dir, f"ck_pass{i}.npz")
        for i in range(len(subsets))
    ]

    def feed(bwd, spill, skip=()):
        skip = set(skip)
        for per_col, group in fwd.stream_column_groups(
            subgrid_configs, spill=spill
        ):
            keys = [
                (sg.off0, sg.off1) for col in per_col for _, sg in col
            ]
            if skip and all(k in skip for k in keys):
                continue
            bwd.add_subgrid_group(
                [[sg for _, sg in col] for col in per_col], group
            )

    def run_passes(spill, autosave=False, resume=False):
        outs = []
        for idx, (i0, i1) in enumerate(subsets):
            bwd = StreamedBackward(
                config, list(facet_configs[i0:i1]),
                residency="sampled", fold_group=fold_group,
            )
            skip = ()
            if resume and checkpoint_generations(ck_paths[idx]):
                skip = restore_streamed_backward_state(
                    ck_paths[idx], bwd
                )
            if autosave:
                bwd.enable_autosave(ck_paths[idx], every_subgrids=1)
            feed(bwd, spill, skip)
            outs.append(np.asarray(bwd.finish_device()))
        return np.concatenate(outs, axis=0)

    try:
        # --- undisturbed reference (clean path: no plan installed) ----
        assert faults.current() is None
        t0 = time.time()
        spill_ref = SpillCache()
        ref = run_passes(spill_ref)
        clean_s = time.time() - t0

        # --- the fault schedule --------------------------------------
        # bwd.feed is called once per group per pass; the kill lands on
        # pass 2's third group, after two autosaved generations — so the
        # corrupted newest generation has a good predecessor to fall
        # back to.
        kill_at = n_groups + 2
        if fault_plan is None:
            fault_plan = FaultPlan(
                faults=[
                    {"site": "spill.read", "kind": "ioerror", "at": 1},
                    {"site": "transfer.d2h", "kind": "ioerror", "at": 1},
                    {"site": "transfer.h2d", "kind": "ioerror", "at": 2},
                    {"site": "checkpoint.restore", "kind": "corrupt",
                     "at": 0},
                    {"site": "bwd.feed", "kind": "kill", "at": kill_at},
                ],
                seed=int(os.environ.get("BENCH_CHAOS_SEED", "20260804")),
            )
        degrade.reset()
        counters0 = dict(
            (metrics.export().get("counters") or {})
        ) if metrics.enabled() else {}

        # --- chaos run: fault schedule + kill + resume ---------------
        t0 = time.time()
        spill_chaos = SpillCache()
        resumes = 0
        got = None
        from swiftly_tpu.obs import recorder as orecorder

        with faults.active(fault_plan):
            try:
                got = run_passes(spill_chaos, autosave=True)
            except WorkerKilled as exc:
                log.warning("chaos drill: %s; resuming from checkpoint",
                            exc)
                orecorder.record(
                    "drill", "chaos.worker_killed", str(exc)
                )
                resumes += 1
                got = run_passes(
                    spill_chaos, autosave=True, resume=True
                )
        chaos_s = time.time() - t0
        # snapshot the black box while the kill -> fallback -> resume
        # story is the recent past (the drill stamps it; --smoke
        # asserts the tail actually tells it)
        post_mortem = (
            orecorder.post_mortem(
                "WorkerKilled",
                reason=f"bwd.feed kill at call {kill_at}, "
                       f"resumed {resumes}x",
            )
            if orecorder.enabled() else None
        )

        bit_identical = bool(
            got.shape == ref.shape and np.array_equal(got, ref)
        )
        counters = dict(
            (metrics.export().get("counters") or {})
        ) if metrics.enabled() else {}

        def delta(name):
            return counters.get(name, 0) - counters0.get(name, 0)

        pstats = fault_plan.stats()
        resilience = {
            "plan": fault_plan.spec(),
            "faults_injected": pstats["by_site"],
            "faults_injected_total": pstats["total"],
            "faults_by_kind": pstats["by_kind"],
            # the drill finished and verified: every injected fault was
            # survived (retried past, degraded around, or resumed over)
            "faults_survived": pstats["total"] if bit_identical else 0,
            "retries": delta("retry.attempts"),
            "retries_recovered": delta("retry.recovered"),
            "degradations": degrade.events(),
            "resume_count": resumes,
            "checkpoint_fallbacks": delta("ckpt.fallbacks"),
            "checkpoint_autosaves": delta("ckpt.autosaves"),
            "checkpoint_saves": delta("ckpt.saves"),
            "kill_site": "bwd.feed",
            "kill_at_call": kill_at,
            "bit_identical": bit_identical,
        }
        record = {
            "metric": f"chaos-drill {config_name}",
            "value": round(chaos_s, 2),
            "unit": "s",
            "config": config_name,
            "n_subgrids": len(subgrid_configs),
            "n_groups": n_groups,
            "n_passes": len(subsets),
            "clean_run": {
                "elapsed_s": round(clean_s, 2),
                "fault_plan_installed": False,
            },
            "resilience": resilience,
            "spill": spill_chaos.stats(),
        }
        if post_mortem is not None:
            record["post_mortem"] = post_mortem
        return record
    finally:
        faults.uninstall()
        shutil.rmtree(work_dir, ignore_errors=True)


def chaos(smoke_mode=False):
    """`bench.py --chaos [--smoke]`: run the kill-and-resume chaos
    drill, stamp the resilience artifact, and validate its schema.

    ``--smoke`` runs the 1k drill (the tier-1 wiring via
    tests/test_bench_smoke.py); the full drill defaults to the 4k
    config (slow-marked in the tests). ``SWIFTLY_FAULT_PLAN`` replaces
    the built-in schedule; ``BENCH_CHAOS_CONFIG`` the config.
    """
    from swiftly_tpu.obs import (
        metrics,
        run_manifest,
        validate_resilience_artifact,
    )
    from swiftly_tpu.resilience import plan_from_env
    from swiftly_tpu.utils import enable_compilation_cache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    orecorder = _maybe_enable_recorder()
    out_path = os.environ.get("BENCH_CHAOS_OUT", "BENCH_chaos.json")
    metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    name = os.environ.get(
        "BENCH_CHAOS_CONFIG",
        "1k[1]-n512-256" if smoke_mode else "4k[1]-n2k-512",
    )
    from swiftly_tpu import SWIFT_CONFIGS

    record = run_chaos_drill(
        name,
        fault_plan=plan_from_env(),
        fold_group=int(os.environ.get("BENCH_CHAOS_FOLD_GROUP", "2")),
        col_group=int(os.environ.get("BENCH_CHAOS_COL_GROUP", "2")),
    )
    record["manifest"] = run_manifest(
        baseline_source=None, params=dict(SWIFT_CONFIGS[name])
    )
    record["telemetry"] = metrics.export()
    if trace_path:
        # a chaos-drill trace shows WHERE the run degraded: the fault
        # injections and ladder steps land as instant events among the
        # pass/group/stage spans
        from swiftly_tpu.obs import summarize_trace
        from swiftly_tpu.obs import trace as otrace

        record["trace"] = summarize_trace(otrace.export())
        otrace.save(trace_path)
        otrace.disable()
    problems = validate_resilience_artifact(record)
    res = record["resilience"]
    # the drill's own invariants, beyond the schema: the schedule must
    # actually have exercised every resilience layer
    if res["retries"] < 1 or res["retries_recovered"] < 1:
        problems.append(
            f"no transient fault was retried+recovered: {res}"
        )
    if res["checkpoint_fallbacks"] < 1:
        problems.append(
            "the corrupted checkpoint generation was never fallen "
            f"back from: {res}"
        )
    if not any(
        d["site"] == "checkpoint" for d in res["degradations"]
    ):
        problems.append(
            f"degradation trail missing the checkpoint fallback: "
            f"{res['degradations']}"
        )
    if orecorder is not None:
        pm_path = os.path.splitext(out_path)[0] + "_postmortem.jsonl"
        orecorder.dump(
            pm_path, "WorkerKilled",
            reason=record.get("post_mortem", {}).get("reason"),
        )
        if "post_mortem" in record:
            record["post_mortem"]["dump_path"] = pm_path
        # the post-mortem must TELL the drill's story: the injected
        # kill and the degradation ladder it forced
        pm_names = [
            e["name"]
            for e in record.get("post_mortem", {}).get("events", [])
        ]
        if not any(
            n.startswith("fault.injected.bwd.feed") for n in pm_names
        ):
            problems.append(
                "chaos post-mortem tail missing the injected bwd.feed "
                f"kill: {pm_names}"
            )
        if not any(n.startswith("degrade.") for n in pm_names):
            problems.append(
                "chaos post-mortem tail missing the degradation "
                f"ladder steps: {pm_names}"
            )
        if "chaos.worker_killed" not in pm_names:
            problems.append(
                "chaos post-mortem tail missing the drill's "
                f"worker-killed marker: {pm_names}"
            )
    import json as _json

    with open(out_path, "w") as fh:
        _json.dump(record, fh, indent=2)
    metrics.disable()
    print(
        json.dumps(
            {
                "chaos": "ok" if not problems else "failed",
                "config": name,
                "artifact": out_path,
                "bit_identical": res["bit_identical"],
                "faults_injected": res["faults_injected_total"],
                "resume_count": res["resume_count"],
                "recorder_events": (
                    record.get("post_mortem", {}).get("n_events", 0)
                ),
                "problems": problems,
            }
        ),
        flush=True,
    )
    return 0 if not problems else 1


def run_mesh_chaos_drill(config_name, fault_plan=None, col_group=2,
                         fold_group=2, max_cols=0):
    """The elastic mesh recovery drill (`bench.py --mesh --chaos`, also
    driven by scripts/mesh_drill.py --chaos).

    1. Run the facet-partitioned mesh-streamed round trip UNDISTURBED
       over N virtual shards (pass 1 records the subgrid stream into
       the spill cache, pass 2 is cache-fed) — the reference facets,
       with NO fault plan installed.
    2. Watchdog phase: re-run the recording briefly with an injected
       collective latency (``mesh.psum``, or ``mesh.ring_step`` when
       SWIFTLY_MESH_COLLECTIVE=ring schedules the pipeline) and a small
       ``SWIFTLY_COLLECTIVE_TIMEOUT_S`` — the stalled collective must
       surface as a caught `CollectiveStalledError` (the silent-hang
       class converted to a detected failure), then is discarded.
    3. Chaos run: fresh spill, fault schedule installed — transient
       spill-read/h2d IOErrors (retried), a ``mesh.feed`` latency
       blip, a bit-flipped newest checkpoint generation (restore must
       fall back a generation DURING migration), and one of the N
       shards killed mid-pass-2 (``mesh.shard_loss`` on a CACHE-FED
       pass — the recorded stream bytes are fixed, so recovery can be
       exact). `mesh.recovery.run_elastic_pass` walks the ladder:
       re-plan on N-1 survivors (priced by `plan.plan_mesh_layout`),
       rebuild the engines, migrate the last autosave across layouts,
       resume at the autosave group boundary.
    4. Assert the recovered facets BIT-IDENTICAL to the undisturbed
       mesh run (backward folds are shard-local per-facet — identical
       math on any layout) and stamp the ``mesh.recovery`` +
       ``resilience`` artifact blocks, including
       ``recovery_overhead`` (disturbed/undisturbed wall ratio — the
       scripts/bench_compare.py sentinel).
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from swiftly_tpu import SWIFT_CONFIGS
    from swiftly_tpu.mesh import (
        MeshStreamedBackward,
        MeshStreamedForward,
        make_facet_mesh,
        run_elastic_pass,
    )
    from swiftly_tpu.obs import metrics
    from swiftly_tpu.plan import PlanInputs, compile_plan
    from swiftly_tpu.resilience import (
        CollectiveStalledError,
        FaultPlan,
        degrade,
        faults,
    )
    from swiftly_tpu.utils.spill import SpillCache

    n_req = int(os.environ.get("BENCH_MESH_DEVICES", "8"))
    n_av = len(jax.devices())
    params = dict(SWIFT_CONFIGS[config_name])
    params.setdefault("fov", 1.0)
    config, fwd, facet_configs, subgrid_configs, _sources = _build(
        "planar", params, jnp.float32, streamed=True
    )
    if max_cols:
        # smoke budget: stream only the first `max_cols` columns — the
        # recovery mechanics (and the bit-identity contract, taken over
        # the SAME truncated set on both runs) are column-count-blind
        keep = set(sorted({sg.off0 for sg in subgrid_configs})[:max_cols])
        subgrid_configs = [
            sg for sg in subgrid_configs if sg.off0 in keep
        ]
    F = len(facet_configs)
    n_shards = min(n_req, n_av, F)
    if n_shards < 3:
        raise ValueError(
            f"mesh chaos drill needs >= 3 facet shards (one dies, >= 2 "
            f"survive a real collective); have {n_shards}"
        )
    inputs = PlanInputs.from_cover(
        config, facet_configs, subgrid_configs, n_devices=n_shards,
        real_facets=getattr(fwd, "_facets_real", False),
        fold_group=fold_group,
    )
    plan = compile_plan(inputs, mode="roundtrip-streamed")
    mesh = make_facet_mesh(n_devices=plan.mesh.facet_shards)
    facet_tasks = list(zip(facet_configs, fwd._facet_data))
    mfwd = MeshStreamedForward(
        config, facet_tasks, layout=plan.mesh, mesh=mesh
    )
    # deterministic column-group count: the fault schedule is indexed
    # by site call number (same discipline as run_chaos_drill)
    mfwd.col_group = col_group
    n_cols = len({sg.off0 for sg in subgrid_configs})
    n_groups = -(-n_cols // col_group)
    if n_groups < 3:
        raise ValueError(
            f"mesh chaos drill needs >= 3 column groups (kill after 2 "
            f"autosaves); {config_name} with col_group={col_group} has "
            f"{n_groups}"
        )
    half = max(1, F // 2)
    subsets = [(0, half), (half, F)] if F > 1 else [(0, F)]

    work_dir = tempfile.mkdtemp(prefix="mesh_chaos_")
    ck_paths = [
        os.path.join(work_dir, f"ck_pass{i}.npz")
        for i in range(len(subsets))
    ]

    def make_bwd(i0, i1, on_mesh):
        return MeshStreamedBackward(
            config, list(facet_configs[i0:i1]), mesh=on_mesh,
            fold_group=fold_group,
        )

    try:
        # --- undisturbed mesh reference (clean path, no plan) --------
        assert faults.current() is None
        t0 = time.time()
        spill_ref = SpillCache(budget_bytes=2e9)
        parts = []
        for i0, i1 in subsets:
            bwd = make_bwd(i0, i1, mesh)
            for per_col, group in mfwd.stream_column_groups(
                subgrid_configs, spill=spill_ref
            ):
                bwd.add_subgrid_group(
                    [[sg for _, sg in col] for col in per_col], group
                )
            parts.append(np.asarray(bwd.finish()))
        ref = np.concatenate(parts, axis=0)
        clean_s = time.time() - t0

        # --- watchdog phase: a stalled collective is a DETECTED loss --
        # the fault site tracks the scheduled collective: mesh.psum
        # under the default, mesh.ring_step when
        # SWIFTLY_MESH_COLLECTIVE=ring pipelines the reduction
        wd_timeout = float(
            os.environ.get("BENCH_MESH_WATCHDOG_S", "0.15")
        )
        stall_site = (
            "mesh.ring_step"
            if getattr(mfwd, "collective", "psum") == "ring"
            else "mesh.psum"
        )
        stall_plan = FaultPlan(
            faults=[
                {"site": stall_site, "kind": "latency", "at": 0,
                 "delay_s": wd_timeout * 4},
            ]
        )
        stalls_detected = 0
        prev_knob = os.environ.get("SWIFTLY_COLLECTIVE_TIMEOUT_S")
        os.environ["SWIFTLY_COLLECTIVE_TIMEOUT_S"] = str(wd_timeout)
        try:
            with faults.active(stall_plan):
                try:
                    for _pc, _g in mfwd.stream_column_groups(
                        subgrid_configs, spill=SpillCache(budget_bytes=2e9)
                    ):
                        pass  # aborted by the first group's stalled sync
                except CollectiveStalledError:
                    stalls_detected = 1
        finally:
            if prev_knob is None:
                os.environ.pop("SWIFTLY_COLLECTIVE_TIMEOUT_S", None)
            else:
                os.environ["SWIFTLY_COLLECTIVE_TIMEOUT_S"] = prev_knob

        # --- the fault schedule --------------------------------------
        # mesh.shard_loss fires once per yielded group; pass 1 (the
        # recording) burns calls 0..n_groups-1, so call n_groups+2
        # lands before pass-2's THIRD group — a CACHE-FED pass with two
        # autosaved generations behind it (the newest gets bit-flipped,
        # so generation fallback must compose with layout migration).
        kill_at = n_groups + 2
        if fault_plan is None:
            fault_plan = FaultPlan(
                faults=[
                    {"site": "spill.read", "kind": "ioerror", "at": 1},
                    {"site": "transfer.h2d", "kind": "ioerror", "at": 2},
                    {"site": "mesh.feed", "kind": "latency", "at": 0,
                     "delay_s": 0.01},
                    {"site": "checkpoint.restore", "kind": "corrupt",
                     "at": 0},
                    {"site": "mesh.shard_loss", "kind": "shard_loss",
                     "at": kill_at},
                ],
                seed=int(os.environ.get("BENCH_CHAOS_SEED", "20260804")),
            )
        degrade.reset()
        counters0 = dict(
            (metrics.export().get("counters") or {})
        ) if metrics.enabled() else {}

        # --- chaos run: elastic passes under the schedule ------------
        t0 = time.time()
        spill_chaos = SpillCache(budget_bytes=2e9)
        parts = []
        reports = []
        fwd_cur = mfwd
        with faults.active(fault_plan):
            for idx, (i0, i1) in enumerate(subsets):
                bwd = make_bwd(i0, i1, fwd_cur.mesh)
                fwd_cur, bwd, rep = run_elastic_pass(
                    fwd_cur, bwd, subgrid_configs, spill_chaos,
                    ck_paths[idx], plan_inputs=inputs,
                    max_recoveries=1,
                )
                reports.append(rep)
                parts.append(np.asarray(bwd.finish()))
        got = np.concatenate(parts, axis=0)
        chaos_s = time.time() - t0
        # snapshot the black box while the shard loss -> re-plan ->
        # migrate -> resume ladder is the recent past
        from swiftly_tpu.obs import recorder as orecorder

        post_mortem = (
            orecorder.post_mortem(
                "ShardLostError",
                reason=f"mesh.shard_loss at call {kill_at}",
            )
            if orecorder.enabled() else None
        )

        bit_identical = bool(
            got.shape == ref.shape and np.array_equal(got, ref)
        )
        counters = dict(
            (metrics.export().get("counters") or {})
        ) if metrics.enabled() else {}

        def delta(name):
            return counters.get(name, 0) - counters0.get(name, 0)

        recoveries = [i for r in reports for i in r["recoveries"]]
        last = recoveries[-1] if recoveries else {}
        recovery_block = {
            "events": len(recoveries),
            "recoveries": recoveries,
            "shards_before": int(n_shards),
            "shards_after": int(reports[-1]["shards_after"]),
            "replanned": last.get("replanned"),
            "migrated": bool(
                any(i["migrated"] for i in recoveries)
            ),
            "subgrids_migrated": int(last.get("subgrids_migrated", 0)),
            "watchdog": {
                "timeout_s": wd_timeout,
                "stalls_detected": stalls_detected,
                "stall_site": stall_site,
                "stall_plan": stall_plan.stats(),
            },
            "kill_site": "mesh.shard_loss",
            "kill_at_call": kill_at,
            "migrations": delta("ckpt.migrations"),
            "checkpoint_fallbacks": delta("ckpt.fallbacks"),
            "checkpoint_autosaves": delta("ckpt.autosaves"),
            "recovery_wall_s": round(
                sum(r["recovery_wall_s"] for r in reports), 4
            ),
            # disturbed/undisturbed wall ratio: the time-to-recover
            # sentinel scripts/bench_compare.py trends (lower = better)
            "recovery_overhead": round(chaos_s / clean_s, 4),
            "bit_identical": bit_identical,
        }
        pstats = fault_plan.stats()
        resilience = {
            "plan": fault_plan.spec(),
            "faults_injected": pstats["by_site"],
            "faults_injected_total": pstats["total"],
            "faults_by_kind": pstats["by_kind"],
            "faults_survived": pstats["total"] if bit_identical else 0,
            "retries": delta("retry.attempts"),
            "retries_recovered": delta("retry.recovered"),
            "degradations": degrade.events(),
            "resume_count": len(recoveries),
            "checkpoint_fallbacks": delta("ckpt.fallbacks"),
            "checkpoint_autosaves": delta("ckpt.autosaves"),
            "checkpoint_saves": delta("ckpt.saves"),
            "kill_site": "mesh.shard_loss",
            "kill_at_call": kill_at,
            "bit_identical": bit_identical,
        }
        mesh_block = {
            "n_devices": int(n_av),
            "facet_shards": int(n_shards),
            "n_facets": F,
            "padded_facets": int(mfwd.stack.n_total),
            "collective_bytes": int(plan.mesh.collective_bytes_total),
            "clean_wall_s": round(clean_s, 4),
            "chaos_wall_s": round(chaos_s, 4),
            # the chaos drill's match audit IS the bit-identity
            # contract: zero tolerance, the recovered stream must equal
            # the undisturbed mesh run byte for byte
            "match": {
                "max_abs_diff": float(np.max(np.abs(got - ref))),
                "tolerance": 0.0,
                "within_tolerance": bit_identical,
                "bit_identical": bit_identical,
            },
            "spill": spill_chaos.stats(),
            "recovery": recovery_block,
        }
        platform = jax.devices()[0].platform
        record = {
            "metric": f"{config_name} mesh chaos drill wall-clock "
                      f"({n_shards} shards kill one mid-stream, "
                      f"planar f32, mesh-chaos, {platform})",
            "value": round(chaos_s, 2),
            "unit": "s",
            "config": config_name,
            "n_subgrids": len(subgrid_configs),
            "n_groups": n_groups,
            "n_passes": len(subsets),
            "clean_run": {
                "elapsed_s": round(clean_s, 2),
                "fault_plan_installed": False,
            },
            "mesh": mesh_block,
            "resilience": resilience,
            "plan_compiled": plan.artifact_block(
                measured_wall_s=chaos_s
            ),
        }
        if post_mortem is not None:
            record["post_mortem"] = post_mortem
        return record
    finally:
        faults.uninstall()
        shutil.rmtree(work_dir, ignore_errors=True)


def mesh_chaos(smoke_mode=False):
    """`bench.py --mesh --chaos [--smoke]`: the elastic mesh recovery
    drill — kill one of N virtual shards mid-stream, re-plan the layout
    on the survivors, migrate the checkpoint across layouts, resume,
    and validate the stamped ``mesh.recovery`` + ``resilience`` blocks.

    ``--smoke`` runs the 1k drill (tier-1 wiring via
    tests/test_bench_smoke.py); the full drill defaults to the 4k
    config (slow-marked in the tests). ``SWIFTLY_FAULT_PLAN`` replaces
    the built-in schedule; ``BENCH_MESH_CHAOS_CONFIG`` the config;
    ``BENCH_MESH_DEVICES`` the shard count.
    """
    from swiftly_tpu.utils import enable_compilation_cache

    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    n_req = int(os.environ.get("BENCH_MESH_DEVICES", "8"))
    n_av = _ensure_mesh_devices(n_req)  # before any other jax use
    key = "mesh_chaos_smoke" if smoke_mode else "mesh_chaos"
    if n_av < 3:
        print(
            json.dumps(
                {
                    key: "failed",
                    "problems": [
                        f"mesh chaos drill needs >= 3 devices, found "
                        f"{n_av}; on CPU set XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8"
                    ],
                }
            ),
            flush=True,
        )
        return 1
    from swiftly_tpu.obs import (
        metrics,
        run_manifest,
        validate_mesh_artifact,
        validate_plan_artifact,
        validate_resilience_artifact,
    )
    from swiftly_tpu.resilience import plan_from_env

    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    orecorder = _maybe_enable_recorder()
    out_path = os.environ.get(
        "BENCH_MESH_CHAOS_OUT", "BENCH_mesh_chaos.json"
    )
    metrics.enable(os.environ.get("SWIFTLY_METRICS_JSONL") or None)
    name = os.environ.get(
        "BENCH_MESH_CHAOS_CONFIG",
        "1k[1]-n512-256" if smoke_mode else "4k[1]-n2k-512",
    )
    from swiftly_tpu import SWIFT_CONFIGS

    record = run_mesh_chaos_drill(
        name,
        fault_plan=plan_from_env(),
        col_group=int(
            os.environ.get(
                "BENCH_CHAOS_COL_GROUP", "1" if smoke_mode else "2"
            )
        ),
        fold_group=int(os.environ.get("BENCH_CHAOS_FOLD_GROUP", "2")),
        max_cols=int(
            os.environ.get(
                "BENCH_MESH_CHAOS_COLS", "3" if smoke_mode else "0"
            )
        ),
    )
    record["manifest"] = run_manifest(
        baseline_source=None, params=dict(SWIFT_CONFIGS[name])
    )
    record["telemetry"] = metrics.export()
    if record.get("plan_compiled"):
        _stamp_plan_accuracy(
            record,
            dump_path=os.path.splitext(out_path)[0]
            + "_plan_postmortem.jsonl",
        )
    if trace_path:
        from swiftly_tpu.obs import summarize_trace
        from swiftly_tpu.obs import trace as otrace

        record["trace"] = summarize_trace(otrace.export())
        otrace.save(trace_path)
        otrace.disable()
    problems = validate_mesh_artifact(record)
    problems.extend(validate_resilience_artifact(record))
    problems.extend(validate_plan_artifact(record))
    rec = record["mesh"]["recovery"]
    # the drill's own invariants, beyond the schema: every rung of the
    # elastic ladder must actually have been walked
    if rec["watchdog"]["stalls_detected"] < 1:
        problems.append(
            "the stalled collective was never detected by the "
            f"watchdog: {rec['watchdog']}"
        )
    if rec["checkpoint_fallbacks"] < 1:
        problems.append(
            "the corrupted checkpoint generation was never fallen "
            "back from during migration (fallback must compose with "
            f"layout migration): {rec}"
        )
    if rec["migrations"] < 1:
        problems.append(
            f"no checkpoint crossed a layout boundary: {rec}"
        )
    res = record["resilience"]
    if res["retries"] < 1 or res["retries_recovered"] < 1:
        problems.append(
            f"no transient fault was retried+recovered: {res}"
        )
    if orecorder is not None:
        pm_path = os.path.splitext(out_path)[0] + "_postmortem.jsonl"
        orecorder.dump(
            pm_path, "ShardLostError",
            reason=record.get("post_mortem", {}).get("reason"),
        )
        if "post_mortem" in record:
            record["post_mortem"]["dump_path"] = pm_path
        # the post-mortem must tell the elastic ladder's story: the
        # injected shard loss and every recovery rung behind it
        pm_names = [
            e["name"]
            for e in record.get("post_mortem", {}).get("events", [])
        ]
        if not any(
            n.startswith("fault.injected.mesh.shard_loss")
            for n in pm_names
        ):
            problems.append(
                "mesh post-mortem tail missing the injected "
                f"shard loss: {pm_names}"
            )
        for step in ("mesh.recovery.detected", "mesh.recovery.replanned",
                     "mesh.recovery.resumed"):
            if step not in pm_names:
                problems.append(
                    f"mesh post-mortem tail missing the {step} "
                    f"ladder step: {pm_names}"
                )
    import json as _json

    with open(out_path, "w") as fh:
        _json.dump(record, fh, indent=2)
    metrics.disable()
    print(
        json.dumps(
            {
                key: "ok" if not problems else "failed",
                "config": name,
                "artifact": out_path,
                "bit_identical": rec["bit_identical"],
                "shards": (
                    f"{rec['shards_before']}->{rec['shards_after']}"
                ),
                "recovery_overhead": rec["recovery_overhead"],
                "stalls_detected": rec["watchdog"]["stalls_detected"],
                "recorder_events": (
                    record.get("post_mortem", {}).get("n_events", 0)
                ),
                "problems": problems,
            }
        ),
        flush=True,
    )
    return 0 if not problems else 1


def main():
    import signal

    from swiftly_tpu.obs import PartialArtifactWriter
    from swiftly_tpu.utils import enable_compilation_cache

    if "--vis" in sys.argv:
        sys.exit(vis_bench(smoke_mode="--smoke" in sys.argv))
    if "--serve" in sys.argv:
        sys.exit(serve_bench(smoke_mode="--smoke" in sys.argv))
    if "--procfleet" in sys.argv:
        sys.exit(procfleet_bench(smoke_mode="--smoke" in sys.argv))
    if "--fleet" in sys.argv:
        sys.exit(fleet_bench(smoke_mode="--smoke" in sys.argv))
    if "--mesh" in sys.argv and "--chaos" in sys.argv:
        sys.exit(mesh_chaos(smoke_mode="--smoke" in sys.argv))
    if "--chaos" in sys.argv:
        sys.exit(chaos(smoke_mode="--smoke" in sys.argv))
    if "--mesh" in sys.argv:
        sys.exit(mesh_bench(smoke_mode="--smoke" in sys.argv))
    if "--precision-child" in sys.argv:
        sys.exit(precision_child())
    if "--precision" in sys.argv:
        sys.exit(precision_bench(smoke_mode="--smoke" in sys.argv))
    if "--delta" in sys.argv:
        sys.exit(delta_bench(smoke_mode="--smoke" in sys.argv))
    if "--smoke" in sys.argv:
        sys.exit(smoke())

    # progress visibility for the hour-scale configs: BENCH_LOGLEVEL=INFO
    # streams per-phase and per-sweep lines to stderr
    logging.basicConfig(
        level=os.environ.get("BENCH_LOGLEVEL", "WARNING"),
        format="%(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    enable_compilation_cache()
    trace_path = _maybe_enable_trace()
    # incremental per-leg flush: a killed run (BENCH_r05 died at rc=124)
    # still leaves every FINISHED leg's full record on disk, plus a
    # "started" marker naming the leg it died in. BENCH_PARTIAL_PATH=""
    # disables.
    partial = PartialArtifactWriter(
        os.environ.get("BENCH_PARTIAL_PATH", "BENCH_partial.jsonl")
    )

    legacy = os.environ.get("BENCH_CONFIG")
    if legacy:
        entries = [(legacy, os.environ.get("BENCH_MODE", "batched"))]
    else:
        # Default legs sized for the 870 s driver window (BENCH_r05 ran
        # the old 8-leg list incl. two 64k legs and died at rc=124 with
        # nothing on stdout): smoke-scale 1k round trip, the 4k fused
        # legs, 32k streamed + sparse, and the 32k round trip as the
        # headline. The 64k/128k flagship legs run via an explicit
        # BENCH_CONFIGS with a matching BENCH_TIME_BUDGET_S.
        spec = os.environ.get(
            "BENCH_CONFIGS",
            "1k[1]-n512-256:roundtrip-streamed,"
            "4k[1]-n2k-512:batched,4k[1]-n2k-512:roundtrip,"
            "32k[1]-n16k-512:streamed,"
            "32k[1]-n16k-512:streamed-sparse,"
            "32k[1]-n16k-512:roundtrip-streamed",
        )
        entries = []
        for item in spec.split(","):
            name, _, mode = item.strip().partition(":")
            entries.append((name, mode or "batched"))

    # The LAST listed entry is the headline metric — but it RUNS FIRST so
    # a slow or failing earlier config can never starve it of the driver
    # window (BENCH_r03 died with the headline unmeasured), and its line
    # is re-printed at the end so the headline is the last stdout line.
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_TIME_BUDGET_S", "870"))
    state = {"headline_line": None}

    def _on_term(signum, frame):  # pragma: no cover - signal path
        # driver timeout: make the headline (if measured) the last line
        if state["headline_line"]:
            print(state["headline_line"], flush=True)
            os._exit(0)
        os._exit(1)

    signal.signal(signal.SIGTERM, _on_term)

    order = [len(entries) - 1] + list(range(len(entries) - 1))
    ok = {}
    for pos in order:
        name, mode = entries[pos]
        is_headline = pos == len(entries) - 1
        elapsed = time.time() - t_start
        # Two skip rules for non-headline legs: the old high-water mark
        # (elapsed > 0.75 * budget), and a PROJECTED overrun — starting
        # a leg whose size-class cost guess does not fit the remaining
        # window is how BENCH_r05 overran 870 s with legs already in
        # hand. A guess can only skip, never kill: headline runs first
        # and unconditionally.
        skip_reason = None
        if budget_s and not is_headline:
            if elapsed > 0.75 * budget_s:
                skip_reason = "time budget"
            elif elapsed + _leg_cost_guess_s(name, mode) > 0.95 * budget_s:
                skip_reason = "time budget (projected leg cost)"
        if skip_reason:
            skip_record = {
                "metric": f"{name} ({mode})",
                "skipped": skip_reason,
                "elapsed_s": round(elapsed, 1),
            }
            print(json.dumps(skip_record), flush=True)
            partial.append(skip_record)
            continue
        partial.append(
            {"leg": name, "mode": mode, "status": "started",
             "elapsed_s": round(elapsed, 1)}
        )
        try:
            record = run_one(name, mode)
            line = json.dumps(record)
            print(line, flush=True)
            partial.append(record)
            if is_headline:
                state["headline_line"] = line
            ok[pos] = True
        except Exception:  # pragma: no cover - report and move on
            ok[pos] = False
            traceback.print_exc(file=sys.stderr)
            fail_record = {"metric": f"{name} ({mode})", "error": "failed"}
            print(json.dumps(fail_record), flush=True)
            partial.append(fail_record)
    if trace_path:
        from swiftly_tpu.obs import trace as otrace

        otrace.save(trace_path)
    if state["headline_line"]:
        print(state["headline_line"], flush=True)
    sys.exit(0 if ok.get(len(entries) - 1) else 1)


if __name__ == "__main__":
    main()
