"""Benchmark: streaming facet->subgrid forward transform throughput.

Runs the full forward pass (every subgrid of the cover) for a catalogue
configuration on the available accelerator with the TPU-native planar
backend, checks RMS vs the direct-DFT oracle on sample subgrids, and
compares wall-clock against the numpy reference backend (same machine,
sample-extrapolated).

Prints ONE JSON line:
  {"metric": ..., "value": <seconds>, "unit": "s",
   "vs_baseline": <numpy_time / this_time>, ...extras}

Environment knobs:
  BENCH_CONFIG   catalogue key (default "4k[1]-n2k-512")
  BENCH_BASELINE_SAMPLES  numpy subgrids to time for the baseline (default 3)
"""

import json
import os
import time

import numpy as np


def _build(backend, params, dtype=None):
    from swiftly_tpu import (
        SwiftlyConfig,
        SwiftlyForward,
        make_full_facet_cover,
        make_full_subgrid_cover,
        make_facet,
    )

    config = SwiftlyConfig(backend=backend, dtype=dtype, **params)
    facet_configs = make_full_facet_cover(config)
    subgrid_configs = make_full_subgrid_cover(config)
    sources = [(1.0, 1, 0)]
    facet_tasks = [
        (fc, make_facet(config.image_size, fc, sources))
        for fc in facet_configs
    ]
    fwd = SwiftlyForward(config, facet_tasks, lru_forward=2, queue_size=64)
    return config, fwd, subgrid_configs, sources


def main():
    import jax

    from swiftly_tpu import SWIFT_CONFIGS, check_subgrid

    config_name = os.environ.get("BENCH_CONFIG", "4k[1]-n2k-512")
    n_baseline = int(os.environ.get("BENCH_BASELINE_SAMPLES", "3"))
    params = dict(SWIFT_CONFIGS[config_name])
    params.setdefault("fov", 1.0)

    platform = jax.devices()[0].platform
    dtype = jax.numpy.float32

    # --- accelerated run (planar backend) --------------------------------
    config, fwd, subgrid_configs, sources = _build("planar", params, dtype)

    # Warmup: compile + run the fused whole-cover program once
    jax.block_until_ready(fwd.all_subgrids(subgrid_configs))

    # Timed: ONE dispatch (fused scan over columns), ONE host sync — the
    # transform's real device wall-clock, not per-subgrid tunnel latency.
    t0 = time.time()
    results = fwd.all_subgrids(subgrid_configs)
    jax.block_until_ready(results)
    elapsed = time.time() - t0

    # RMS vs oracle on a few sample subgrids
    rms = max(
        check_subgrid(
            config.image_size, sg, config.core.as_complex(results[i]), sources
        )
        for i, sg in list(enumerate(subgrid_configs))[:: max(1, len(subgrid_configs) // 4)]
    )

    # --- numpy reference baseline (sample-extrapolated) ------------------
    # Warm one subgrid first so the one-time facet preparation is excluded
    # from the per-subgrid sample, exactly as the planar run's warmup does.
    _, fwd_np, sg_np, _ = _build("numpy", params)
    fwd_np.get_subgrid_task(sg_np[0])
    t0 = time.time()
    for sg in sg_np[1 : 1 + n_baseline]:
        fwd_np.get_subgrid_task(sg)
    numpy_total = (time.time() - t0) / n_baseline * len(sg_np)

    print(
        json.dumps(
            {
                "metric": f"{config_name} forward facet->subgrid wall-clock "
                          f"({len(subgrid_configs)} subgrids, planar f32, "
                          f"{platform})",
                "value": round(elapsed, 4),
                "unit": "s",
                "vs_baseline": round(numpy_total / elapsed, 2),
                "rms_vs_dft_oracle": float(f"{rms:.3e}"),
                "numpy_baseline_s": round(numpy_total, 2),
                "n_subgrids": len(subgrid_configs),
            }
        )
    )


if __name__ == "__main__":
    main()
