# Runtime image for swiftly-tpu (parity: reference Dockerfile, two-stage
# python slim). The default image targets CPU execution (tests, small
# configs); for TPU VMs install jax[tpu] instead of jax.

FROM python:3.11-slim AS build

WORKDIR /app
COPY pyproject.toml ./
COPY swiftly_tpu ./swiftly_tpu
COPY scripts ./scripts
COPY bench.py ./
RUN pip install --no-cache-dir --prefix=/install .

FROM python:3.11-slim

COPY --from=build /install /usr/local
COPY scripts /app/scripts
COPY bench.py /app/bench.py
WORKDIR /app

# CPU-mesh defaults so multi-device code paths work out of the box
ENV JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8

ENTRYPOINT ["python", "scripts/demo_api.py"]
CMD ["--swift_config", "1k[1]-n512-256"]
