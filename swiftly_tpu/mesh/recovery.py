"""Elastic mesh recovery: survive device loss mid-stream.

A lost shard on an N-device mesh is the one failure the PR-4 resilience
ladder could not absorb: retries cannot bring a device back, checkpoint
generations all describe the DEAD layout, and the stream has nowhere to
resume onto. The paper's Dask original gets this for free from its
scheduler (DaggerFFT, arXiv 2601.12209, re-schedules a lost worker's
tasks); a TPU-native static-layout stack has to rebuild the property —
the wafer-scale slide-FFT work (arXiv 2401.05427) makes the argument
that layouts must be RE-DERIVABLE after topology change, not pinned.

This module is the new rung of the degradation ladder::

    shard lost (ShardLostError — injected, or a watchdog-detected
                stalled collective)
      → re-PLAN the layout on the survivors
        (`plan.plan_mesh_layout` on ``inputs.replace(n_devices=k)`` —
        the shrunk layout is priced by the same cost model that chose
        the original, not guessed)
      → REBUILD the engines on a survivor mesh
        (`MeshStreamedForward/Backward.rebuild_on`: same config, same
        facets, new fabric)
      → MIGRATE the last autosave across layouts
        (`utils.checkpoint.restore_streamed_backward_state` gathers the
        saved facet stacks, re-pads them for the survivor shard count
        and re-places — `ckpt.migrations`)
      → RESUME the column stream at the last autosave group boundary
        (processed groups skipped, the spill cache re-feeds the rest).

Bit-identity contract: the backward's folds and finishes are
shard-local per-facet math (byte-identical on ANY layout — only the
forward column psum's reduction order depends on the shard count), and
the resumed feed replays CACHED subgrid bytes fixed by the original
recording. So a loss during a cache-fed pass recovers to a result
bit-identical to the undisturbed run — the same contract the PR-4
kill-and-resume drill pins on one chip, now across a layout change.
``bench.py --mesh --chaos`` asserts exactly this.

Everything is observable: ``mesh.recovery.*`` counters, trace instants
at detection/re-plan/resume, and a `report` dict shaped for the
``mesh.recovery`` artifact block (`obs.validate_mesh_artifact`).
"""

from __future__ import annotations

import logging
import time

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace
from ..parallel.mesh import make_facet_mesh, mesh_size
from ..resilience import degrade as _degrade
from ..resilience.faults import ShardLostError
from ..resilience.watchdog import collective_timeout_s
from ..utils.checkpoint import (
    checkpoint_generations,
    restore_streamed_backward_state,
)

__all__ = [
    "recover_engines",
    "run_elastic_pass",
    "survivor_mesh",
]

logger = logging.getLogger(__name__)


def survivor_mesh(mesh, lost_shard=None):
    """(mesh', lost) — a fresh 1-D facet mesh over the survivors of
    losing one shard of `mesh`.

    :param lost_shard: index of the dead shard; default the LAST shard
        (deterministic for drills — a real detector would pass the
        shard whose collective stalled).
    """
    devices = list(mesh.devices.flat)
    lost = len(devices) - 1 if lost_shard is None else int(lost_shard)
    if not 0 <= lost < len(devices):
        raise ValueError(
            f"lost_shard {lost} out of range for a "
            f"{len(devices)}-device mesh"
        )
    survivors = [d for i, d in enumerate(devices) if i != lost]
    if not survivors:
        raise ShardLostError(
            "no surviving devices to re-plan onto", shard=lost
        )
    return make_facet_mesh(devices=survivors), lost


def recover_engines(forward, backward, plan_inputs=None,
                    mode="roundtrip-streamed", lost_shard=None,
                    ckpt_path=None):
    """One recovery step: re-plan, rebuild, migrate. Returns
    ``(forward', backward', processed, info)``.

    The original engines are left untouched (their mesh may contain the
    dead device; nothing is torn down through it). ``processed`` is the
    migrated checkpoint's (off0, off1) ledger — the groups the resumed
    feed skips — or ``()`` when no checkpoint generation exists (the
    loss landed before the first autosave: recovery degrades to a full
    re-run on the survivor layout, still exact).

    :param plan_inputs: the `plan.PlanInputs` the original layout was
        compiled from; when given, the survivor layout comes from
        `plan.plan_mesh_layout` on ``replace(n_devices=survivors)`` —
        priced by the cost model — and is bound by the rebuilt engines.
    """
    t0 = time.monotonic()
    before = mesh_size(forward.mesh)
    _metrics.count("mesh.recovery.events")
    _trace.instant(
        "mesh.recovery.detected", cat="fault",
        shards=before, lost_shard=lost_shard,
    )
    _recorder.record("mesh", "mesh.recovery.detected",
                     f"{before} shard(s), lost {lost_shard}")
    mesh, lost = survivor_mesh(forward.mesh, lost_shard)
    layout = None
    if plan_inputs is not None:
        from ..plan import plan_mesh_layout

        layout = plan_mesh_layout(
            plan_inputs.replace(n_devices=mesh_size(mesh)), mode
        )
        _metrics.count("mesh.recovery.replans")
    _trace.instant(
        "mesh.recovery.replanned", cat="fault",
        shards=mesh_size(mesh),
        facet_shards=(layout.facet_shards if layout else None),
    )
    _recorder.record("mesh", "mesh.recovery.replanned",
                     f"{before} -> {mesh_size(mesh)} shard(s)")
    new_fwd = forward.rebuild_on(mesh, layout)
    new_bwd = backward.rebuild_on(mesh, layout)
    processed = ()
    migrated = False
    if ckpt_path and checkpoint_generations(ckpt_path):
        # cross-layout restore: checkpoint.py gathers the saved facet
        # stacks, re-pads for the survivor shard count and re-places
        processed = restore_streamed_backward_state(ckpt_path, new_bwd)
        migrated = True
    wall = time.monotonic() - t0
    _degrade.record(
        "mesh", "replan_survivors",
        f"shard {lost} lost; re-planned {before} -> {mesh_size(mesh)} "
        f"shard(s), {len(processed)} subgrid(s) migrated",
    )
    _trace.instant(
        "mesh.recovery.resumed", cat="fault",
        shards=mesh_size(mesh), skipped=len(processed),
        recovery_wall_s=wall,
    )
    _recorder.record("mesh", "mesh.recovery.resumed",
                     f"{len(processed)} subgrid(s) migrated, "
                     f"{wall:.3f}s")
    logger.warning(
        "mesh recovery: shard %s lost; re-planned %d -> %d shard(s) "
        "in %.3fs (%d subgrid(s) already folded)",
        lost, before, mesh_size(mesh), wall, len(processed),
    )
    info = {
        "shards_before": int(before),
        "shards_after": int(mesh_size(mesh)),
        "lost_shard": int(lost),
        "replanned": layout.as_dict() if layout is not None else None,
        "migrated": migrated,
        "subgrids_migrated": len(processed),
        "recovery_wall_s": wall,
    }
    return new_fwd, new_bwd, processed, info


def run_elastic_pass(forward, backward, subgrid_configs, spill,
                     ckpt_path, plan_inputs=None,
                     mode="roundtrip-streamed", autosave_every=1,
                     max_recoveries=1):
    """Feed the column stream into `backward`, surviving shard loss.

    Streams `forward.stream_column_groups(subgrid_configs, spill=...)`
    into ``backward.add_subgrid_group`` with per-group autosave to
    `ckpt_path`. A `ShardLostError` anywhere in the loop (an injected
    ``mesh.shard_loss``/``mesh.feed`` fault, or the watchdog's
    `CollectiveStalledError` from a stalled ``mesh.psum`` or
    ``mesh.ring_step``) triggers `recover_engines`; the pass resumes on
    the rebuilt engines at the last autosave boundary, skipping
    fully-processed groups — the same skip discipline as the PR-4
    kill-and-resume drill. The rebuilt layout re-resolves the
    collective for the survivor shard count (a 2-shard survivor ring is
    a different pipeline than the 8-shard original).

    Returns ``(forward', backward', report)``: the (possibly rebuilt)
    engines — the backward with the pass fully folded in (callers
    ``finish()`` it), the forward to drive any LATER passes on the
    surviving fabric — and the ``mesh.recovery``-shaped report dict::

        {"events": int, "recoveries": [info, ...],
         "watchdog": {"timeout_s": float|None},
         "shards_before": int, "shards_after": int,
         "recovery_wall_s": float}

    At most `max_recoveries` losses are absorbed; one more re-raises
    (a mesh losing shards faster than it can re-plan is an outage, not
    a degradation).
    """
    fwd, bwd = forward, backward
    shards0 = mesh_size(fwd.mesh)
    bwd.enable_autosave(ckpt_path, every_subgrids=autosave_every)
    skip = set()
    recoveries = []
    while True:
        try:
            for per_col, group in fwd.stream_column_groups(
                subgrid_configs, spill=spill
            ):
                keys = [
                    (sg.off0, sg.off1) for col in per_col for _, sg in col
                ]
                if skip and all(k in skip for k in keys):
                    continue
                bwd.add_subgrid_group(
                    [[sg for _, sg in col] for col in per_col], group
                )
            break
        except ShardLostError as exc:
            if len(recoveries) >= max_recoveries:
                raise
            logger.warning(
                "mesh pass: %s; walking the recovery ladder", exc
            )
            fwd, bwd, processed, info = recover_engines(
                fwd, bwd,
                plan_inputs=plan_inputs, mode=mode,
                lost_shard=getattr(exc, "shard", None),
                ckpt_path=ckpt_path,
            )
            info["detected_via"] = type(exc).__name__
            recoveries.append(info)
            skip = set(map(tuple, processed))
            bwd.enable_autosave(ckpt_path, every_subgrids=autosave_every)
    report = {
        "events": len(recoveries),
        "recoveries": recoveries,
        "watchdog": {"timeout_s": collective_timeout_s()},
        "shards_before": int(shards0),
        "shards_after": int(mesh_size(bwd.mesh)),
        "recovery_wall_s": float(
            sum(r["recovery_wall_s"] for r in recoveries)
        ),
    }
    return fwd, bwd, report
