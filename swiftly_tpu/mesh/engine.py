"""Mesh-streamed execution engine: the streamed pipeline SPMD over a
`jax.sharding.Mesh` (ROADMAP item 1 — the multi-chip arc).

`parallel.streamed` carries a facet-sharded shard_map variant of every
stage body (`*_sharded`: facet pass, column pass, sampled/ct/fft folds,
finishes), but until now only the isolated kernels in `parallel.sharded`
and the whole-cover batched paths consumed a mesh — the 64k/128k
streamed engines ran on one chip. This module is the binding layer that
turns those pieces into a mesh-streamed ENGINE:

* `MeshStreamedForward` / `MeshStreamedBackward` mirror the
  `StreamedForward` / `StreamedBackward` API exactly
  (`stream_column_groups`, spill feed, `add_subgrid_group`, row slabs,
  autosave) — they ARE the streamed executors, constructed over a config
  whose facet stacks are laid out via `parallel.mesh.facet_sharding`.
  Per-column partial sums reduce with ONE `lax.psum` over the facet axis
  inside the jitted column-pass body (the streamed pipeline's only
  collective; every facet-side op — sampled facet pass, backward column
  pass, folds, finishes — is shard-local). The facet stack is
  zero-padded to a multiple of the mesh size (`pad_to_shards`; padded
  facets carry zero masks and contribute exact zeros).
* The engine binds the plan compiler's `MeshLayout`
  (`plan.compiler.MeshLayout`, a ``status: "stub"`` field since PR 7):
  pass ``layout=plan.mesh`` and the engine validates the shard count,
  records the executed padding, and flips ``status`` to ``"bound"`` —
  the artifact then shows which executor consumed the layout.
* d2h/spill traffic reads only ADDRESSABLE shards (`host_replica` /
  `host_gather`): on a multi-host pod slice each process pulls its own
  shards (or any one replica of a replicated output) instead of
  addressing devices it cannot reach.
* The multi-chip backward consumes the SAME feed-once/fold-many
  schedule as the single-chip engine: the engines speak the streamed
  API, so `parallel.streamed.feed_backward_passes` drives shared feeds
  over `MeshStreamedForward`/`MeshStreamedBackward` unchanged (the
  plan's ``backward.feed_group`` sizes the chunk; ``bench.py --mesh``
  routes both its single-chip reference and the mesh run through it).
* Elastic recovery surface: the engines carry the mesh-path fault
  sites (``mesh.psum`` / ``mesh.ring_step`` — whichever schedule the
  column-group sync is draining — on the host sync downstream of the
  column collective, watchdog-wrapped when
  ``SWIFTLY_COLLECTIVE_TIMEOUT_S`` is set, so a stalled collective
  raises instead of hanging; ``mesh.shard_loss`` once per yielded
  forward group; ``mesh.feed`` per backward group feed) and a
  ``rebuild_on(mesh, layout)`` hook that re-constructs the same engine
  on a SURVIVOR mesh — `mesh.recovery` drives detect → re-plan →
  migrate → resume over these (docs/resilience.md), with the ring
  schedule re-resolved for the survivor shard count on rebuild.
* The collective schedule itself is selectable:
  ``SWIFTLY_MESH_COLLECTIVE={psum,ring,auto}`` picks between the
  blocking per-group `lax.psum` and the `ppermute` ring
  (`parallel.sharded.ring_allreduce`) whose chunk rotations hide
  behind the next group's shard-local contraction and h2d staging
  fill (docs/multichip.md "Collective schedules").

Exactness contract: per-facet math is byte-identical to the single-chip
engine (the shard_map bodies are built from the same ``*_fn`` builders);
only the forward column pass's facet-sum REDUCTION ORDER differs (local
scan per shard + psum vs one scan over all facets), so mesh and
single-chip results agree to reduction-order tolerance, which
``bench.py --mesh`` asserts and stamps (docs/multichip.md). That
contract covers the column-pass BODY choice too: `resolve_colpass`
(einsum / fused Pallas / fft, SWIFTLY_COLPASS) resolves inside the
shared builders with the shard-LOCAL facet count, so under the mesh the
fused Pallas kernel is the same one grid program per shard — it reduces
the shard's local facets in-kernel (its K loop runs over local F only)
and the per-column `lax.psum` over the facet axis stays the engine's
single collective, exactly as in the einsum body.

The pattern is exactly the contraction-over-mesh shape of "Large-Scale
Discrete Fourier Transform on TPUs" (arXiv 2002.03260) and "Distributed
Linear Algebra with TPUs" (arXiv 2112.09017): shard the summed axis,
reduce locally, one ICI collective per contraction.
"""

from __future__ import annotations

import copy
import logging

import numpy as np

from ..obs import metrics as _metrics
from ..parallel.mesh import (
    FACET_AXIS,
    facet_sharding,
    make_facet_mesh,
    mesh_size,
    pad_to_shards,
    resolve_collective,
)
from ..parallel.streamed import StreamedBackward, StreamedForward
from ..resilience.faults import fault_point as _fault_point
from ..resilience.retry import retry_transient as _retry
from ..resilience.watchdog import watch_collective as _watch

__all__ = [
    "MeshStreamedBackward",
    "MeshStreamedForward",
    "attach_mesh",
    "host_gather",
    "host_replica",
    "resolve_facet_shards",
]

logger = logging.getLogger(__name__)


def resolve_facet_shards(n_facets, n_devices=None):
    """Facet-shard count for a cover: every available device, capped at
    the facet count (a shard with no real facet would hold only
    zero-padding — exact, but pure waste)."""
    import jax

    if n_devices is None:
        n_devices = len(jax.devices())
    return max(1, min(int(n_devices), int(n_facets)))


def attach_mesh(swiftly_config, mesh):
    """A shallow copy of ``swiftly_config`` with ``mesh`` attached.

    The copy shares the numerical core (no PSWF rebuild); only the
    execution-fabric field differs — the caller's config object is
    never mutated."""
    if swiftly_config.core.backend in ("numpy", "native"):
        raise ValueError(
            "a device mesh requires the 'jax' or 'planar' backend"
        )
    cfg = copy.copy(swiftly_config)
    cfg.mesh = mesh
    return cfg


def host_replica(arr):
    """One host copy of a REPLICATED mesh array, reading only
    addressable shards.

    Single-process (all shards addressable): a plain ``np.asarray``.
    Multi-host: every device holds the full replicated value, so the
    first ADDRESSABLE shard's data is the whole array — no cross-host
    pull ever happens."""
    if not hasattr(arr, "addressable_shards"):
        return np.asarray(arr)
    import jax

    if jax.process_count() == 1:
        return np.asarray(arr)
    return np.asarray(arr.addressable_shards[0].data)


def host_gather(arr):
    """Host copy of a (possibly facet-SHARDED) mesh array from its
    addressable shards only.

    Single-process: ``np.asarray``. Multi-host: each process fills the
    global-shaped output at its addressable shards' indices and leaves
    the rows it cannot address ZERO — the per-process view of a sharded
    result (processes own disjoint facet rows; a global gather would be
    a cross-host transfer the engine deliberately never performs —
    docs/multichip.md)."""
    if not hasattr(arr, "addressable_shards"):
        return np.asarray(arr)
    import jax

    if jax.process_count() == 1 or getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    out = np.zeros(arr.shape, dtype=arr.dtype)
    for shard in arr.addressable_shards:
        out[shard.index] = np.asarray(shard.data)
    return out


def _resolve_mesh(swiftly_config, n_facets, layout, mesh, n_devices):
    """(mesh, layout) for an engine: an explicit mesh wins, else the
    layout's shard count, else the config's own mesh, else every device
    (capped at the facet count). A layout, when given, must agree with
    the mesh it is bound to."""
    if mesh is None:
        mesh = getattr(swiftly_config, "mesh", None)
    if mesh is None:
        shards = (
            int(layout.facet_shards)
            if layout is not None
            else resolve_facet_shards(n_facets, n_devices)
        )
        mesh = make_facet_mesh(n_devices=shards)
    if layout is not None and int(layout.facet_shards) != mesh_size(mesh):
        raise ValueError(
            f"MeshLayout plans {layout.facet_shards} facet shard(s) but "
            f"the mesh has {mesh_size(mesh)} device(s); compile the plan "
            f"with n_devices={mesh_size(mesh)} or build the matching mesh"
        )
    return mesh, layout


def _bind_layout(layout, engine):
    """Flip the plan's MeshLayout stub to ``bound`` and record what the
    engine actually executed (the padding is the stack's, not a
    re-derivation)."""
    if layout is None:
        return None
    layout.padded_facets = int(engine.stack.n_total)
    layout.status = "bound"
    if _metrics.enabled():
        _metrics.gauge("mesh.layout", dict(layout.as_dict()))
    return layout


class MeshStreamedForward(StreamedForward):
    """`StreamedForward` over a facet-sharded device mesh.

    Same API and the same sampled-DFT streaming strategy (facets
    resident, column groups, spill feed); the facet stack, offsets and
    masks are placed with `parallel.mesh.facet_sharding`, each device's
    column pass reduces its LOCAL facets and one psum per column group
    completes the sum over the mesh.

    :param layout: optional `plan.compiler.MeshLayout` (e.g.
        ``compile_plan(...).mesh``) — validated against the mesh and
        flipped to ``status: "bound"``
    :param mesh: explicit `jax.sharding.Mesh` (shared with the backward
        so device-to-device feeding stays on one fabric); default: the
        config's mesh, else a fresh 1-D facet mesh over ``n_devices``
    :param n_devices: device count when no layout/mesh is given
        (default: all available, capped at the facet count)
    """

    def __init__(self, swiftly_config, facet_tasks, layout=None, mesh=None,
                 n_devices=None, col_block=512, col_group=None):
        mesh, layout = _resolve_mesh(
            swiftly_config, len(facet_tasks), layout, mesh, n_devices
        )
        super().__init__(
            attach_mesh(swiftly_config, mesh), facet_tasks,
            col_block=col_block, residency="device", col_group=col_group,
        )
        self.mesh = mesh
        self.layout = _bind_layout(layout, self)
        self._rebuild_kw = dict(
            swiftly_config=swiftly_config, facet_tasks=facet_tasks,
            col_block=col_block, col_group=col_group,
        )

    @property
    def facet_shards(self):
        return mesh_size(self.mesh)

    @property
    def collective(self):
        """The facet-axis reduction schedule the NEXT dispatch runs
        (``psum`` or ``ring``) — resolved from SWIFTLY_MESH_COLLECTIVE
        at read time, exactly like the compiled kernels resolve it at
        call time, so the recorded pedigree always names the executed
        schedule."""
        return resolve_collective(self.facet_shards)

    def rebuild_on(self, mesh, layout=None):
        """A fresh engine of the SAME construction on a different mesh.

        The elastic recovery hook: after a shard loss, `mesh.recovery`
        re-plans the layout on the survivors and rebuilds the engines
        here — same config/facets/blocking, new fabric (the ring
        schedule, when selected, re-resolves for the survivor shard
        count on the next dispatch — its step count is n-1, so the
        re-planned collective is automatically right-sized). The
        original engine is left untouched (its devices may be gone;
        nothing is torn down through them)."""
        return type(self)(mesh=mesh, layout=layout, **self._rebuild_kw)

    def stream_column_groups(self, subgrid_configs, spill=None):
        """`StreamedForward.stream_column_groups` with the
        ``mesh.shard_loss`` fault site fired once per yielded group —
        the canonical place a drill kills one of N virtual shards
        mid-stream (between group boundaries, where an autosave-aligned
        resume is possible)."""
        for item in super().stream_column_groups(subgrid_configs,
                                                 spill=spill):
            _fault_point("mesh.shard_loss")
            yield item

    def layout_summary(self):
        """The executed mesh layout as a dict (artifact-ready)."""
        return {
            "n_devices": self.facet_shards,
            "facet_shards": self.facet_shards,
            "axis": FACET_AXIS,
            "n_facets": int(self.stack.n_real),
            "padded_facets": int(self.stack.n_total),
            "collective": self.collective,
        }

    def _spill_store(self, spill, per_col, out_g):
        """Copy one yielded group's stack to the cache — reading only
        an addressable replica of the (replicated) group output, so the
        spill fill never addresses another host's devices.

        This host pull is the first point the stream BLOCKS on the
        column group's collective completing, which makes it the
        engine's stall-detection site: the sync runs through the
        ``mesh.psum`` (or, under the ring schedule, ``mesh.ring_step``)
        fault point under the collective watchdog
        (``SWIFTLY_COLLECTIVE_TIMEOUT_S``), so a collective hung on a
        dead peer raises `CollectiveStalledError` — a catchable shard
        loss — instead of blocking the host forever.

        Overlap semantics: `stream_column_groups` stores one group
        BEHIND compute (group g's sync runs after group g+1's dispatch)
        and the triple-buffer prefetch thread is already filling group
        g+1's staging slab while this sync waits — so under the ring
        schedule the final `ppermute` steps of group g drain behind
        both the next group's shard-local contraction and its h2d feed
        (the communication-overlap contract; docs/multichip.md)."""
        if spill.gave_up:
            return
        # resolved per group: the site must name the schedule the
        # devices are actually draining (psum and ring are separately
        # priced, separately watched, separately chaos-drilled)
        site = (
            "mesh.ring_step" if self.collective == "ring" else "mesh.psum"
        )

        def pull():
            _fault_point("transfer.d2h")

            def sync():
                _fault_point(site)
                # split the block: the wait on the group's collective is
                # the plan's ICI stage (mesh.psum / mesh.ring_step), the
                # host copy after it is spill.write — timed apart so the
                # plan-accuracy ledger (obs.ledger) joins each against
                # its own priced stage
                with _metrics.stage(site) as st:
                    if hasattr(out_g, "block_until_ready"):
                        out_g.block_until_ready()
                        st.bytes_moved = int(getattr(out_g, "nbytes", 0))
                with _metrics.stage("spill.write") as st:
                    arr = host_replica(out_g)
                    st.bytes_moved = int(arr.nbytes)
                return arr

            return _watch(sync, site)

        host = _retry(pull, site="transfer.d2h")
        if spill.put(per_col, host) and _metrics.enabled():
            _metrics.count("spill.writes")
            _metrics.count("spill.bytes_written", int(host.nbytes))


class MeshStreamedBackward(StreamedBackward):
    """`StreamedBackward` over a facet-sharded device mesh.

    Same API (per-column/stack/group feeding, fold groups, ``row_slab``
    output-row slabs, autosave/resume); the image-space accumulator,
    pending rows and masks are facet-sharded, every fold is shard-local
    (no collectives — the subgrids arrive replicated), and checkpoints
    record the mesh layout so kill+resume restores onto the same
    sharding (`utils.checkpoint`).

    Pass the forward's ``mesh`` so a device-to-device feed
    (`MeshStreamedForward.stream_column_groups` →
    `add_subgrid_group`) stays on one fabric.
    """

    def __init__(self, swiftly_config, facet_configs, layout=None,
                 mesh=None, n_devices=None, col_block=512,
                 residency="sampled", fold_group=4, row_slab=None):
        mesh, layout = _resolve_mesh(
            swiftly_config, len(facet_configs), layout, mesh, n_devices
        )
        super().__init__(
            attach_mesh(swiftly_config, mesh), facet_configs,
            col_block=col_block, residency=residency,
            fold_group=fold_group, row_slab=row_slab,
        )
        self.mesh = mesh
        self.layout = _bind_layout(layout, self)
        self._rebuild_kw = dict(
            swiftly_config=swiftly_config, facet_configs=facet_configs,
            col_block=col_block, residency=residency,
            fold_group=fold_group, row_slab=row_slab,
        )

    @property
    def facet_shards(self):
        return mesh_size(self.mesh)

    def rebuild_on(self, mesh, layout=None):
        """A fresh engine of the SAME construction on a different mesh
        (see `MeshStreamedForward.rebuild_on`). The rebuilt backward
        starts empty — `mesh.recovery` migrates the last autosave into
        it via `utils.checkpoint.restore_streamed_backward_state`,
        which re-pads the facet stacks for the new layout."""
        return type(self)(mesh=mesh, layout=layout, **self._rebuild_kw)

    def add_subgrid_group(self, col_sg_lists, subgrids_group):
        """`StreamedBackward.add_subgrid_group` behind the ``mesh.feed``
        fault site (the per-group mesh feed boundary — distinct from the
        engine-generic ``bwd.feed`` fired inside, so mesh drills can
        target the mesh path without faulting the single-chip
        reference run)."""
        _fault_point("mesh.feed")
        return super().add_subgrid_group(col_sg_lists, subgrids_group)

    def finish(self):
        """Finished facet stack as a host array, pulled from addressable
        shards only (each pod-slice process receives its own facet rows;
        single-process receives everything)."""
        if self._base.residency == "sampled":
            return host_gather(self.finish_device())[: self.stack.n_real]
        return super().finish()
