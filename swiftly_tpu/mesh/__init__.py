"""Mesh-streamed engine: the streamed facet<->subgrid pipeline SPMD over
a `jax.sharding.Mesh` (ROADMAP item 1).

`MeshStreamedForward` / `MeshStreamedBackward` mirror the
`parallel.streamed` executor API — column-group streaming, spill feed,
row slabs, autosave — with the facet stack sharded over the mesh's
facet axis, per-column facet sums reduced by one `lax.psum` inside the
jitted stage bodies, and d2h/spill traffic on addressable shards only.
They bind the plan compiler's `MeshLayout` (``plan.compile_plan(...,
n_devices=...)`` → ``plan.mesh``), flipping its ``status`` to
``"bound"``.

Quick start (CPU simulation: 8 virtual devices)::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'EOF'
    from swiftly_tpu.mesh import MeshStreamedForward, MeshStreamedBackward
    fwd = MeshStreamedForward(config, facet_tasks, layout=plan.mesh)
    bwd = MeshStreamedBackward(config, facet_configs, mesh=fwd.mesh)
    for per_col, group in fwd.stream_column_groups(subgrid_configs):
        bwd.add_subgrid_group([[sg for _, sg in c] for c in per_col], group)
    facets = bwd.finish()
    EOF

`mesh.recovery` adds the elastic rung: a shard lost mid-stream
(`ShardLostError` — injected, or a watchdog-caught stalled collective)
re-plans the layout on the survivors, migrates the last autosave across
layouts and resumes the stream bit-identically (``bench.py --mesh
--chaos`` is the drill).

See docs/multichip.md for the layout/env knobs, the CPU host-device
simulation recipe, the reduction-order tolerance contract and the
failure semantics; the `bench.py --mesh` leg measures scaling vs the
single-chip engine.
"""

from ..parallel.mesh import (
    FACET_AXIS,
    facet_sharding,
    initialize_multihost,
    make_facet_mesh,
    mesh_size,
    pad_to_shards,
)
from .engine import (
    MeshStreamedBackward,
    MeshStreamedForward,
    attach_mesh,
    host_gather,
    host_replica,
    resolve_facet_shards,
)
from .recovery import recover_engines, run_elastic_pass, survivor_mesh

__all__ = [
    "FACET_AXIS",
    "MeshStreamedBackward",
    "MeshStreamedForward",
    "attach_mesh",
    "facet_sharding",
    "host_gather",
    "host_replica",
    "initialize_multihost",
    "make_facet_mesh",
    "mesh_size",
    "pad_to_shards",
    "recover_engines",
    "resolve_facet_shards",
    "run_elastic_pass",
    "survivor_mesh",
]
