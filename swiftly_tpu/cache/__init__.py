"""Shared cache fabric: one recorded subgrid stream, N replica views.

`SharedStreamTier` is the fleet-wide two-tier cache — a single
versioned, spill-backed L2 over the recorded stream plus per-replica
hot-row L1 views (`FabricFeedView`) with single-flight recompute dedup.
See docs/serving.md (Cache fabric) and `plan.price_cache_tier` for the
L1/L2/recompute pricing.
"""

from .fabric import FabricFeedView, SharedStreamTier

__all__ = ["FabricFeedView", "SharedStreamTier"]
