"""Cache fabric: ONE recorded subgrid stream serving N elastic replicas.

The PR 6 fleet gave every replica a private `CachedColumnFeed` over a
private spill cache — fleet memory scaled N× with replica count and a
facet update had to roll N caches. The fabric collapses that to a
two-tier design in the DaggerFFT shape (work units scheduled over one
shared, located data tier, arXiv 2601.12209):

* **L2** — one shared, versioned, spill-backed `utils.spill.SpillCache`
  holding the single resident copy of the recorded stream. Reads go
  through the cache's reader–writer gate, which composes with the delta
  engine's ``begin_patch`` mark (reads that race a patch bounce with
  `StreamMidPatch`) and with ``stream_version`` pinning (a view indexed
  at version v refuses rows once the version moves).
* **L1** — a small per-replica hot-row cache (`api.LRUCache`) fronting
  the L2: the zipf head of a serving workload is answered from the
  replica's own recently-promoted rows without touching the shared
  tier. L1 rows are version-pinned through the same gate as L2 reads
  and are cleared on every fabric `roll`.
* **Single-flight recompute dedup** — concurrent misses on the same
  key (`single_flight`) collapse to one compute: the first caller in
  wins the leadership and runs the closure, followers block on its
  result. The cache-vs-recompute trade this arbitrates is priced by
  `plan.price_cache_tier`.

One index (`parallel.streamed.CachedColumnFeed.build_index`) is built
per stream and shared by every view — N replicas do not re-scan the
stream metadata N times, and `roll` rebuilds it only when a facet
update actually re-recorded the stream (patch mode rewrites payloads in
place, so row coordinates survive).
"""

from __future__ import annotations

import threading

import numpy as np

from ..api import LRUCache
from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace
from ..parallel.streamed import CachedColumnFeed

__all__ = ["FabricFeedView", "SharedStreamTier"]


class _Flight:
    """One in-flight single-flight computation."""

    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None


class FabricFeedView(CachedColumnFeed):
    """One replica's feed view over the shared stream tier.

    Quacks like the `CachedColumnFeed` the `serve.SubgridService`
    already consumes (``lookup``/``stream_version``/hit counters), but
    is a VIEW: the spill cache, the row index and the version pin are
    the fabric's — only the hot-row L1 and the counters are this
    replica's own. `lookup` order: serve gate (mid-patch / complete /
    version — an L1 row must never bypass it) → L1 → L2 row read with
    promotion into L1.
    """

    def __init__(self, fabric, replica_id, l1_rows=64):
        super().__init__(
            fabric.spill, index=fabric.index,
            stream_version=fabric.stream_version,
        )
        self.fabric = fabric
        self.replica_id = int(replica_id)
        self._l1_rows = int(l1_rows)
        self.l1 = LRUCache(self._l1_rows,
                           name=f"cache.l1.r{self.replica_id}")
        self.l1_hits = 0
        self.l2_hits = 0
        self.promotions = 0
        self.l1_evictions = 0

    def lookup(self, config):
        """Fabric lookup: gate, then L1, then the shared L2 (promoting
        the row). Raises LookupError exactly like the base feed —
        consumers keep their fall-back-to-compute contract."""
        self._gate()
        key = (config.off0, config.off1, config.size)
        row = self.l1.get(key)
        if row is not None:
            hit = self._index.get(key)
            if hit is not None and self._masks_match(config, hit[3]):
                self.l1_hits += 1
                self.hits += 1
                if _metrics.enabled():
                    _metrics.count("cache.l1_hits")
                return row
        row = super().lookup(config)
        if row is None:
            return None
        self.l2_hits += 1
        if _metrics.enabled():
            _metrics.count("cache.l2_hits")
        ev_key, _ev = self.l1.set(key, row)
        self.promotions += 1
        if ev_key is not None:
            self.l1_evictions += 1
            if _metrics.enabled():
                _metrics.count("cache.l1_evictions")
        return row

    def single_flight(self, key, fn):
        """Delegate to the fabric's fleet-wide dedup registry."""
        return self.fabric.single_flight(key, fn)

    def adopt(self, index, stream_version, *, clear_l1=True):
        """Roll this view to the fabric's post-update state: new shared
        index + version pin, L1 dropped (its rows were recorded under
        the superseded facet stack)."""
        self._index = index
        self.stream_version = int(stream_version)
        if clear_l1:
            self.l1 = LRUCache(self._l1_rows,
                               name=f"cache.l1.r{self.replica_id}")

    def stats(self):
        """JSON-ready per-view counters (one ``views`` row of the
        fabric's `SharedStreamTier.stats`)."""
        return {
            "replica": self.replica_id,
            "l1_hits": self.l1_hits,
            "l2_hits": self.l2_hits,
            "misses": self.misses,
            "evictions": self.evicted,
            "stale": self.stale,
            "promotions": self.promotions,
            "l1_evictions": self.l1_evictions,
            "l1_len": len(self.l1),
            "l1_rows": self._l1_rows,
        }


class SharedStreamTier:
    """The fabric: one spill-backed L2 + per-replica L1 views.

    :param spill: a COMPLETE `utils.spill.SpillCache` holding the
        recorded stream (typically `delta.IncrementalForward.spill`) —
        the fleet's single resident stream copy
    :param l1_rows: default hot-row capacity of each replica's L1
    """

    def __init__(self, spill, *, l1_rows=64):
        if not getattr(spill, "complete", False):
            raise ValueError(
                "SharedStreamTier requires a COMPLETE spill cache; an "
                "incomplete stream would silently miss-serve every view"
            )
        self.spill = spill
        self.l1_rows = int(l1_rows)
        self.stream_version = int(getattr(spill, "stream_version", 0))
        self.index = CachedColumnFeed.build_index(spill)
        self.index_builds = 1
        self.rolls = 0
        self.dedup_hits = 0
        self.dedup_computes = 0
        self._views = {}
        self._retired_views = 0
        self._retired_counters = {
            k: 0
            for k in ("l1_hits", "l2_hits", "misses", "evictions",
                      "stale", "promotions", "l1_evictions")
        }
        self._lock = threading.Lock()
        self._inflight = {}  # key -> _Flight

    # -- views ---------------------------------------------------------------

    def view(self, replica_id, l1_rows=None):
        """The feed view for one replica (created on first use, stable
        after — an autoscaled replica that drains and returns gets its
        warm L1 back)."""
        with self._lock:
            v = self._views.get(replica_id)
            if v is None:
                v = FabricFeedView(
                    self, replica_id,
                    self.l1_rows if l1_rows is None else l1_rows,
                )
                self._views[replica_id] = v
            return v

    def drop_view(self, replica_id):
        """Forget a drained replica's view: its L1 is freed and its
        final counters fold into the retired ledger so fabric-wide
        stats survive scale-in."""
        with self._lock:
            view = self._views.pop(replica_id, None)
            if view is not None:
                row = view.stats()
                for k in ("l1_hits", "l2_hits", "misses", "evictions",
                          "stale", "promotions", "l1_evictions"):
                    self._retired_counters[k] += row[k]
                self._retired_views += 1
            return view

    @property
    def views(self):
        with self._lock:
            return dict(self._views)

    # -- facet updates -------------------------------------------------------

    def roll(self, report=None):
        """Adopt a landed facet update: ONE version re-pin + L1 sweep
        for the whole fleet (`ServeFleet.post_facet_update` calls this
        once instead of building N feeds). The shared index is rebuilt
        only when the update re-recorded the stream (``replay``); a
        ``patch`` rewrote payloads in place, so row coordinates — and
        the index — survive. Returns the adopted stream version."""
        with self._lock:
            mode = (report or {}).get("mode")
            old = self.stream_version
            self.stream_version = int(
                getattr(self.spill, "stream_version", 0)
            )
            if mode not in ("patch", "noop"):
                self.index = CachedColumnFeed.build_index(self.spill)
                self.index_builds += 1
            moved = self.stream_version != old
            for v in self._views.values():
                v.adopt(self.index, self.stream_version,
                        clear_l1=moved)
            self.rolls += 1
        _trace.instant("cache.roll", cat="cache",
                       stream_version=self.stream_version,
                       mode=mode)
        _recorder.record("cache", "cache.roll",
                         f"v{self.stream_version} mode={mode}")
        if _metrics.enabled():
            _metrics.count("cache.rolls")
        return self.stream_version

    # -- single-flight recompute dedup --------------------------------------

    @staticmethod
    def request_key(config):
        """Dedup identity of one subgrid request: offsets, size AND
        mask content (configs that collide on coordinates but differ in
        masks are different results — same rule as the feed's
        ``_masks_match``)."""

        def digest(m):
            return None if m is None else hash(np.asarray(m).tobytes())

        return (
            int(config.off0), int(config.off1), int(config.size),
            digest(getattr(config, "mask0", None)),
            digest(getattr(config, "mask1", None)),
        )

    def single_flight(self, key, fn):
        """Run ``fn`` once per concurrently-requested ``key``: the
        first caller leads and computes; followers arriving before the
        leader finishes block and adopt its result (bit-identical — the
        engine is deterministic, so whose replica computed is
        unobservable). A leader failure re-raises to the leader and
        followers compute independently — dedup never converts one
        transient failure into N failures."""
        with self._lock:
            fl = self._inflight.get(key)
            leader = fl is None
            if leader:
                fl = _Flight()
                self._inflight[key] = fl
        if leader:
            try:
                fl.result = fn()
            except BaseException as exc:
                fl.error = exc
                raise
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                    self.dedup_computes += 1
                fl.event.set()
            return fl.result
        fl.event.wait()
        if fl.error is not None:
            return fn()
        with self._lock:
            self.dedup_hits += 1
        if _metrics.enabled():
            _metrics.count("cache.dedup_hits")
        return fl.result

    # -- export --------------------------------------------------------------

    def stats(self):
        """JSON-ready fabric block (the ``bench.py --fleet`` artifact's
        ``cache`` block, validated by `obs.validate_fleet_artifact`):
        the single-resident-copy claim, fabric-wide hit/miss/eviction/
        promotion counters aggregated over views, the dedup ledger and
        per-view rows."""
        sp = self.spill.stats()
        with self._lock:
            views = [v.stats() for v in self._views.values()]
            dedup_hits = self.dedup_hits
            dedup_computes = self.dedup_computes
            retired = dict(self._retired_counters)
            retired_views = self._retired_views
        agg = {
            k: sum(v[k] for v in views) + retired[k]
            for k in ("l1_hits", "l2_hits", "misses", "evictions",
                      "stale", "promotions", "l1_evictions")
        }
        served = agg["l1_hits"] + agg["l2_hits"]
        lookups = served + agg["misses"]
        return {
            "resident_stream_copies": 1,
            "stream_entries": int(sp["entries"]),
            "stream_bytes": int(sp["ram_bytes"] + sp["disk_bytes"]),
            "stream_version": int(self.stream_version),
            "views": len(views),
            "retired_views": int(retired_views),
            "index_builds": int(self.index_builds),
            "rolls": int(self.rolls),
            **agg,
            "hit_ratio": round(served / lookups, 4) if lookups else 0.0,
            "l1_hit_share": (
                round(agg["l1_hits"] / served, 4) if served else 0.0
            ),
            "dedup_hits": int(dedup_hits),
            "dedup_computes": int(dedup_computes),
            "per_view": sorted(views, key=lambda v: v["replica"]),
        }
