"""Shared transient-failure retry: jittered exponential backoff plus
transient-vs-fatal classification.

Before this module each subsystem had its own failure posture: a flaky
spill-disk read killed a multi-hour run, serve retried instantly with
no backoff (thundering-herd on a struggling device), and transfers had
no retry at all. `retry_transient` is the one wrapper all of them use:

* **Classification first.** Only transiently-classified errors retry
  (`is_transient`): OS-level I/O errors, timeouts, and runtime errors
  whose text carries the runtime's transient status codes
  (``RESOURCE_EXHAUSTED``, ``UNAVAILABLE``, ...). Deterministic errors
  (a shape mismatch, a config error) re-raise immediately — retrying
  them only delays the real diagnosis. `faults.WorkerKilled` is a
  ``BaseException`` and never enters the handler at all.
* **Jittered exponential backoff.** Delay ``min(max_s, base_s * 2^k)``
  scaled by a uniform [0.5, 1.0) jitter — synchronized retry storms
  from parallel workers decorrelate.
* **Accounted.** ``retry.attempts`` / ``retry.attempts.<site>`` count
  every retry, ``retry.recovered`` the calls that succeeded after one,
  ``retry.exhausted`` the ones that ran out of attempts (via
  `obs.metrics`, zero-cost when disabled).

``SWIFTLY_RETRY_MAX`` (default 3) caps retry attempts process-wide.
"""

from __future__ import annotations

import os
import random
import time

from ..obs import metrics as _metrics

__all__ = [
    "OOM_MARKERS",
    "TRANSIENT_MARKERS",
    "backoff_delay",
    "is_oom",
    "is_transient",
    "max_retry_attempts",
    "retry_transient",
]

# Runtime status codes that mark a failure worth retrying when they
# appear in an exception's text (XLA/PJRT surface these as RuntimeError
# strings, not typed exceptions).
TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "CANCELLED",
    "temporarily unavailable",
)

# Allocator-failure markers (XLA/PJRT surface OOMs as RuntimeError
# text too). Shared by every OOM ladder — bench's plan shrinker and the
# serve batch splitter classify with ONE rule instead of private forks.
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory")

_rng = random.Random()


def max_retry_attempts(default=3):
    """Process-wide retry cap (``SWIFTLY_RETRY_MAX``, default 3)."""
    try:
        return max(0, int(os.environ.get("SWIFTLY_RETRY_MAX", default)))
    except ValueError:
        return default


def is_transient(exc) -> bool:
    """Worth retrying? OS-level I/O failures and timeouts are; anything
    whose message carries a transient runtime status code is; other
    (deterministic) errors are not."""
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return any(marker in text for marker in TRANSIENT_MARKERS)


def is_oom(exc) -> bool:
    """Is this an allocator failure (device or host out-of-memory)?

    The one classifier behind every OOM degradation ladder (bench's
    streamed-plan shrinker, serve's batch splitter): an exception whose
    type or message carries an ``OOM_MARKERS`` entry, e.g. XLA's
    ``RESOURCE_EXHAUSTED`` status or a Python ``MemoryError``.
    """
    if isinstance(exc, MemoryError):
        return True
    text = f"{type(exc).__name__}: {exc}"
    lower = text.lower()
    return any(
        m in text or m.lower() in lower for m in OOM_MARKERS
    )


def backoff_delay(attempt, base_s=0.05, max_s=2.0, rng=None):
    """Jittered exponential delay for retry number `attempt` (0-based)."""
    r = (rng or _rng).random()
    return min(max_s, base_s * (2.0 ** attempt)) * (0.5 + 0.5 * r)


def retry_transient(fn, site="", max_attempts=None, base_s=0.05,
                    max_s=2.0, classify=is_transient, sleep=time.sleep,
                    rng=None, on_retry=None):
    """Call ``fn()``; retry transiently-classified failures with jittered
    exponential backoff. Returns ``fn()``'s value or re-raises the last
    error (fatal errors re-raise immediately, unretried).

    :param site: metrics label (``retry.attempts.<site>``)
    :param max_attempts: retry cap (default ``SWIFTLY_RETRY_MAX``)
    :param classify: predicate deciding retryability (`is_transient`)
    :param sleep: injectable for tests (receives the delay in seconds)
    :param on_retry: optional ``fn(attempt, exc, delay_s)`` observer
    """
    attempts = (
        max_retry_attempts() if max_attempts is None else int(max_attempts)
    )
    for attempt in range(attempts + 1):
        try:
            out = fn()
        except Exception as exc:
            if not classify(exc):
                raise
            if attempt >= attempts:
                _metrics.count("retry.exhausted")
                if site:
                    _metrics.count(f"retry.exhausted.{site}")
                raise
            _metrics.count("retry.attempts")
            if site:
                _metrics.count(f"retry.attempts.{site}")
            delay = backoff_delay(attempt, base_s, max_s, rng)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            _metrics.event("retry", site=site, attempt=attempt,
                           error=f"{type(exc).__name__}: {exc}",
                           delay_s=round(delay, 4))
            sleep(delay)
        else:
            if attempt:
                _metrics.count("retry.recovered")
                if site:
                    _metrics.count(f"retry.recovered.{site}")
            return out
    raise AssertionError("unreachable")  # pragma: no cover
