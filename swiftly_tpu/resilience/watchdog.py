"""Stalled-collective watchdog: turn a hung psum into a detected
failure.

A lost device on a real mesh does not announce itself — the next
collective that includes it simply never completes, and the host
blocks forever inside a device sync. That silent-hang class is the
worst failure mode a multi-hour streamed transform can have: no
exception, no checkpoint, no operator signal. The wafer-scale
slide-FFT work (arXiv 2401.05427) makes the same point from the other
side — a static layout must be *re-derivable* after topology change,
which first requires the topology change to be DETECTED.

`watch_collective` is that detector: it runs the blocking call (the
device sync downstream of the mesh engine's one ``lax.psum`` per
column group) on a worker thread and waits with a deadline. If the
deadline passes, the host raises :class:`CollectiveStalledError` — a
:class:`~swiftly_tpu.resilience.faults.ShardLostError` subclass, so
the elastic recovery ladder treats a stall and an explicit shard loss
identically: re-plan on survivors, migrate the checkpoint, resume.

**Default off.** The knob is ``SWIFTLY_COLLECTIVE_TIMEOUT_S`` (unset,
empty, or ``0`` disables). On CPU simulation a "collective" is just
local math and XLA cannot hang on a peer, so the watchdog would add a
thread hop per group for nothing — it stays off unless an operator
(or a drill) opts in. When disabled, `watch_collective` calls the
function directly: zero overhead, same no-op discipline as
`faults.fault_point` and the disabled metrics registry.

The worker thread is daemonic: if the collective truly never returns
(real device loss), the thread is abandoned and dies with the
process after recovery re-plans around it — there is no portable way
to cancel a blocked device sync, and recovery does not need to.
"""

from __future__ import annotations

import os
import threading

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from .faults import ShardLostError

__all__ = [
    "CollectiveStalledError",
    "collective_timeout_s",
    "watch_collective",
]

_ENV_KNOB = "SWIFTLY_COLLECTIVE_TIMEOUT_S"


class CollectiveStalledError(ShardLostError):
    """A watched collective did not complete within the deadline.

    Subclasses :class:`ShardLostError` deliberately: a stall IS the
    symptom of a lost shard, and the recovery ladder handles both the
    same way. Carries the site and the timeout that expired.
    """

    def __init__(self, site, timeout_s):
        super().__init__(
            f"collective at {site!r} stalled past "
            f"{timeout_s:g}s watchdog deadline"
        )
        self.site = site
        self.timeout_s = timeout_s


def collective_timeout_s(env=None):
    """The watchdog deadline in seconds, or None when disabled.

    Reads ``SWIFTLY_COLLECTIVE_TIMEOUT_S`` (from `env` or the process
    environment). Unset, empty, non-numeric, zero, or negative all
    mean disabled — off is the safe default on CPU simulation, where
    a collective cannot hang on a peer.
    """
    raw = (env or os.environ).get(_ENV_KNOB)
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        return None
    return t if t > 0 else None


def watch_collective(fn, site, timeout_s=None):
    """Run blocking `fn()` under a stall deadline; return its result.

    With `timeout_s` None (or the env knob disabled), this is a direct
    call — the production fast path. Otherwise `fn` runs on a daemon
    thread and the caller waits at most `timeout_s` seconds: on
    expiry a :class:`CollectiveStalledError` is raised (counted as
    ``watchdog.stalls`` / ``watchdog.stalls.<site>`` and stamped as a
    trace instant), converting the silent hang into a failure the
    elastic recovery ladder can catch. If `fn` itself raises, the
    exception is re-raised on the caller's thread unchanged.
    """
    if timeout_s is None:
        timeout_s = collective_timeout_s()
    if timeout_s is None:
        return fn()

    box = {}
    done = threading.Event()

    def _run():
        try:
            box["value"] = fn()
        except BaseException as exc:  # re-raised on the caller thread
            box["error"] = exc
        finally:
            done.set()

    worker = threading.Thread(
        target=_run, name=f"watchdog:{site}", daemon=True
    )
    worker.start()
    if not done.wait(timeout_s):
        _metrics.count("watchdog.stalls")
        _metrics.count(f"watchdog.stalls.{site}")
        _metrics.event(
            "watchdog.stall", site=site, timeout_s=timeout_s
        )
        _trace.instant(
            "watchdog.stall", cat="fault", site=site, timeout_s=timeout_s
        )
        raise CollectiveStalledError(site, timeout_s)
    if "error" in box:
        raise box["error"]
    return box["value"]
