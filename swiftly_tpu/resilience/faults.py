"""Deterministic, seedable fault injection for resilience testing.

Multi-hour streamed transforms at 64k-128k scale make worker and I/O
failure an *expected* event (DaggerFFT, arXiv 2601.12209, schedules
recovery; TPU-scale linear algebra depends on resumable long jobs,
arXiv 2112.09017). This module is how the repo rehearses those events
on CPU in seconds: the engine's failure-prone sites — spill disk
read/write, host<->device transfers, checkpoint save/restore, serve
dispatch, backward feed — each call ``fault_point(site)``, and an
installed `FaultPlan` injects failures there on a deterministic
schedule.

**The clean path costs nothing.** With no plan installed (production),
``fault_point`` is one module-global ``None`` check and an immediate
return — the hooks compile away to no-ops exactly like the disabled
metrics registry (`obs.metrics`). Chaos is strictly opt-in via
``install(plan)`` / ``active(plan)`` or the ``SWIFTLY_FAULT_PLAN`` env
knob.

Fault kinds:

* ``ioerror``   — raise :class:`FaultError` (an ``IOError``; classified
  transient by `resilience.retry.is_transient`)
* ``oom``       — raise :class:`InjectedResourceExhausted` (message
  carries ``RESOURCE_EXHAUSTED`` so the engine's OOM ladders trigger)
* ``corrupt``   — bit-flip the payload: an ``ndarray`` payload returns
  a flipped copy; a file-path payload gets one byte flipped in place
  (checkpoint CRCs must catch it on restore)
* ``latency``   — sleep ``delay_s`` (SLO/backpressure drills)
* ``kill``      — raise :class:`WorkerKilled` (a ``BaseException``:
  it tears through every ``except Exception`` isolation layer, the
  way a real SIGKILL would — only an explicit drill harness catches it)
* ``shard_loss`` — raise :class:`ShardLostError` (an ``Exception``,
  unlike ``kill``: losing ONE shard of a mesh is a survivable,
  *recoverable* event — the elastic recovery ladder catches it,
  re-plans the layout on the survivors and resumes; it is deliberately
  NOT transient-classified, because retrying the same collective on
  the same dead mesh cannot succeed)

Schedules are per-site call-indexed and deterministic: ``at`` fires on
the Nth call to the site (0-based), ``every`` fires periodically, ``p``
fires probabilistically from the plan's seeded RNG — same seed, same
plan, same run, same faults. Every injection is counted
(``fault.injected`` / ``fault.injected.<site>`` via obs) and recorded
in ``plan.injected`` for the resilience artifact block.

Known sites (see docs/resilience.md for the full table):
``spill.write``, ``spill.read``, ``spill.get_row``, ``transfer.h2d``,
``transfer.d2h``, ``checkpoint.save``, ``checkpoint.save.done``,
``checkpoint.restore``, ``serve.dispatch``, ``bwd.feed``,
``fleet.replica.kill`` (every replica pump iteration — ``kill`` here
is simulated chip death), ``fleet.health.probe`` (each active health
probe), ``fleet.route`` (every fleet routing decision),
``mesh.psum`` (the mesh engine's one collective per column group —
``latency`` here simulates a stalled all-reduce for the watchdog,
``shard_loss`` a device dropping out of it), ``mesh.ring_step``
(the same collective site when SWIFTLY_MESH_COLLECTIVE=ring schedules
the ppermute pipeline — a stalled ring step raises
``CollectiveStalledError`` and the re-plan ladder rebuilds on
survivors with the ring re-resolved for the new shard count),
``mesh.feed`` (each
mesh backward group feed), ``mesh.shard_loss`` (each mesh forward
column-group yield — the canonical site for killing one of N virtual
shards mid-stream).
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace

__all__ = [
    "KINDS",
    "FaultError",
    "FaultPlan",
    "InjectedResourceExhausted",
    "ShardLostError",
    "WorkerKilled",
    "active",
    "corrupt_array",
    "corrupt_file",
    "current",
    "fault_point",
    "install",
    "plan_from_env",
    "uninstall",
]

KINDS = ("ioerror", "oom", "corrupt", "latency", "kill", "shard_loss")


class FaultError(IOError):
    """An injected I/O failure (transient by classification)."""


class InjectedResourceExhausted(RuntimeError):
    """An injected allocator failure; message carries RESOURCE_EXHAUSTED
    so `bench._is_oom`-style ladders treat it like the real thing."""


class WorkerKilled(BaseException):
    """Simulated worker death. Deliberately NOT an ``Exception``: retry
    wrappers and isolation layers must not absorb it — only a drill
    harness that then exercises the resume path catches it."""


class ShardLostError(RuntimeError):
    """One shard of a mesh dropped out mid-stream.

    Unlike :class:`WorkerKilled` this IS an ``Exception`` — a single
    shard loss on an N-device mesh is survivable, and the elastic
    recovery ladder (``mesh.recovery``) is built to catch it, re-plan
    the layout on the surviving devices and resume from the last
    autosave. It carries no transient marker and is not an
    ``OSError``, so `resilience.retry.is_transient` correctly refuses
    to retry it in place: the same collective on the same broken mesh
    can never succeed, only a re-planned one can.

    :param shard: the lost shard's index when known, else None.
    """

    def __init__(self, message, shard=None):
        super().__init__(message)
        self.shard = shard


def corrupt_array(arr, rng=None):
    """A copy of `arr` with one bit flipped (position from `rng`)."""
    import numpy as np

    out = np.array(arr)
    flat = out.view(np.uint8).reshape(-1)
    if flat.size:
        r = rng or random
        i = r.randrange(flat.size) if hasattr(r, "randrange") else 0
        flat[i] ^= 1 << (r.randrange(8) if hasattr(r, "randrange") else 0)
    return out


def corrupt_file(path, rng=None):
    """Flip one byte of the file at `path` in place (returns `path`).

    The position avoids the first/last 64 bytes when possible so the
    flip lands in array data (exercising CRC verification) rather than
    always in the zip directory.
    """
    size = os.path.getsize(path)
    if size == 0:
        return path
    lo, hi = (64, size - 64) if size > 192 else (0, size)
    r = rng or random
    pos = r.randrange(lo, hi) if hi > lo else 0
    with open(path, "r+b") as fh:
        fh.seek(pos)
        byte = fh.read(1)
        fh.seek(pos)
        fh.write(bytes([byte[0] ^ 0xFF]))
    return path


class _Rule:
    __slots__ = ("site", "kind", "at", "every", "p", "times", "delay_s",
                 "fired")

    def __init__(self, spec):
        self.site = spec["site"]
        self.kind = spec.get("kind", "ioerror")
        if self.kind not in KINDS:
            raise ValueError(
                f"fault kind {self.kind!r} not in {KINDS}"
            )
        self.at = spec.get("at")
        self.every = spec.get("every")
        self.p = spec.get("p")
        if self.at is None and self.every is None and self.p is None:
            raise ValueError(
                f"fault rule for {self.site!r} needs one of at/every/p"
            )
        # `at` fires once by default; every/p keep firing unless capped
        default_times = 1 if self.at is not None else None
        self.times = spec.get("times", default_times)
        self.delay_s = float(spec.get("delay_s", 0.05))
        self.fired = 0

    def spec(self):
        out = {"site": self.site, "kind": self.kind}
        for f in ("at", "every", "p"):
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        if self.times is not None:
            out["times"] = self.times
        if self.kind == "latency":
            out["delay_s"] = self.delay_s
        return out

    def matches(self, n, rng):
        if self.times is not None and self.fired >= self.times:
            return False
        if self.at is not None and n == self.at:
            return True
        if self.every is not None and self.every > 0 and n % self.every == 0:
            return True
        if self.p is not None and rng.random() < self.p:
            return True
        return False


class FaultPlan:
    """A deterministic schedule of injected faults over named sites.

    :param faults: iterable of rule dicts — ``{"site": ..., "kind": ...,
        "at"/"every"/"p": ..., "times": ..., "delay_s": ...}``
    :param seed: seeds the plan RNG (probabilistic rules and bit-flip
        positions) — the whole plan is replayable from (faults, seed)
    """

    def __init__(self, faults=(), seed=0):
        self.seed = int(seed)
        self.rules = [
            r if isinstance(r, _Rule) else _Rule(dict(r)) for r in faults
        ]
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.calls = {}  # site -> call count
        self.injected = []  # [(site, kind, call_index), ...]

    @classmethod
    def from_spec(cls, spec):
        """Build from a JSON-able dict ``{"seed": ..., "faults": [...]}``
        (or a bare fault list)."""
        if isinstance(spec, (list, tuple)):
            return cls(faults=spec)
        return cls(faults=spec.get("faults", ()), seed=spec.get("seed", 0))

    def spec(self):
        return {"seed": self.seed, "faults": [r.spec() for r in self.rules]}

    def fire(self, site, payload=None):
        """One site call: match rules, inject at most one fault."""
        with self._lock:
            n = self.calls.get(site, 0)
            self.calls[site] = n + 1
            hit = None
            for rule in self.rules:
                if rule.site == site and rule.matches(n, self._rng):
                    rule.fired += 1
                    hit = rule
                    break
            if hit is not None:
                self.injected.append((site, hit.kind, n))
        if hit is None:
            return payload
        _metrics.count("fault.injected")
        _metrics.count(f"fault.injected.{site}")
        _metrics.event("fault", site=site, fault_kind=hit.kind, call=n)
        # a chaos-drill trace shows WHERE the run was hit: each
        # injection is an instant event on the recorded timeline
        _trace.instant("fault.injected", cat="fault", site=site,
                       fault_kind=hit.kind, call=n)
        # the black box keeps injections even with tracing off — a
        # post-mortem must show what was fired before the trigger raise
        _recorder.record("fault", f"fault.injected.{site}",
                         f"{hit.kind} call {n}")
        if hit.kind == "ioerror":
            raise FaultError(f"injected IOError at {site} (call {n})")
        if hit.kind == "oom":
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected allocator failure at "
                f"{site} (call {n})"
            )
        if hit.kind == "kill":
            raise WorkerKilled(f"injected worker death at {site} (call {n})")
        if hit.kind == "shard_loss":
            raise ShardLostError(
                f"injected shard loss at {site} (call {n})"
            )
        if hit.kind == "latency":
            time.sleep(hit.delay_s)
            return payload
        # corrupt: bit-flip the payload (array copy or file in place)
        if payload is None:
            return payload
        if isinstance(payload, (str, os.PathLike)):
            return corrupt_file(payload, self._rng)
        return corrupt_array(payload, self._rng)

    def stats(self):
        """JSON-ready injection summary for resilience artifacts."""
        with self._lock:
            by_site = {}
            by_kind = {}
            for site, kind, _n in self.injected:
                by_site[site] = by_site.get(site, 0) + 1
                by_kind[kind] = by_kind.get(kind, 0) + 1
            return {
                "total": len(self.injected),
                "by_site": by_site,
                "by_kind": by_kind,
                "seed": self.seed,
            }


# ---------------------------------------------------------------------------
# The installed plan. `fault_point` is on hot paths (per-group transfers):
# the disabled check must stay one global read + None test.
# ---------------------------------------------------------------------------

_ACTIVE = None


def fault_point(site, payload=None):
    """Hook one failure-prone call site; returns `payload` (possibly
    corrupted). A no-op returning `payload` unchanged when no plan is
    installed — the production fast path."""
    plan = _ACTIVE
    if plan is None:
        return payload
    return plan.fire(site, payload)


def current():
    return _ACTIVE


def install(plan):
    """Install `plan` process-wide (None uninstalls). Returns the plan."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def uninstall():
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def active(plan):
    """Scoped installation: the plan applies inside the block only."""
    prev = _ACTIVE
    install(plan)
    try:
        yield plan
    finally:
        install(prev)


def plan_from_env():
    """The `FaultPlan` named by ``SWIFTLY_FAULT_PLAN`` (inline JSON, or
    ``@/path/to/plan.json``), or None when unset. Not auto-installed —
    chaos entry points (``bench.py --chaos``, scripts/chaos_drill.py)
    install it explicitly so a stray env var can never fault a
    production run that did not ask for chaos."""
    raw = os.environ.get("SWIFTLY_FAULT_PLAN")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:]) as fh:
            raw = fh.read()
    return FaultPlan.from_spec(json.loads(raw))
