"""The graceful-degradation ledger: every rung a run steps down is
recorded, counted, and stamped into artifacts.

The engine's degradation ladder (docs/resilience.md) trades cost for
survival, never correctness:

* spill-disk failure      -> host-RAM-only cache -> forward replay
* corrupt checkpoint      -> previous good generation
* fused-batch OOM         -> split batch          -> per-request
* cache-feed eviction     -> recompute (serve; pre-existing)

Each step calls :func:`record` at the moment it happens; `events()` is
the JSON-ready trail the chaos drill and ``bench.py --chaos`` stamp
into the artifact's resilience block, and ``degrade.<site>.<action>``
counters land in `obs.metrics` (zero-cost when metrics are off). The
ledger itself always records (bounded at ``_MAX_EVENTS``) — a
degradation that nobody can see afterwards is half a failure.
"""

from __future__ import annotations

import threading

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace

__all__ = ["events", "record", "reset"]

_MAX_EVENTS = 1024  # bound the trail on pathological flapping

_lock = threading.Lock()
_events = []
_dropped = 0


def record(site, action, detail=None):
    """One ladder step: `site` stepped down via `action` (e.g.
    ``record("spill", "disk_to_ram", "write failed: ...")``)."""
    global _dropped
    _metrics.count("degrade.events")
    _metrics.count(f"degrade.{site}.{action}")
    _metrics.event("degrade", site=site, action=action,
                   detail=str(detail) if detail is not None else None)
    # ladder steps land on the trace too: a chaos-drill timeline shows
    # WHERE the run degraded, not just that it did
    _trace.instant(f"degrade.{site}.{action}", cat="degrade",
                   site=site, action=action)
    _recorder.record("degrade", f"degrade.{site}.{action}",
                     str(detail) if detail is not None else None)
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            _dropped += 1
            return
        _events.append(
            {
                "site": site,
                "action": action,
                "detail": str(detail) if detail is not None else None,
            }
        )


def events():
    """The degradation trail so far (JSON-ready list, oldest first)."""
    with _lock:
        out = list(_events)
        if _dropped:
            out.append(
                {
                    "site": "degrade",
                    "action": "events_dropped",
                    "detail": f"{_dropped} past the {_MAX_EVENTS} cap",
                }
            )
        return out


def reset():
    """Clear the trail (drill/test isolation)."""
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0
