"""Per-dependency circuit breaker: fail fast instead of piling on.

A fleet front that keeps routing requests at a dead or struggling
replica converts one failure into many: every routed request waits out
a timeout, retries pile onto the struggling device, and the survivors'
capacity drains into futile re-sends. The breaker is the classic
remedy (the pattern DaggerFFT's scheduler applies to failed FFT
workers, arXiv 2601.12209): after ``failure_threshold`` CONSECUTIVE
failures the breaker **opens** and the router stops offering traffic;
after a jittered, escalating reopen delay it goes **half-open** and
admits a bounded number of probe requests; probe successes **close**
it again, a probe failure re-opens it with a longer delay.

States and transitions::

            failures >= threshold                reopen deadline passed
    CLOSED ───────────────────────▶ OPEN ───────────────────────────▶ HALF_OPEN
      ▲                              ▲                                   │
      │   half_open_probes successes │        any probe failure          │
      └──────────────────────────────┼───────────────────────────────────┤
                                     └───────────────────────────────────┘

The reopen delay reuses the PR-4 jittered exponential curve
(`resilience.retry.backoff_delay` over the consecutive-open count,
capped at ``max_reopen_s``) so repeatedly-failing replicas are probed
ever less often — and, with a seeded ``rng``, deterministically in
drills. Every transition is recorded in ``transitions`` (bounded),
counted (``breaker.to_<state>`` via `obs.metrics`) and landed on the
trace as an instant event, so a chaos-drill artifact can show the full
open → half-open → closed cycle.

Thread-safe; ``clock`` and ``rng`` are injectable for deterministic
tests. See docs/resilience.md for the vocabulary.
"""

from __future__ import annotations

import threading
import time

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace
from .retry import backoff_delay

__all__ = ["CLOSED", "HALF_OPEN", "OPEN", "CircuitBreaker"]

CLOSED = "closed"          # traffic flows; consecutive failures counted
OPEN = "open"              # no traffic until the reopen deadline
HALF_OPEN = "half_open"    # a bounded number of probe requests flow

_MAX_TRANSITIONS = 256  # bound the recorded trail on pathological flapping


class CircuitBreaker:
    """Closed → open → half-open → closed failure gate for one target.

    :param name: metrics/trace label (e.g. ``"replica-2"``)
    :param failure_threshold: consecutive failures that open the breaker
    :param reopen_s: base of the open→half-open delay; each consecutive
        open doubles it (jittered, capped at ``max_reopen_s``)
    :param max_reopen_s: reopen-delay cap
    :param half_open_probes: probe requests admitted while half-open;
        the same number of successes closes the breaker
    :param rng: seeded RNG for the reopen jitter (deterministic drills)
    :param clock: injectable monotonic clock for tests
    """

    def __init__(self, name="", failure_threshold=3, reopen_s=0.5,
                 max_reopen_s=30.0, half_open_probes=2, rng=None,
                 clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if half_open_probes < 1:
            raise ValueError("half_open_probes must be >= 1")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reopen_s = float(reopen_s)
        self.max_reopen_s = float(max_reopen_s)
        self.half_open_probes = int(half_open_probes)
        self._rng = rng
        self._clock = clock
        self._lock = threading.RLock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._open_count = 0        # consecutive opens (escalates reopen)
        self._reopen_t = None       # open → half-open deadline
        self._probes_inflight = 0
        self._probe_successes = 0
        self.transitions = []       # [{"t", "from", "to", "reason"}, ...]
        self.dropped_transitions = 0

    # -- state ---------------------------------------------------------------

    @property
    def state(self):
        """The breaker's current state (``open`` stays ``open`` until a
        probe is actually admitted by `allow` — state peeks never
        transition)."""
        with self._lock:
            return self._state

    def _transition(self, to, reason, now):
        frm = self._state
        self._state = to
        if len(self.transitions) < _MAX_TRANSITIONS:
            self.transitions.append(
                {"t": round(now, 6), "from": frm, "to": to,
                 "reason": reason}
            )
        else:
            self.dropped_transitions += 1
        _metrics.count(f"breaker.to_{to}")
        if self.name:
            _metrics.count(f"breaker.{self.name}.to_{to}")
        _trace.instant("breaker.transition", cat="breaker",
                       breaker=self.name, frm=frm, to=to, reason=reason)
        _recorder.record("breaker",
                         f"breaker.{self.name or 'default'}.{frm}->{to}",
                         reason)

    # -- the gate ------------------------------------------------------------

    def allow(self, now=None):
        """May one request pass right now?

        CLOSED always allows. OPEN denies until the reopen deadline,
        then transitions to HALF_OPEN and admits the call as the first
        probe. HALF_OPEN admits up to ``half_open_probes`` in-flight
        probes. Callers that route a request after a True MUST report
        its outcome via `record_success` / `record_failure` — in
        half-open, that report is what closes (or re-opens) the breaker.
        """
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock() if now is None else now
            if self._state == OPEN:
                if self._reopen_t is not None and now >= self._reopen_t:
                    self._transition(
                        HALF_OPEN,
                        f"reopen deadline passed after "
                        f"{self._open_count} open(s)", now,
                    )
                    self._probes_inflight = 1
                    self._probe_successes = 0
                    return True
                return False
            # HALF_OPEN: bounded probe admission
            if self._probes_inflight < self.half_open_probes:
                self._probes_inflight += 1
                return True
            return False

    # -- outcome reports -----------------------------------------------------

    def record_success(self, now=None):
        """One request against the target succeeded."""
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._probes_inflight = max(0, self._probes_inflight - 1)
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    now = self._clock() if now is None else now
                    self._open_count = 0
                    self._transition(
                        CLOSED,
                        f"{self._probe_successes} probe successes", now,
                    )

    def record_failure(self, now=None, reason=""):
        """One request against the target failed (or timed out)."""
        with self._lock:
            now = self._clock() if now is None else now
            if self._state == HALF_OPEN:
                # a probe failure re-opens with an escalated delay
                self._probes_inflight = max(0, self._probes_inflight - 1)
                self._open(now, reason or "half-open probe failed")
                return
            if self._state == OPEN:
                return  # already open; nothing new to learn
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.failure_threshold:
                self._open(
                    now,
                    reason
                    or f"{self._consecutive_failures} consecutive failures",
                )

    def trip(self, now=None, reason="tripped"):
        """Force the breaker open on external evidence (e.g. a health
        lease revocation) — stronger than one request failure, so it
        does not wait out ``failure_threshold``. A no-op when already
        open."""
        with self._lock:
            if self._state == OPEN:
                return
            now = self._clock() if now is None else now
            self._probes_inflight = 0
            self._open(now, reason)

    def _open(self, now, reason):  # caller holds the lock
        self._open_count += 1
        self._consecutive_failures = 0
        # the PR-4 jittered exponential curve over consecutive opens:
        # a target that keeps failing its probes is probed ever less
        # often, and seeded rng makes the drill schedule replayable
        delay = backoff_delay(
            self._open_count - 1, base_s=self.reopen_s,
            max_s=self.max_reopen_s, rng=self._rng,
        )
        self._reopen_t = now + delay
        self._transition(OPEN, f"{reason} (reopen in {delay:.3f}s)", now)

    # -- export --------------------------------------------------------------

    def stats(self):
        """JSON-ready breaker summary for fleet artifacts."""
        with self._lock:
            return {
                "name": self.name,
                "state": self._state,
                "open_count": self._open_count,
                "consecutive_failures": self._consecutive_failures,
                "transitions": list(self.transitions),
                "dropped_transitions": self.dropped_transitions,
            }

    def __repr__(self):
        return (
            f"CircuitBreaker({self.name!r}, state={self.state}, "
            f"opens={self._open_count})"
        )
