"""Resilient execution: fault injection, retry/backoff, degradation.

The paper's workload is multi-hour streamed transforms that never
materialise the full grid; the ROADMAP's is production serving. Both
treat worker and I/O failure as an expected event (DaggerFFT,
arXiv 2601.12209; TPU-scale linear algebra, arXiv 2112.09017). This
package is the discipline layer:

* ``resilience.faults``  — deterministic, seedable `FaultPlan` hooking
  named engine sites (spill I/O, transfers, checkpoint save/restore,
  serve dispatch); zero-cost no-op when no plan is installed.
* ``resilience.retry``   — the shared `retry_transient` wrapper:
  transient-vs-fatal classification + jittered exponential backoff,
  accounted via `obs.metrics` (``retry.*`` counters); `is_oom` is the
  one allocator-failure classifier every OOM ladder shares.
* ``resilience.breaker`` — the per-dependency `CircuitBreaker`
  (closed → open on consecutive failures, half-open probes, jittered
  escalating reopen) the serve fleet gates each replica with.
* ``resilience.degrade`` — the graceful-degradation ledger every
  ladder step (spill disk -> RAM -> replay; corrupt checkpoint ->
  previous generation; fused batch -> split -> per-request; lost
  mesh shard -> re-planned survivor layout) records into, stamped
  into chaos artifacts.
* ``resilience.watchdog`` — the stalled-collective watchdog
  (``SWIFTLY_COLLECTIVE_TIMEOUT_S``): turns a hung mesh psum into a
  caught :class:`CollectiveStalledError` so the elastic recovery
  ladder (`mesh.recovery`) can re-plan instead of hanging forever.

Hardened checkpointing (atomic tmp+fsync+rename writes, per-array
CRC32, keep-N generation rotation with automatic fallback) lives in
`utils.checkpoint`; the chaos drill that exercises all of it is
``bench.py --chaos`` / scripts/chaos_drill.py. See docs/resilience.md.
"""

from . import degrade
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .faults import (
    FaultError,
    FaultPlan,
    InjectedResourceExhausted,
    ShardLostError,
    WorkerKilled,
    active,
    fault_point,
    install,
    plan_from_env,
    uninstall,
)
from .retry import backoff_delay, is_oom, is_transient, retry_transient
from .watchdog import (
    CollectiveStalledError,
    collective_timeout_s,
    watch_collective,
)

__all__ = [
    "CLOSED",
    "CircuitBreaker",
    "CollectiveStalledError",
    "FaultError",
    "FaultPlan",
    "HALF_OPEN",
    "InjectedResourceExhausted",
    "OPEN",
    "ShardLostError",
    "WorkerKilled",
    "active",
    "backoff_delay",
    "collective_timeout_s",
    "degrade",
    "fault_point",
    "install",
    "is_oom",
    "is_transient",
    "plan_from_env",
    "retry_transient",
    "uninstall",
    "watch_collective",
]
