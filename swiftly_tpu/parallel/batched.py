"""Batched execution kernels: whole facet stacks per XLA program.

Where the reference schedules one Dask task per (facet, subgrid) pair
(/root/reference/src/ska_sdp_exec_swiftly/api.py:263-279), the TPU path
stacks all facets into one array and `vmap`s the per-axis primitives over
the stack, with per-facet offsets as traced vectors. One jitted program
then computes *every* facet's contribution to a subgrid and reduces them —
on a device mesh the same reduction becomes a `psum` over the facet axis
(see swiftly_tpu.parallel.sharded).

All kernels take the (hashable) SwiftlyCore as a static argument; window
constants embed as XLA constants. The numpy backend executes the same
semantics with an eager loop, which keeps the streaming API
backend-agnostic.

Array conventions (complex backends; planar adds a trailing (re,im) axis):
  facets       [F, yB, yB]     stacked facet data
  BF_Fs        [F, yN, yB]     facets prepared along axis 0
  NMBF_BFs     [F, m, yN]      one subgrid column's contributions (m=xM_yN)
  NMBF_NMBFs   [F, m, m]       per-facet contribution to one subgrid
  NAF_NAFs     [F, m, m]       per-facet contribution from one subgrid
  NAF_MNAFs    [F, m, yN]      per-column backward accumulators
  MNAF_BMNAFs  [F, yN, yB]     per-facet backward accumulators
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.core import (
    add_to_facet_math,
    add_to_subgrid_math,
    extract_from_facet_math,
    extract_from_subgrid_math,
    finish_facet_math,
    finish_subgrid_math,
    prepare_facet_math,
    prepare_subgrid_math,
)

__all__ = [
    "accumulate_column_batch",
    "accumulate_facet_batch",
    "backward_all_batch",
    "extract_columns_batch",
    "finish_facets_batch",
    "forward_all_batch",
    "prepare_facets_batch",
    "split_accumulate_batch",
    "split_subgrid_batch",
    "subgrid_from_columns_batch",
    "subgrids_from_columns_batch",
]


def _is_host(core):
    """Host-eager backends: loop over the stack calling the core's
    dispatching methods (plain numpy, or the compiled native kernels)."""
    return core.backend in ("numpy", "native")


def _mask_along(p, data, mask, axis):
    """Multiply `data` by a per-axis 0/1 mask (real vector)."""
    return data * p.broadcast_along(mask, p.ndim(data), axis)


def _as_real(x, rdt):
    """Nested mask lists -> a device array of the core's real dtype,
    keeping already-placed jax arrays (and their sharding) as-is."""
    if hasattr(x, "sharding"):
        return x if x.dtype == rdt else x.astype(rdt)
    return jnp.asarray(np.asarray(x), rdt)


def facet_contrib_to_subgrid(core, NMBF_BF, foff0, foff1, sg_off1):
    """One facet's column block -> its padded-subgrid summand [xM, xM].

    The per-facet body of the forward hot loop, shared by the single-device
    vmap reduction and the shard_map+psum path (so the two spmd modes can
    never diverge numerically)."""
    p = core._p
    NMBF_NMBF = extract_from_facet_math(
        p, core.xM_yN_size, core.N, core.yN_size, NMBF_BF, sg_off1, 1
    )
    acc0 = add_to_subgrid_math(
        p, core._Fn, core.xM_size, core.N, NMBF_NMBF, foff0, 0
    )
    return add_to_subgrid_math(
        p, core._Fn, core.xM_size, core.N, acc0, foff1, 1
    )


def subgrid_contrib_to_facet(core, prepped, foff0, foff1):
    """A prepared subgrid -> one facet's contribution block [m, m].

    The per-facet body of the backward split, shared by both spmd modes."""
    p = core._p
    e0 = extract_from_subgrid_math(
        p, core._Fn, core.xM_yN_size, core.xM_size, core.N, prepped, foff0, 0
    )
    return extract_from_subgrid_math(
        p, core._Fn, core.xM_yN_size, core.xM_size, core.N, e0, foff1, 1
    )


def finish_masked_subgrid(core, summed, sg_offs, subgrid_size, mask0, mask1):
    """Finish a summed padded subgrid and apply ownership masks."""
    p = core._p
    subgrid = finish_subgrid_math(p, subgrid_size, summed, sg_offs)
    subgrid = _mask_along(p, subgrid, mask0, 0)
    return _mask_along(p, subgrid, mask1, 1)


# -- facet -> subgrid -------------------------------------------------------


@functools.partial(jax.jit, static_argnums=0)
def _prepare_facets_j(core, facets, offs0):
    fn = lambda facet, off: prepare_facet_math(
        core._p, core._Fb, core.yN_size, facet, off, 0
    )
    return jax.vmap(fn)(facets, offs0)


def prepare_facets_batch(core, facets, offs0):
    """[F, yB, yB] -> BF_Fs [F, yN, yB]: prepare all facets along axis 0.

    Done once per streaming session and reused for every subgrid
    (reference `_get_BF_Fs`, api.py:281-298).
    """
    if _is_host(core):
        return np.stack(
            [core.prepare_facet(f, int(o), 0) for f, o in zip(facets, offs0)]
        )
    return _prepare_facets_j(core, core._prep(facets), jnp.asarray(offs0))


def _extract_columns_fn(core, BF_Fs, off0, offs1):
    def fn(BF_F, off1):
        col = extract_from_facet_math(
            core._p, core.xM_yN_size, core.N, core.yN_size, BF_F, off0, 0
        )
        return prepare_facet_math(
            core._p, core._Fb, core.yN_size, col, off1, 1
        )

    return jax.vmap(fn)(BF_Fs, offs1)


_extract_columns_j = functools.partial(jax.jit, static_argnums=0)(
    _extract_columns_fn
)


def extract_columns_batch(core, BF_Fs, off0, offs1):
    """BF_Fs [F, yN, yB] -> NMBF_BFs [F, m, yN] for one subgrid column.

    Axis-0 extraction at the column's off0 plus axis-1 preparation; shared
    by every subgrid with this off0 (reference `extract_column`,
    api_helper.py:200-210).
    """
    if _is_host(core):
        out = []
        for BF_F, off1 in zip(BF_Fs, offs1):
            col = core.extract_from_facet(BF_F, int(off0), 0)
            out.append(core.prepare_facet(col, int(off1), 1))
        return np.stack(out)
    return _extract_columns_j(
        core, BF_Fs, jnp.asarray(off0), jnp.asarray(offs1)
    )


@functools.partial(jax.jit, static_argnums=(0, 6))
def _subgrid_from_columns_j(
    core, NMBF_BFs, offs0, offs1, sg_offs, masks, subgrid_size
):
    contrib = lambda NMBF_BF, foff0, foff1: facet_contrib_to_subgrid(
        core, NMBF_BF, foff0, foff1, sg_offs[1]
    )
    summed = jnp.sum(jax.vmap(contrib)(NMBF_BFs, offs0, offs1), axis=0)
    return finish_masked_subgrid(
        core, summed, sg_offs, subgrid_size, masks[0], masks[1]
    )


def subgrid_from_columns_batch(
    core, NMBF_BFs, offs0, offs1, sg_off0, sg_off1, subgrid_size, masks
):
    """NMBF_BFs [F, m, yN] -> finished subgrid [xA, xA] for one subgrid.

    Extracts the axis-1 contribution per facet, embeds both axes into the
    padded-subgrid frame, sums over facets (the psum-able reduction),
    finishes, and applies ownership masks (reference
    `sum_and_finish_subgrid`, api_helper.py:73-112).
    """
    if _is_host(core):
        p = core._p
        summed = None
        for NMBF_BF, foff0, foff1 in zip(NMBF_BFs, offs0, offs1):
            NMBF_NMBF = core.extract_from_facet(NMBF_BF, int(sg_off1), 1)
            acc = core.add_to_subgrid(NMBF_NMBF, int(foff0), 0)
            acc = core.add_to_subgrid(acc, int(foff1), 1)
            summed = acc if summed is None else summed + acc
        subgrid = core.finish_subgrid(
            summed, [int(sg_off0), int(sg_off1)], subgrid_size
        )
        subgrid = _mask_along(p, subgrid, masks[0], 0)
        return _mask_along(p, subgrid, masks[1], 1)
    return _subgrid_from_columns_j(
        core,
        NMBF_BFs,
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        jnp.asarray([sg_off0, sg_off1]),
        [jnp.asarray(masks[0], core._Fb.dtype),
         jnp.asarray(masks[1], core._Fb.dtype)],
        subgrid_size,
    )


@functools.partial(jax.jit, static_argnums=(0, 5))
def _subgrids_from_columns_multi_j(
    core, NMBF_BFs, offs0, offs1, sg_offs_arr, subgrid_size, masks0, masks1
):
    def one(sg_offs, mask0, mask1):
        contrib = lambda NMBF_BF, foff0, foff1: facet_contrib_to_subgrid(
            core, NMBF_BF, foff0, foff1, sg_offs[1]
        )
        summed = jnp.sum(jax.vmap(contrib)(NMBF_BFs, offs0, offs1), axis=0)
        return finish_masked_subgrid(
            core, summed, sg_offs, subgrid_size, mask0, mask1
        )

    return jax.vmap(one)(sg_offs_arr, masks0, masks1)


def subgrids_from_columns_batch(
    core, NMBF_BFs, offs0, offs1, sg_offs_list, subgrid_size, masks_list
):
    """All subgrids of one column in a single program: [S, xA, xA].

    vmap over the subgrid axis on top of the per-facet vmap — one XLA
    dispatch computes a whole column of subgrids, amortising launch
    overhead (the per-subgrid variant is `subgrid_from_columns_batch`).

    :param sg_offs_list: [(off0, off1), ...] for the column's subgrids
    :param masks_list: [(mask0, mask1), ...] matching sg_offs_list
    """
    if _is_host(core):
        return np.stack(
            [
                subgrid_from_columns_batch(
                    core, NMBF_BFs, offs0, offs1, so[0], so[1],
                    subgrid_size, masks,
                )
                for so, masks in zip(sg_offs_list, masks_list)
            ]
        )
    rdt = core._Fb.dtype
    return _subgrids_from_columns_multi_j(
        core,
        NMBF_BFs,
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        jnp.asarray(sg_offs_list),
        subgrid_size,
        jnp.asarray(np.stack([m[0] for m in masks_list]), rdt),
        jnp.asarray(np.stack([m[1] for m in masks_list]), rdt),
    )


@functools.partial(jax.jit, static_argnums=(0, 5))
def _forward_all_j(
    core, BF_Fs, foffs, col_offs0, sg_offs1, subgrid_size, masks0, masks1
):
    offs0, offs1 = foffs

    def one_column(_, xs):
        off0, col_sg_offs1, col_m0, col_m1 = xs
        cols = _extract_columns_j(core, BF_Fs, off0, offs1)

        def one_sg(off1, mask0, mask1):
            contrib = lambda NMBF_BF, foff0, foff1: facet_contrib_to_subgrid(
                core, NMBF_BF, foff0, foff1, off1
            )
            summed = jnp.sum(jax.vmap(contrib)(cols, offs0, offs1), axis=0)
            return finish_masked_subgrid(
                core,
                summed,
                jnp.stack([off0, off1]),
                subgrid_size,
                mask0,
                mask1,
            )

        return None, jax.vmap(one_sg)(col_sg_offs1, col_m0, col_m1)

    _, subgrids = jax.lax.scan(
        one_column, None, (col_offs0, sg_offs1, masks0, masks1)
    )
    return subgrids


def forward_all_batch(
    core, BF_Fs, offs0, offs1, col_offs0, sg_offs1, subgrid_size,
    masks0, masks1,
):
    """The full forward cover as ONE program: [C, S, xA, xA].

    Scans over the C subgrid columns; per column, extracts the facet
    column blocks once and vmaps over its S subgrids. One XLA dispatch
    (and one host sync) computes every subgrid of the cover — the
    dispatch/sync-latency-optimal shape for remote-attached TPUs.

    :param col_offs0: [C] column offsets
    :param sg_offs1: [C, S] per-column subgrid off1 values
    :param masks0/masks1: [C, S, xA] per-subgrid ownership masks
    """
    if _is_host(core):
        out = []
        for c, off0 in enumerate(col_offs0):
            cols = extract_columns_batch(core, BF_Fs, off0, offs1)
            out.append(
                np.stack(
                    [
                        subgrid_from_columns_batch(
                            core, cols, offs0, offs1, off0, sg_offs1[c][s],
                            subgrid_size,
                            (masks0[c][s], masks1[c][s]),
                        )
                        for s in range(len(sg_offs1[c]))
                    ]
                )
            )
        return np.stack(out)
    rdt = core._Fb.dtype
    return _forward_all_j(
        core,
        BF_Fs,
        (jnp.asarray(offs0), jnp.asarray(offs1)),
        jnp.asarray(col_offs0),
        jnp.asarray(sg_offs1),
        subgrid_size,
        _as_real(masks0, rdt),
        _as_real(masks1, rdt),
    )


# -- subgrid -> facet -------------------------------------------------------


@functools.partial(jax.jit, static_argnums=0)
def _split_subgrid_j(core, subgrid, sg_offs, offs0, offs1):
    prepped = prepare_subgrid_math(core._p, core.xM_size, subgrid, sg_offs)
    extract = lambda foff0, foff1: subgrid_contrib_to_facet(
        core, prepped, foff0, foff1
    )
    return jax.vmap(extract)(offs0, offs1)


def split_subgrid_batch(core, subgrid, sg_off0, sg_off1, offs0, offs1):
    """Subgrid [xA, xA] -> NAF_NAFs [F, m, m]: contributions to all facets.

    (Reference `prepare_and_split_subgrid`, api_helper.py:115-139.)
    """
    if _is_host(core):
        prepped = core.prepare_subgrid(
            np.asarray(subgrid, dtype=complex), [int(sg_off0), int(sg_off1)]
        )
        out = []
        for foff0, foff1 in zip(offs0, offs1):
            e0 = core.extract_from_subgrid(prepped, int(foff0), 0)
            out.append(core.extract_from_subgrid(e0, int(foff1), 1))
        return np.stack(out)
    return _split_subgrid_j(
        core,
        core._prep(subgrid),
        jnp.asarray([sg_off0, sg_off1]),
        jnp.asarray(offs0),
        jnp.asarray(offs1),
    )


def _split_accumulate_fn(core, subgrids, sg_offs_arr, foffs, NAF_MNAFs):
    offs0, offs1 = foffs

    def step(acc, xs):
        subgrid, sg_offs = xs
        prepped = prepare_subgrid_math(
            core._p, core.xM_size, subgrid, sg_offs
        )
        extract = lambda foff0, foff1: subgrid_contrib_to_facet(
            core, prepped, foff0, foff1
        )
        NAF_NAFs = jax.vmap(extract)(offs0, offs1)
        fold = lambda c: add_to_facet_math(
            core._p, core.yN_size, core.N, c, sg_offs[1], 1
        )
        return acc + jax.vmap(fold)(NAF_NAFs), None

    # scan keeps the live set at one [F, m, yN] accumulator instead of
    # materialising all S subgrids' contributions at once.
    acc, _ = jax.lax.scan(step, NAF_MNAFs, (subgrids, sg_offs_arr))
    return acc


_split_accumulate_multi_j = functools.partial(
    jax.jit, static_argnums=0, donate_argnums=4
)(_split_accumulate_fn)


def split_accumulate_batch(core, subgrids, sg_offs_list, offs0, offs1,
                           NAF_MNAFs):
    """Fold a whole column of subgrids into its accumulator in one program.

    Equivalent to `split_subgrid_batch` + `accumulate_column_batch` per
    subgrid; `subgrids` is the stacked [S, xA, xA] column, `sg_offs_list`
    the matching [(off0, off1), ...]. Returns the updated NAF_MNAFs
    [F, m, yN] (input donated on device backends).
    """
    if _is_host(core):
        for sg, (o0, o1) in zip(subgrids, sg_offs_list):
            NAF_NAFs = split_subgrid_batch(core, sg, o0, o1, offs0, offs1)
            NAF_MNAFs = accumulate_column_batch(core, NAF_NAFs, o1, NAF_MNAFs)
        return NAF_MNAFs
    if isinstance(subgrids, (list, tuple)):
        subgrids = jnp.stack([core._prep(sg) for sg in subgrids])
    return _split_accumulate_multi_j(
        core,
        subgrids,
        jnp.asarray(sg_offs_list),
        (jnp.asarray(offs0), jnp.asarray(offs1)),
        NAF_MNAFs,
    )


# The old accumulator value is dead after each fold — donate it so XLA
# updates in place instead of allocating a fresh [F, m, yN] per subgrid.
@functools.partial(jax.jit, static_argnums=0, donate_argnums=3)
def _accumulate_column_j(core, NAF_NAFs, sg_off1, NAF_MNAFs):
    fn = lambda c: add_to_facet_math(core._p, core.yN_size, core.N, c, sg_off1, 1)
    return NAF_MNAFs + jax.vmap(fn)(NAF_NAFs)


def accumulate_column_batch(core, NAF_NAFs, sg_off1, NAF_MNAFs):
    """Fold one subgrid's NAF_NAFs [F, m, m] into the column accumulator
    NAF_MNAFs [F, m, yN] (reference `accumulate_column`,
    api_helper.py:142-152)."""
    if _is_host(core):
        for i, c in enumerate(NAF_NAFs):
            core.add_to_facet(c, int(sg_off1), 1, out=NAF_MNAFs[i])
        return NAF_MNAFs
    return _accumulate_column_j(
        core, NAF_NAFs, jnp.asarray(sg_off1), NAF_MNAFs
    )


def _accumulate_facet_fn(core, NAF_MNAFs, sg_off0, offs1, masks1, facet_size,
                         MNAF_BMNAFs):
    p = core._p

    def fold(NAF_MNAF, off1, mask1):
        NAF_BMNAF = finish_facet_math(
            p, core._Fb, facet_size, NAF_MNAF, off1, 1
        )
        NAF_BMNAF = _mask_along(p, NAF_BMNAF, mask1, 1)
        return add_to_facet_math(p, core.yN_size, core.N, NAF_BMNAF, sg_off0, 0)

    return MNAF_BMNAFs + jax.vmap(fold)(NAF_MNAFs, offs1, masks1)


_accumulate_facet_j = functools.partial(
    jax.jit, static_argnums=(0, 5), donate_argnums=6
)(_accumulate_facet_fn)


def accumulate_facet_batch(
    core, NAF_MNAFs, sg_off0, offs1, masks1, facet_size, MNAF_BMNAFs
):
    """Fold an evicted column accumulator into the per-facet accumulators.

    Axis-1 finish + mask, then axis-0 embed at the column's sg_off0
    (reference `accumulate_facet`, api_helper.py:155-179).
    """
    if _is_host(core):
        p = core._p
        for i, (NAF_MNAF, off1, mask1) in enumerate(
            zip(NAF_MNAFs, offs1, masks1)
        ):
            NAF_BMNAF = core.finish_facet(NAF_MNAF, int(off1), facet_size, 1)
            NAF_BMNAF = np.ascontiguousarray(
                _mask_along(p, NAF_BMNAF, np.asarray(mask1), 1)
            )
            core.add_to_facet(NAF_BMNAF, int(sg_off0), 0, out=MNAF_BMNAFs[i])
        return MNAF_BMNAFs
    return _accumulate_facet_j(
        core,
        NAF_MNAFs,
        jnp.asarray(sg_off0),
        jnp.asarray(offs1),
        jnp.asarray(masks1, core._Fb.dtype),
        facet_size,
        MNAF_BMNAFs,
    )


def _finish_facets_fn(core, MNAF_BMNAFs, offs0, masks0, facet_size):
    p = core._p

    def fin(MNAF_BMNAF, off0, mask0):
        facet = finish_facet_math(
            p, core._Fb, facet_size, MNAF_BMNAF, off0, 0
        )
        return _mask_along(p, facet, mask0, 0)

    return jax.vmap(fin)(MNAF_BMNAFs, offs0, masks0)


_finish_facets_j = functools.partial(jax.jit, static_argnums=(0, 4))(
    _finish_facets_fn
)


def finish_facets_batch(core, MNAF_BMNAFs, offs0, masks0, facet_size):
    """MNAF_BMNAFs [F, yN, yB] -> finished facets [F, yB, yB]
    (reference `finish_facet` wrapper, api_helper.py:182-197)."""
    if _is_host(core):
        p = core._p
        out = []
        for MNAF_BMNAF, off0, mask0 in zip(MNAF_BMNAFs, offs0, masks0):
            facet = core.finish_facet(MNAF_BMNAF, int(off0), facet_size, 0)
            out.append(_mask_along(p, facet, np.asarray(mask0), 0))
        return np.stack(out)
    return _finish_facets_j(
        core,
        MNAF_BMNAFs,
        jnp.asarray(offs0),
        jnp.asarray(masks0, core._Fb.dtype),
        facet_size,
    )


@functools.partial(jax.jit, static_argnums=(0, 5))
def _backward_all_j(
    core, subgrids, sg_offs, foffs, fmasks, facet_size
):
    offs0, offs1 = foffs
    masks0, masks1 = fmasks
    p = core._p
    F = offs0.shape[0]
    zeros_col = jnp.zeros(
        (F, core.xM_yN_size, core.yN_size) + subgrids.shape[4:],
        dtype=subgrids.dtype,
    )

    def one_column(MNAF_BMNAFs, xs):
        col_sgs, col_offs = xs
        NAF_MNAFs = _split_accumulate_fn(
            core, col_sgs, col_offs, (offs0, offs1), zeros_col
        )
        MNAF_BMNAFs = _accumulate_facet_fn(
            core, NAF_MNAFs, col_offs[0, 0], offs1, masks1, facet_size,
            MNAF_BMNAFs,
        )
        return MNAF_BMNAFs, None

    init = jnp.zeros(
        (F, core.yN_size, facet_size) + subgrids.shape[4:],
        dtype=subgrids.dtype,
    )
    MNAF_BMNAFs, _ = jax.lax.scan(one_column, init, (subgrids, sg_offs))
    return _finish_facets_fn(core, MNAF_BMNAFs, offs0, masks0, facet_size)


def backward_all_batch(
    core, subgrids, sg_offs, offs0, offs1, masks0, masks1, facet_size
):
    """The full backward cover as ONE program: facets [F, yB, yB].

    Scans over the C subgrid columns (inner scan over each column's S
    subgrids), folding column accumulators into the per-facet
    accumulators, then finishes all facets — one XLA dispatch for the
    whole subgrid->facet transform.

    :param subgrids: [C, S, xA, xA] stacked column-major subgrid data
    :param sg_offs: [C, S, 2] matching (off0, off1) pairs (off0 constant
        within a column)
    """
    if _is_host(core):
        MNAF_BMNAFs = np.zeros(
            (len(offs0), core.yN_size, facet_size), dtype=complex
        )
        for c in range(len(subgrids)):
            col = np.zeros(
                (len(offs0), core.xM_yN_size, core.yN_size), dtype=complex
            )
            col = split_accumulate_batch(
                core, subgrids[c], [tuple(o) for o in sg_offs[c]],
                offs0, offs1, col,
            )
            MNAF_BMNAFs = accumulate_facet_batch(
                core, col, sg_offs[c][0][0], offs1, masks1, facet_size,
                MNAF_BMNAFs,
            )
        return finish_facets_batch(
            core, MNAF_BMNAFs, offs0, masks0, facet_size
        )
    if isinstance(subgrids, (list, tuple)):
        subgrids = jnp.stack(
            [jnp.stack([core._prep(sg) for sg in col]) for col in subgrids]
        )
    rdt = core._Fb.dtype
    return _backward_all_j(
        core,
        subgrids,
        jnp.asarray(np.asarray(sg_offs)),
        (jnp.asarray(offs0), jnp.asarray(offs1)),
        (_as_real(masks0, rdt), _as_real(masks1, rdt)),
        facet_size,
    )
