"""Explicit shard_map + psum execution of the hot streaming kernels.

The GSPMD path (swiftly_tpu.parallel.batched with facet-sharded inputs)
lets XLA infer the collectives. This module is the explicit alternative:
the facet stack is mapped over the mesh's facet axis with `jax.shard_map`,
each device reduces its local facets' contributions, and one `lax.psum`
over ICI/DCN produces the subgrid — the deterministic, hand-placed
collective schedule for the reference's facet-contribution sum
(/root/reference/src/ska_sdp_exec_swiftly/api_helper.py:73-112, where the
sum is Dask worker-to-worker transfers + a task-side loop).

Forward (`subgrid_from_columns_sharded`):
  per-device: vmap over local facets -> local partial padded subgrid
  collective: psum over the facet axis     [the only cross-device traffic:
                                            one xM x xM buffer per subgrid]
  replicated: finish (iFFT + crop) + masks

Backward (`split_subgrid_sharded`):
  replicated: prepare_subgrid (pad + FFT) on every device
  per-device: vmap extract -> facet-sharded NAF_NAFs  [traffic: the xA x xA
                                            subgrid broadcast at placement]

Column/facet accumulation stays elementwise per facet (no collectives), so
the batched kernels handle it under either mode. The per-facet math bodies
are shared with the batched module (`facet_contrib_to_subgrid`,
`subgrid_contrib_to_facet`), so the two spmd modes cannot diverge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.core import prepare_subgrid_math
from .batched import (
    facet_contrib_to_subgrid,
    finish_masked_subgrid,
    subgrid_contrib_to_facet,
)
from .mesh import FACET_AXIS

__all__ = [
    "split_subgrid_sharded",
    "subgrid_from_columns_sharded",
]


# Bounded: long-lived processes sweeping many configurations must not pin
# every (core, mesh) pair's compiled executable forever. Evicted kernels
# simply recompile on next use.
@functools.lru_cache(maxsize=32)
def _forward_kernel(core, mesh, subgrid_size: int):
    """Build the jitted shard_map program for one (core, mesh, size)."""

    def body(NMBF_BFs, offs0, offs1, sg_offs, mask0, mask1):
        contrib = lambda NMBF_BF, foff0, foff1: facet_contrib_to_subgrid(
            core, NMBF_BF, foff0, foff1, sg_offs[1]
        )
        # Local reduction over this shard's facets, then one all-reduce.
        local = jnp.sum(jax.vmap(contrib)(NMBF_BFs, offs0, offs1), axis=0)
        summed = jax.lax.psum(local, FACET_AXIS)
        return finish_masked_subgrid(
            core, summed, sg_offs, subgrid_size, mask0, mask1
        )

    mapped = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(FACET_AXIS), P(FACET_AXIS), P(FACET_AXIS), P(), P(), P()),
        out_specs=P(),
    )
    return jax.jit(mapped)


def subgrid_from_columns_sharded(
    core, mesh, NMBF_BFs, offs0, offs1, sg_off0, sg_off1, subgrid_size, masks
):
    """Facet-sharded NMBF_BFs [F, m, yN] -> replicated subgrid [xA, xA].

    Same contract as ``batched.subgrid_from_columns_batch`` but with the
    facet reduction expressed as an explicit ``lax.psum`` over the mesh.
    """
    fn = _forward_kernel(core, mesh, subgrid_size)
    rdt = core._Fb.dtype
    return fn(
        NMBF_BFs,
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        jnp.asarray([sg_off0, sg_off1]),
        jnp.asarray(masks[0], rdt),
        jnp.asarray(masks[1], rdt),
    )


@functools.lru_cache(maxsize=32)
def _backward_kernel(core, mesh):
    def body(subgrid, sg_offs, offs0, offs1):
        prepped = prepare_subgrid_math(
            core._p, core.xM_size, subgrid, sg_offs
        )
        extract = lambda foff0, foff1: subgrid_contrib_to_facet(
            core, prepped, foff0, foff1
        )
        return jax.vmap(extract)(offs0, offs1)

    mapped = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), P(FACET_AXIS), P(FACET_AXIS)),
        out_specs=P(FACET_AXIS),
    )
    return jax.jit(mapped)


def split_subgrid_sharded(
    core, mesh, subgrid, sg_off0, sg_off1, offs0, offs1
):
    """Replicated subgrid [xA, xA] -> facet-sharded NAF_NAFs [F, m, m].

    Same contract as ``batched.split_subgrid_batch``; the subgrid is
    broadcast once, extraction is device-local per facet shard.
    """
    fn = _backward_kernel(core, mesh)
    return fn(
        core._prep(subgrid),
        jnp.asarray([sg_off0, sg_off1]),
        jnp.asarray(offs0),
        jnp.asarray(offs1),
    )
