"""Explicit shard_map + psum execution of the hot streaming kernels.

The GSPMD path (swiftly_tpu.parallel.batched with facet-sharded inputs)
lets XLA infer the collectives. This module is the explicit alternative:
the facet stack is mapped over the mesh's facet axis with `jax.shard_map`,
each device reduces its local facets' contributions, and one `lax.psum`
over ICI/DCN produces the subgrid — the deterministic, hand-placed
collective schedule for the reference's facet-contribution sum
(/root/reference/src/ska_sdp_exec_swiftly/api_helper.py:73-112, where the
sum is Dask worker-to-worker transfers + a task-side loop).

Forward (`subgrid_from_columns_sharded`):
  per-device: vmap over local facets -> local partial padded subgrid
  collective: psum over the facet axis     [the only cross-device traffic:
                                            one xM x xM buffer per subgrid]
  replicated: finish (iFFT + crop) + masks

The facet-axis reduction itself has two schedules (SWIFTLY_MESH_COLLECTIVE):
the blocking `lax.psum` above, or `ring_allreduce` — a reduce-scatter +
all-gather built from 2(n-1) `lax.ppermute` chunk rotations whose steps
overlap neighbouring compute instead of fencing it (same sum up to
reduction order; see docs/multichip.md "Collective schedules").

Backward (`split_subgrid_sharded`):
  replicated: prepare_subgrid (pad + FFT) on every device
  per-device: vmap extract -> facet-sharded NAF_NAFs  [traffic: the xA x xA
                                            subgrid broadcast at placement]

Column/facet accumulation stays elementwise per facet (no collectives), so
the batched kernels handle it under either mode. The per-facet math bodies
are shared with the batched module (`facet_contrib_to_subgrid`,
`subgrid_contrib_to_facet`), so the two spmd modes cannot diverge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

import numpy as np

from ..ops.core import prepare_subgrid_math
from .batched import (
    _accumulate_facet_fn,
    _as_real,
    _extract_columns_fn,
    _finish_facets_fn,
    _split_accumulate_fn,
    facet_contrib_to_subgrid,
    finish_masked_subgrid,
    subgrid_contrib_to_facet,
)
from .mesh import FACET_AXIS, mesh_size, resolve_collective, varying


def ring_allreduce(x, axis_name: str, n_shards: int | None = None):
    """Facet-axis all-reduce as a `ppermute` ring: reduce-scatter then
    all-gather, 2(n-1) neighbour rotations of a 1/n-size chunk.

    The buffer is flattened and split into n equal chunks (zero-padded to
    a multiple of n — exact, the pad never aliases real elements). Each
    shard owns one chunk's running sum; every reduce-scatter step rotates
    the partial one hop around the ring and folds in the local copy of
    the chunk now in flight, so after n-1 steps shard i holds the fully
    reduced chunk (i+1) % n. The all-gather phase rotates the finished
    chunks the rest of the way around. Per-step traffic is size/n vs the
    whole buffer for a blocking psum, and each step's `ppermute` has no
    data dependence on neighbouring column contractions — XLA is free to
    run the rotation concurrently with the next facet block's local
    einsum (the overlap the mesh engine's triple-buffer feed completes).

    Exactness: every shard accumulates each chunk in the SAME ring
    order, so the result is deterministic and shard-count-reproducible,
    but the reduction ORDER differs from psum's tree — expect float
    rounding drift within the documented tolerance (docs/multichip.md),
    not bit-identity. Zero-padded facet shards (9-over-8 cover) add
    exact zeros, so padding never widens the drift.
    """
    n = int(n_shards) if n_shards is not None else jax.lax.psum(1, axis_name)
    if n <= 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    flat = x.reshape(-1)
    per = -(-flat.size // n)
    pad = n * per - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    parts = flat.reshape(n, per)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def chunk(k):
        return jax.lax.dynamic_index_in_dim(parts, k % n, 0, keepdims=False)

    with jax.named_scope("swiftly/mesh.ring_step"):
        acc = chunk(idx)
        for s in range(1, n):  # reduce-scatter
            acc = jax.lax.ppermute(acc, axis_name, perm)
            acc = acc + chunk(idx - s)
        own = (idx + 1) % n  # shard i finishes chunk (i+1) % n
        gathered = jnp.zeros((n, per), acc.dtype)
        gathered = jax.lax.dynamic_update_index_in_dim(gathered, acc, own, 0)
        cur = acc
        for s in range(1, n):  # all-gather
            cur = jax.lax.ppermute(cur, axis_name, perm)
            gathered = jax.lax.dynamic_update_index_in_dim(
                gathered, cur, (own - s) % n, 0
            )
    out = gathered.reshape(-1)
    if pad:
        out = out[: x.size]
    return out.reshape(x.shape)


def collective_sum(x, axis_name: str, collective: str = "psum",
                   n_shards: int | None = None):
    """The facet-axis reduction under the selected schedule: blocking
    `lax.psum` (XLA all-reduce) or the `ppermute` ring."""
    if collective == "ring":
        return ring_allreduce(x, axis_name, n_shards)
    return jax.lax.psum(x, axis_name)


def _mapped(fn, mesh, in_specs, out_specs, check_rep: bool = True):
    """shard_map with an optional check_rep=False escape hatch.

    Ring kernels mix `ppermute`/`axis_index` results into replicated
    outputs — correct (every shard materialises the same gathered sum)
    but not provable by the replication checker, so they opt out the
    same way streamed.py's `_shmap` does. psum kernels keep the check.
    """
    if check_rep:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    except TypeError:  # pragma: no cover - jax without check_rep kwarg
        return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _scoped(name, fn):
    """Wrap a kernel body in ``jax.named_scope`` so its compiled HLO ops
    carry the stage name (shared vocabulary with the host-side stage
    timers in ``obs.metrics``; zero runtime cost — trace-time only)."""

    def wrapped(*args, **kwargs):
        with jax.named_scope(name):
            return fn(*args, **kwargs)

    return wrapped


__all__ = [
    "backward_all_sharded",
    "collective_sum",
    "forward_all_sharded",
    "ring_allreduce",
    "split_accumulate_sharded",
    "split_subgrid_sharded",
    "subgrid_from_columns_sharded",
    "subgrids_from_columns_sharded",
]


# Bounded: long-lived processes sweeping many configurations must not pin
# every (core, mesh) pair's compiled executable forever. Evicted kernels
# simply recompile on next use.
@functools.lru_cache(maxsize=32)
def _forward_kernel(core, mesh, subgrid_size: int, collective: str = "psum"):
    """Build the jitted shard_map program for one (core, mesh, size,
    collective)."""
    n_shards = mesh_size(mesh)

    def body(NMBF_BFs, offs0, offs1, sg_offs, mask0, mask1):
        contrib = lambda NMBF_BF, foff0, foff1: facet_contrib_to_subgrid(
            core, NMBF_BF, foff0, foff1, sg_offs[1]
        )
        # Local reduction over this shard's facets, then one all-reduce.
        local = jnp.sum(jax.vmap(contrib)(NMBF_BFs, offs0, offs1), axis=0)
        summed = collective_sum(local, FACET_AXIS, collective, n_shards)
        return finish_masked_subgrid(
            core, summed, sg_offs, subgrid_size, mask0, mask1
        )

    mapped = _mapped(
        _scoped("swiftly/fwd.column_pass", body),
        mesh=mesh,
        in_specs=(P(FACET_AXIS), P(FACET_AXIS), P(FACET_AXIS), P(), P(), P()),
        out_specs=P(),
        check_rep=collective != "ring",
    )
    return jax.jit(mapped)


def subgrid_from_columns_sharded(
    core, mesh, NMBF_BFs, offs0, offs1, sg_off0, sg_off1, subgrid_size, masks
):
    """Facet-sharded NMBF_BFs [F, m, yN] -> replicated subgrid [xA, xA].

    Same contract as ``batched.subgrid_from_columns_batch`` but with the
    facet reduction expressed as an explicit collective over the mesh
    (``lax.psum`` or the `ppermute` ring, per SWIFTLY_MESH_COLLECTIVE —
    resolved at call time so psum and ring can run in one process).
    """
    fn = _forward_kernel(
        core, mesh, subgrid_size, resolve_collective(mesh_size(mesh))
    )
    rdt = core._Fb.dtype
    return fn(
        NMBF_BFs,
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        jnp.asarray([sg_off0, sg_off1]),
        jnp.asarray(masks[0], rdt),
        jnp.asarray(masks[1], rdt),
    )


@functools.lru_cache(maxsize=32)
def _backward_kernel(core, mesh):
    def body(subgrid, sg_offs, offs0, offs1):
        prepped = prepare_subgrid_math(
            core._p, core.xM_size, subgrid, sg_offs
        )
        extract = lambda foff0, foff1: subgrid_contrib_to_facet(
            core, prepped, foff0, foff1
        )
        return jax.vmap(extract)(offs0, offs1)

    mapped = _shard_map(
        _scoped("swiftly/bwd.column_pass", body),
        mesh=mesh,
        in_specs=(P(), P(), P(FACET_AXIS), P(FACET_AXIS)),
        out_specs=P(FACET_AXIS),
    )
    return jax.jit(mapped)


def split_subgrid_sharded(
    core, mesh, subgrid, sg_off0, sg_off1, offs0, offs1
):
    """Replicated subgrid [xA, xA] -> facet-sharded NAF_NAFs [F, m, m].

    Same contract as ``batched.split_subgrid_batch``; the subgrid is
    broadcast once, extraction is device-local per facet shard.
    """
    fn = _backward_kernel(core, mesh)
    return fn(
        core._prep(subgrid),
        jnp.asarray([sg_off0, sg_off1]),
        jnp.asarray(offs0),
        jnp.asarray(offs1),
    )


# ---------------------------------------------------------------------------
# Fused column/whole-cover mesh programs
#
# The per-subgrid kernels above cost one dispatch (and one psum) per
# subgrid — dispatch-latency-bound on remote-attached devices, exactly the
# disease the single-device fused paths cured. These kernels batch a whole
# column (or the whole cover) into ONE shard_map program with ONE psum per
# column: per-device work scales with local facets (F/d), cross-device
# traffic is one [S, xM, xM] buffer per column.
# ---------------------------------------------------------------------------


def _column_partial_then_finish(core, cols, offs0, offs1, off0, col_sg_offs1,
                                col_m0, col_m1, subgrid_size,
                                collective="psum", n_shards=None):
    """Local facet reduction for all S subgrids of one column, one
    collective, then the (replicated) finishes. Shared by the column and
    whole-cover kernels."""

    def partial_sg(off1):
        contrib = lambda NMBF_BF, foff0, foff1: facet_contrib_to_subgrid(
            core, NMBF_BF, foff0, foff1, off1
        )
        return jnp.sum(jax.vmap(contrib)(cols, offs0, offs1), axis=0)

    partial = jax.vmap(partial_sg)(col_sg_offs1)  # [S, xM, xM] local
    # one collective per column: blocking all-reduce or ppermute ring
    summed = collective_sum(partial, FACET_AXIS, collective, n_shards)

    def fin(s, off1, m0, m1):
        return finish_masked_subgrid(
            core, s, jnp.stack([off0, off1]), subgrid_size, m0, m1
        )

    return jax.vmap(fin)(summed, col_sg_offs1, col_m0, col_m1)


@functools.lru_cache(maxsize=32)
def _forward_column_kernel(core, mesh, subgrid_size: int,
                           collective: str = "psum"):
    """One column's S subgrids in one program: single collective per
    column (all-reduce or ppermute ring)."""
    n_shards = mesh_size(mesh)

    def body(NMBF_BFs, offs0, offs1, off0, sg_offs1, masks0, masks1):
        return _column_partial_then_finish(
            core, NMBF_BFs, offs0, offs1, off0, sg_offs1, masks0, masks1,
            subgrid_size, collective, n_shards,
        )

    mapped = _mapped(
        _scoped("swiftly/fwd.column_pass", body),
        mesh=mesh,
        in_specs=(
            P(FACET_AXIS), P(FACET_AXIS), P(FACET_AXIS), P(), P(), P(), P(),
        ),
        out_specs=P(),
        check_rep=collective != "ring",
    )
    return jax.jit(mapped)


def subgrids_from_columns_sharded(
    core, mesh, NMBF_BFs, offs0, offs1, sg_offs_list, subgrid_size, masks_list
):
    """All subgrids of one column on the mesh: [S, xA, xA], one dispatch.

    Mesh analogue of ``batched.subgrids_from_columns_batch``: local facet
    reduction + a single collective for the whole stacked column.
    """
    fn = _forward_column_kernel(
        core, mesh, subgrid_size, resolve_collective(mesh_size(mesh))
    )
    rdt = core._Fb.dtype
    return fn(
        NMBF_BFs,
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        jnp.asarray(sg_offs_list[0][0]),
        jnp.asarray([so[1] for so in sg_offs_list]),
        jnp.asarray(np.stack([m[0] for m in masks_list]), rdt),
        jnp.asarray(np.stack([m[1] for m in masks_list]), rdt),
    )


@functools.lru_cache(maxsize=32)
def _forward_all_kernel(core, mesh, subgrid_size: int,
                        collective: str = "psum"):
    """The whole forward cover as ONE shard_map program.

    Scan over columns; per column: extract the local facets' column
    blocks, reduce their contributions for all S subgrids, one
    collective, finish. O(1) dispatches and O(columns) collectives for
    the entire transform — the mesh analogue of
    ``batched.forward_all_batch``. Under the ring schedule the scanned
    column's `ppermute` rotations carry no dependence on the next
    column's extraction/contraction, so the rotation overlaps the next
    column's local work instead of fencing it.
    """
    n_shards = mesh_size(mesh)

    def body(BF_Fs, offs0, offs1, col_offs0, sg_offs1, masks0, masks1):
        def one_column(_, xs):
            off0, col_sg_offs1, col_m0, col_m1 = xs
            cols = _extract_columns_fn(core, BF_Fs, off0, offs1)
            return None, _column_partial_then_finish(
                core, cols, offs0, offs1, off0, col_sg_offs1, col_m0,
                col_m1, subgrid_size, collective, n_shards,
            )

        _, subgrids = jax.lax.scan(
            one_column, None, (col_offs0, sg_offs1, masks0, masks1)
        )
        return subgrids

    mapped = _mapped(
        _scoped("swiftly/fwd.fused_forward", body),
        mesh=mesh,
        in_specs=(
            P(FACET_AXIS), P(FACET_AXIS), P(FACET_AXIS), P(), P(), P(), P(),
        ),
        out_specs=P(),
        check_rep=collective != "ring",
    )
    return jax.jit(mapped)


def forward_all_sharded(
    core, mesh, BF_Fs, offs0, offs1, col_offs0, sg_offs1, subgrid_size,
    masks0, masks1,
):
    """The full forward cover on the mesh: [C, S, xA, xA], one dispatch.

    Same contract as ``batched.forward_all_batch`` with the facet
    reduction as one explicit collective per scanned column.
    """
    fn = _forward_all_kernel(
        core, mesh, subgrid_size, resolve_collective(mesh_size(mesh))
    )
    rdt = core._Fb.dtype
    return fn(
        BF_Fs,
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        jnp.asarray(col_offs0),
        jnp.asarray(sg_offs1),
        _as_real(masks0, rdt),
        _as_real(masks1, rdt),
    )


@functools.lru_cache(maxsize=32)
def _backward_column_kernel(core, mesh):
    """Fold one column's stacked subgrids into the facet-sharded
    per-column accumulator — all facet work is local (the subgrids are
    replicated; no collectives at all)."""

    def body(subgrids, sg_offs_arr, offs0, offs1, NAF_MNAFs):
        return _split_accumulate_fn(
            core, subgrids, sg_offs_arr, (offs0, offs1), NAF_MNAFs
        )

    mapped = _shard_map(
        _scoped("swiftly/bwd.column_pass", body),
        mesh=mesh,
        in_specs=(
            P(), P(), P(FACET_AXIS), P(FACET_AXIS), P(FACET_AXIS),
        ),
        out_specs=P(FACET_AXIS),
    )
    return jax.jit(mapped, donate_argnums=4)


def split_accumulate_sharded(
    core, mesh, subgrids, sg_offs_list, offs0, offs1, NAF_MNAFs
):
    """Mesh analogue of ``batched.split_accumulate_batch``: one dispatch
    folds a whole column of subgrids into its facet-sharded accumulator
    (donated)."""
    if isinstance(subgrids, (list, tuple)):
        subgrids = jnp.stack([core._prep(sg) for sg in subgrids])
    fn = _backward_column_kernel(core, mesh)
    return fn(
        subgrids,
        jnp.asarray(sg_offs_list),
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        NAF_MNAFs,
    )


@functools.lru_cache(maxsize=32)
def _backward_all_kernel(core, mesh, facet_size: int):
    """The whole backward cover as ONE shard_map program.

    Subgrids arrive replicated; every facet-side op (extract, accumulate,
    finish) is local to the facet shard, so the program needs NO
    collectives — the facet stack materialises sharded (out_specs
    P(facet)). Mesh analogue of ``batched.backward_all_batch``.
    """

    def body(subgrids, sg_offs, offs0, offs1, masks0, masks1):
        F = offs0.shape[0]
        # scan carries must be tagged shard-varying up front: their
        # updates mix in the facet-sharded offsets/masks
        zeros_col = varying(
            jnp.zeros(
                (F, core.xM_yN_size, core.yN_size) + subgrids.shape[4:],
                dtype=subgrids.dtype,
            ),
            FACET_AXIS,
        )

        def one_column(MNAF_BMNAFs, xs):
            col_sgs, col_offs = xs
            NAF_MNAFs = _split_accumulate_fn(
                core, col_sgs, col_offs, (offs0, offs1), zeros_col
            )
            MNAF_BMNAFs = _accumulate_facet_fn(
                core, NAF_MNAFs, col_offs[0, 0], offs1, masks1, facet_size,
                MNAF_BMNAFs,
            )
            return MNAF_BMNAFs, None

        init = varying(
            jnp.zeros(
                (F, core.yN_size, facet_size) + subgrids.shape[4:],
                dtype=subgrids.dtype,
            ),
            FACET_AXIS,
        )
        MNAF_BMNAFs, _ = jax.lax.scan(one_column, init, (subgrids, sg_offs))
        return _finish_facets_fn(core, MNAF_BMNAFs, offs0, masks0, facet_size)

    mapped = _shard_map(
        _scoped("swiftly/bwd.fused_backward", body),
        mesh=mesh,
        in_specs=(
            P(), P(), P(FACET_AXIS), P(FACET_AXIS), P(FACET_AXIS),
            P(FACET_AXIS),
        ),
        out_specs=P(FACET_AXIS),
    )
    return jax.jit(mapped)


def backward_all_sharded(
    core, mesh, subgrids, sg_offs, offs0, offs1, masks0, masks1, facet_size
):
    """The full backward cover on the mesh: facets [F, yB, yB], one
    dispatch, zero collectives (facet work is shard-local).

    Same contract as ``batched.backward_all_batch``.
    """
    if isinstance(subgrids, (list, tuple)):
        subgrids = jnp.stack(
            [jnp.stack([core._prep(sg) for sg in col]) for col in subgrids]
        )
    fn = _backward_all_kernel(core, mesh, facet_size)
    rdt = core._Fb.dtype
    return fn(
        subgrids,
        jnp.asarray(np.asarray(sg_offs)),
        jnp.asarray(offs0),
        jnp.asarray(offs1),
        _as_real(masks0, rdt),
        _as_real(masks1, rdt),
    )
