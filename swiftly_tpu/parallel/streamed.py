"""Out-of-core streamed execution: transforms larger than device memory.

The whole-cover batched path (`swiftly_tpu.parallel.batched`) keeps the
prepared facet stack `BF_Fs` [F, yN, yB] resident on device; at N = 32768
that is ~13 GiB and at N = 65536 ~53 GiB — beyond a single chip's HBM.
This module runs the same transform with bounded device residency by
streaming through host memory, which is the TPU realisation of the
reference's design goal of "minimising memory residency" while "generating
arbitrary grid chunks" (reference docs/src/index.rst:11-12; the column
intermediates mirror its LRU-bounded NMBF_BF / NAF_MNAF working sets,
api.py:300-324,402-438).

Forward (facets -> subgrids), two device passes:

1. *Facet pass* — with `residency="host"`: stream facet column-blocks
   [F, yB, Cb] to the device; prepare along axis 0 and extract the
   contribution rows for EVERY subgrid column offset in one program ->
   [K, F, m, Cb], landing in the host-RAM `NMBF_all` buffer
   [K, F, m, yB] (total size equals one prepared facet stack re-indexed
   by column: K*m ≈ yN). With `residency="device"` the facet pass is a
   sampled DFT instead: facets upload once and stay in HBM, and each
   group of columns' contribution rows is one einsum — no NMBF buffer
   exists (see `_facet_pass_sampled_j`).
2. *Column pass* — per subgrid column k: take the column's [F, m, yB]
   rows (host upload, or a slice of the sampled group buffer), prepare
   along axis 1, extract/accumulate/finish all S subgrids of the column
   in one program -> [S, xA, xA].

Backward (subgrids -> facets) is the exact dual:

1. *Column pass* — per column: fold the column's subgrids into a
   NAF_MNAF accumulator (scan), finish axis 1 + mask -> NAF_BMNAF
   [F, m, yB], accumulated per-column into `NAF_all` [K, F, m, yB].
2. *Facet pass* — stream `NAF_all` column-blocks [K, F, m, Cb] back;
   embed each column's rows at its offset (axis-0 add_to_facet), sum
   over columns, finish axis 0 + mask -> facet blocks [F, yB, Cb].

Peak device residency is a handful of [F, m, yN]-scale blocks (~1 GiB at
N = 32768) regardless of N; host residency is one [K, F, m, yB] buffer.
All stage programs are built from the same `*_math` primitives as the
batched path, so streamed and batched results are numerically identical.
"""

from __future__ import annotations

import functools
import logging
import time

import numpy as np

logger = logging.getLogger(__name__)


def _rss_gib():
    """Resident set size in GiB (cheap /proc read; 0.0 if unavailable)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * 4096 / 2**30
    except Exception:  # pragma: no cover - non-linux
        return 0.0

from ..ops.core import (
    add_to_facet_math,
    add_to_subgrid_math,
    extract_from_facet_math,
    finish_facet_math,
    prepare_facet_math,
)
from .batched import (
    _mask_along,
    facet_contrib_to_subgrid,
    finish_masked_subgrid,
)

__all__ = ["StreamedForward", "StreamedBackward", "feed_backward_passes"]


def _planar(core):
    return core.backend == "planar"


def _tail(core):
    """Trailing data-layout axes: the planar backend carries (re, im)."""
    return (2,) if _planar(core) else ()


def _np_dtype(core):
    return np.dtype(core.dtype)


def _real_plane_or_none(core, data):
    """The facet's real plane as [yB, yB] float, or None if it has any
    imaginary content (or the backend is not planar).

    Point-source facet models are exactly real; detecting that here lets
    the sampled-DFT path store/upload HALF the bytes and skip half its
    einsums. One full host-side pass over the data — the same cost the
    planar layout conversion pays anyway.
    """
    if not _planar(core):
        return None
    data = np.asarray(data)
    if data.ndim and data.shape[-1] == 2 and not np.iscomplexobj(data):
        if np.any(data[..., 1]):
            return None
        return np.asarray(data[..., 0], dtype=_np_dtype(core))
    if np.iscomplexobj(data) and np.any(data.imag):
        return None
    return np.asarray(data.real, dtype=_np_dtype(core))


def _to_host_layout(core, data):
    """One facet/subgrid as a host numpy array in device layout."""
    if _planar(core):
        data = np.asarray(data)
        if data.ndim and data.shape[-1] == 2 and not np.iscomplexobj(data):
            return np.asarray(data, dtype=_np_dtype(core))
        # assign planes directly (casting on write): no full-precision
        # stacked intermediate — this path handles multi-GiB facets
        out = np.empty(data.shape + (2,), dtype=_np_dtype(core))
        out[..., 0] = data.real
        out[..., 1] = data.imag
        return out
    return np.asarray(data, dtype=_np_dtype(core))


import jax  # noqa: E402

from jax.sharding import PartitionSpec as _P  # noqa: E402

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map  # noqa: E402
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map  # noqa: E402

from .mesh import (  # noqa: E402
    FACET_AXIS,
    mesh_size as _mesh_size,
    resolve_collective as _resolve_collective_env,
    varying,
)
from .sharded import collective_sum as _collective_sum  # noqa: E402

from ..obs import metrics as _metrics  # noqa: E402
from ..obs import trace as _trace  # noqa: E402
from ..resilience import degrade as _degrade  # noqa: E402
from ..resilience.faults import fault_point as _fault_point  # noqa: E402
from ..resilience.retry import retry_transient as _retry  # noqa: E402


def _scoped(name, fn):
    """Wrap a stage body in ``jax.named_scope`` so its compiled HLO ops
    carry the stage name — the trace-side half of the shared stage
    vocabulary (the host-side half is ``obs.metrics``' TraceAnnotation
    of the same name minus the "swiftly/" prefix). Zero runtime cost:
    the scope exists only at trace time, as op-name metadata."""

    def wrapped(*args, **kwargs):
        with jax.named_scope(name):
            return fn(*args, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# Stage programs
#
# Each stage has a pure body builder (`*_fn`) shared by the single-device
# jit (`*_j`) and the facet-sharded shard_map variant (`*_sharded`). On a
# mesh every per-facet op is shard-local; the only collective in the whole
# streamed pipeline is one psum per subgrid column in the forward column
# pass (`axis_name` below).
# ---------------------------------------------------------------------------


def _jit(static=(), donate=()):
    return functools.partial(
        jax.jit, static_argnums=static, donate_argnums=donate
    )


def _shmap(fn, mesh, in_specs, out_specs, donate=()):
    # check_rep=False: jax has no replication rule for pallas_call, so
    # the rep checker rejects any body that lowers the fused colpass
    # kernel (SWIFTLY_COLPASS=pallas under the mesh engine). The psum
    # placement is pinned by the body builders themselves.
    try:
        mapped = _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    except TypeError:  # pragma: no cover - jax without check_rep kwarg
        mapped = _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    return jax.jit(mapped, donate_argnums=donate)


def _facet_pass_fwd_fn(core):
    """facet block [F, yB', Cb] -> contribution rows [K, F, m, Cb]."""
    p = core._p

    def fn(facet_block, foffs0, col_offs0):
        def per_facet(fb, off0):
            prep = prepare_facet_math(p, core._Fb, core.yN_size, fb, off0, 0)

            def per_col(sg_off0):
                return extract_from_facet_math(
                    p, core.xM_yN_size, core.N, core.yN_size, prep, sg_off0, 0
                )

            return jax.vmap(per_col)(col_offs0)  # [K, m, Cb]

        out = jax.vmap(per_facet)(facet_block, foffs0)  # [F, K, m, Cb]
        return jax.numpy.swapaxes(out, 0, 1)  # [K, F, m, Cb]

    return fn


@functools.lru_cache(maxsize=None)
def _facet_pass_fwd_j(core):
    return _jit()(
        _scoped("swiftly/fwd.facet_pass", _facet_pass_fwd_fn(core))
    )


@functools.lru_cache(maxsize=None)
def _facet_pass_fwd_sharded(core, mesh):
    """Facet-sharded forward facet pass (all ops shard-local)."""
    return _shmap(
        _scoped("swiftly/fwd.facet_pass", _facet_pass_fwd_fn(core)), mesh,
        in_specs=(_P(FACET_AXIS), _P(FACET_AXIS), _P()),
        out_specs=_P(None, FACET_AXIS),
    )


# -- operator-matrix (einsum) column pass -----------------------------------
#
# Every per-facet op in the forward column pass after the axis-1 prep is
# LINEAR with a statically-shaped [xM, m] operator: the axis-0 chain
# fft -> roll -> Fn window -> wrapped_embed (`add_to_subgrid_math`) is a
# matrix A0_f, the axis-1 chain a matrix op1_f, and the finish iFFTs fold
# into them (iFFT along an axis commutes with cropping the OTHER axis).
# The whole column pass then collapses to two big einsums,
#
#   H    = A0_f @ NMBF_BF_f                  [F, xM, yN]   (shared by all S)
#   P_s  = sum_f gather_s(H_f) @ op1_f^T     [xM, xM]      (K = F*m)
#
# and the per-subgrid finish is a crop + mask (no FFT left). Versus the
# per-facet chain this roughly doubles the matmul FLOPs but removes the
# scan-over-facets accumulator traffic, the per-(facet, subgrid) rolls and
# embeds, and the m-sized matmul tiles that ran at ~9% of the MXU ceiling
# (measured, scripts/roofline.py): the K = F*m contraction folds the facet
# reduction into the MXU. The operators are built IN-TRACE by applying the
# existing `*_math` chain to an identity block — correctness by
# construction, ~1 ms per program, and both spmd modes reuse the body.
#
# `SWIFTLY_COLPASS` selects the body (einsum|fft|pallas|auto, default
# auto; read at TRACE time like SWIFTLY_PRECISION — the lru-cached jits
# bake it in). "auto" resolves per program via
# `utils.flops.resolve_colpass`: the fused Pallas kernel on TPU (the
# whole per-subgrid triple product A0 @ Xn @ B1 as one grid program,
# `ops.pallas_kernels.colpass_pallas` — no [F, xM, yN] H transient, no
# per-einsum dispatch gaps), einsum elsewhere (it measured faster than
# the fft chain at EVERY forward shape tried, resident full-stack AND
# Fg=1 slabs). The BACKWARD pass (`resolve_colpass_bwd`) follows the
# same auto rule (pallas on TPU, einsum otherwise).


from ..utils.flops import (  # noqa: E402
    resolve_colpass as _resolve_colpass,
    resolve_colpass_bwd as _resolve_colpass_bwd,
)


def _colpass_sblock() -> int:
    """Subgrids per einsum block: bounds the [Sb, F, xM, m] gather
    transient. Default 256 covers every catalogue column in ONE block
    (S <= 293 at 128k) — measured 13% faster than Sb=64 at 32k (the
    lax.map blocks padded the short tail and serialized); the knob
    remains for configs whose [S, F, xM, m] gather would not fit."""
    import os

    return max(1, int(os.environ.get("SWIFTLY_COLPASS_SBLOCK", "256")))


def _colpass_blocks():
    """(bm, bn, bk) tile sizes for the fused Pallas column-pass kernel
    (`SWIFTLY_COLPASS_BM/BN/BK`, default 256 each — xM/m fit in one or
    two MXU-aligned tiles at every catalogue scale). Read at TRACE time;
    `plan/autotune.refit` learns measured-best blocks from artifact
    history and `scripts/plan_explain.py --colpass` prints them so
    operators can export the env."""
    import os

    return (
        max(8, int(os.environ.get("SWIFTLY_COLPASS_BM", "256"))),
        max(8, int(os.environ.get("SWIFTLY_COLPASS_BN", "256"))),
        max(8, int(os.environ.get("SWIFTLY_COLPASS_BK", "256"))),
    )


def _ceinsum(core, spec, a, b):
    """Complex einsum (spec written for the logical axes): planar arrays
    contract via 4 real MXU einsums, complex backends directly."""
    import jax.numpy as jnp

    if _planar(core):
        from ..ops.planar_backend import _cmatmul

        outr, outi = _cmatmul(
            a[..., 0], a[..., 1], (b[..., 0], b[..., 1]), spec, a.dtype
        )
        return jnp.stack([outr, outi], axis=-1)
    return jnp.einsum(spec, a, b)


def _colpass_operators(core, foffs0, foffs1):
    """Forward column-pass operators, built in-trace from an identity.

    A0 [F, xM, m(,2)]: axis-0 `add_to_subgrid_math` with the finish iFFT
    folded along the output axis. B1 [F, m, xM(,2)]: the axis-1 operator
    in row-basis layout (B1[f, j, b] = op1_f[b, j]), iFFT folded, so the
    stage-2 contraction is `X[..., j] . B1[f, j, b]`.
    """
    import jax.numpy as jnp

    p = core._p
    m, xM = core.xM_yN_size, core.xM_size
    if _planar(core):
        eye = (
            jnp.zeros((m, m, 2), core.dtype)
            .at[:, :, 0]
            .set(jnp.eye(m, dtype=core.dtype))
        )
    else:
        eye = jnp.eye(m, dtype=core.dtype)

    def a0(off0):
        A = add_to_subgrid_math(p, core._Fn, xM, core.N, eye, off0, 0)
        return p.ifft(A, 0)

    def b1(off1):
        B = add_to_subgrid_math(p, core._Fn, xM, core.N, eye, off1, 1)
        return p.ifft(B, 1)

    return jax.vmap(a0)(foffs0), jax.vmap(b1)(foffs1)


def _crop_masked_subgrid(core, P, sg_offs, subgrid_size, mask0, mask1):
    """Finish an IMAGE-space padded subgrid: crop both axes + masks (the
    iFFTs already live in the einsum operators)."""
    p = core._p
    out = p.wrapped_extract(P, subgrid_size, sg_offs[0], 0)
    out = p.wrapped_extract(out, subgrid_size, sg_offs[1], 1)
    out = _mask_along(p, out, mask0, 0)
    return _mask_along(p, out, mask1, 1)


def _blocked_collective(block, sg_offs, axis_name, collective, n_shards):
    """Run the Sb-blocked per-column contraction and reduce the facet
    axis. psum: lax.map over blocks, one blocking all-reduce at the end
    (the existing schedule). ring: unrolled block loop with each block
    ring-reduced the moment its contraction finishes — block k's chunk
    rotations are data-independent of block k+1's contraction, so XLA
    schedules the `ppermute` steps concurrently with the next block's
    local einsum/Pallas work instead of fencing after all of them."""
    import jax.numpy as jnp

    S = sg_offs.shape[0]
    Sb = min(_colpass_sblock(), S)
    nb = -(-S // Sb)
    Sb = -(-S // nb)  # rebalanced: pad < nb, never a near-full block
    if nb == 1:
        P = block(sg_offs)
        if axis_name is not None:
            P = _collective_sum(P, axis_name, collective, n_shards)
        return P
    pad = nb * Sb - S
    so_p = (
        jnp.concatenate([sg_offs, jnp.repeat(sg_offs[-1:], pad, 0)])
        if pad
        else sg_offs
    )
    so_b = so_p.reshape((nb, Sb) + so_p.shape[1:])
    if axis_name is not None and collective == "ring":
        parts = [
            _collective_sum(block(so_b[i]), axis_name, "ring", n_shards)
            for i in range(nb)
        ]
        return jnp.concatenate(parts, axis=0)[:S]
    P = jax.lax.map(block, so_b)
    P = P.reshape((nb * Sb,) + P.shape[2:])[:S]
    if axis_name is not None:
        P = _collective_sum(P, axis_name, collective, n_shards)
    return P


def _colpass_einsum_body(
    core, subgrid_size, ops, NMBF, foffs1, sg_offs, masks0, masks1,
    axis_name=None, finish=True, collective="psum", n_shards=None,
):
    """One column through the einsum column pass, with prebuilt `ops`
    (so group callers hoist the operator build out of their column loop).

    ``collective`` picks the facet-axis reduction: one blocking psum per
    column, or the `ppermute` ring — multi-block columns ring-reduce
    each Sb block as soon as its contraction finishes (unrolled loop
    instead of lax.map), so block k's chunk rotation overlaps block
    k+1's local einsum in the emitted schedule.
    """
    import jax.numpy as jnp

    p = core._p
    m, yN = core.xM_yN_size, core.yN_size
    A0, B1 = ops

    def prep1(x, off1):
        return prepare_facet_math(p, core._Fb, yN, x, off1, 1)

    NMBF_BF = jax.vmap(prep1)(NMBF, foffs1)  # [F, m, yN(,2)]
    H = _ceinsum(core, "fai,fij->faj", A0, NMBF_BF)  # [F, xM, yN(,2)]

    def block(so_blk):
        def gather(so):
            return extract_from_facet_math(
                p, m, core.N, yN, H, so[1], 2
            )  # [F, xM, m(,2)]

        X = jax.vmap(gather)(so_blk)  # [Sb, F, xM, m(,2)]
        return _ceinsum(core, "sfaj,fjb->sab", X, B1)  # [Sb, xM, xM(,2)]

    P = _blocked_collective(block, sg_offs, axis_name, collective, n_shards)
    if not finish:
        return P

    def fin(Pi, so, m0, m1):
        return _crop_masked_subgrid(core, Pi, so, subgrid_size, m0, m1)

    return jax.vmap(fin)(P, sg_offs, masks0, masks1)


def _colpass_pallas_body(
    core, subgrid_size, ops, NMBF, foffs1, sg_offs, masks0, masks1,
    axis_name=None, finish=True, interpret=None, collective="psum",
    n_shards=None,
):
    """One column through the FUSED Pallas column pass.

    The same contraction as `_colpass_einsum_body`, reassociated per
    subgrid: P_s = Σ_f A0_f @ Xn_sf @ B1_f, where Xn_sf gathers the
    subgrid's m columns from NMBF_BF directly — the gather acts on the
    output (j) axis of H = A0 @ NMBF_BF, so it commutes past the
    stage-1 contraction and the [F, xM, yN] H transient (~2.4 GB at
    128k) never materialises; the gather transient shrinks from
    [Sb, F, xM, m] to [Sb, F, m, m]. Prepare matmul, K = F*m operator
    contraction and the complex recombination run as ONE grid program
    with the output tile resident in VMEM (`colpass_pallas`,
    reduce_f=True). Pre-finish partials and the crop finish are
    identical to the einsum body's (image space), so the two bodies are
    drop-in interchangeable for every caller — including the group
    step/finish pairing and the shard-local psum placement.
    """
    import jax.numpy as jnp

    from ..ops.pallas_kernels import colpass_pallas, pallas_interpret

    p = core._p
    m, yN = core.xM_yN_size, core.yN_size
    A0, B1 = ops
    if interpret is None:
        interpret = pallas_interpret()
    bm, bn, bk = _colpass_blocks()

    def prep1(x, off1):
        return prepare_facet_math(p, core._Fb, yN, x, off1, 1)

    NMBF_BF = jax.vmap(prep1)(NMBF, foffs1)  # [F, m, yN, 2]

    def block(so_blk):
        def gather(so):
            return extract_from_facet_math(
                p, m, core.N, yN, NMBF_BF, so[1], 2
            )  # [F, m, m, 2]

        Xn = jax.vmap(gather)(so_blk)  # [Sb, F, m, m, 2]
        Pr, Pi = colpass_pallas(
            A0[..., 0], A0[..., 1],
            Xn[..., 0], Xn[..., 1],
            B1[..., 0], B1[..., 1],
            reduce_f=True, bm=bm, bn=bn, bk=bk, interpret=interpret,
        )
        return jnp.stack([Pr, Pi], axis=-1)  # [Sb, xM, xM, 2]

    P = _blocked_collective(block, sg_offs, axis_name, collective, n_shards)
    if not finish:
        return P

    def fin(Pi_, so, m0, m1):
        return _crop_masked_subgrid(core, Pi_, so, subgrid_size, m0, m1)

    return jax.vmap(fin)(P, sg_offs, masks0, masks1)


def _column_pass_fwd_einsum_fn(core, subgrid_size, axis_name=None,
                               finish=True, collective="psum", n_shards=None):
    def fn(NMBF, foffs0, foffs1, sg_offs, masks0=None, masks1=None):
        ops = _colpass_operators(core, foffs0, foffs1)
        return _colpass_einsum_body(
            core, subgrid_size, ops, NMBF, foffs1, sg_offs, masks0,
            masks1, axis_name, finish, collective, n_shards,
        )

    return fn


def _column_pass_fwd_pallas_fn(core, subgrid_size, axis_name=None,
                               finish=True, collective="psum", n_shards=None):
    def fn(NMBF, foffs0, foffs1, sg_offs, masks0=None, masks1=None):
        ops = _colpass_operators(core, foffs0, foffs1)
        return _colpass_pallas_body(
            core, subgrid_size, ops, NMBF, foffs1, sg_offs, masks0,
            masks1, axis_name, finish, None, collective, n_shards,
        )

    return fn


def _column_pass_fwd_fn(core, subgrid_size, axis_name=None,
                        collective="psum", n_shards=None):
    """NMBF column [F, m, yB] -> the column's subgrids [S, xA, xA].

    Trace-time dispatcher: the fused Pallas kernel or the
    operator-matrix einsum body per `resolve_colpass` (both share the
    image-space partial/crop-finish contract), the per-facet fft chain
    otherwise. Callers that need PRE-finish partials (the facet-slab
    group step) pick a body explicitly instead — the fft body's
    partials live in a different space (grid, not image) and must pair
    with the matching group finish.
    """
    bodies = {
        "einsum": _column_pass_fwd_einsum_fn(
            core, subgrid_size, axis_name, True, collective, n_shards
        ),
        "pallas": _column_pass_fwd_pallas_fn(
            core, subgrid_size, axis_name, True, collective, n_shards
        ),
        "fft": _column_pass_fwd_fft_fn(
            core, subgrid_size, axis_name, True, collective, n_shards
        ),
    }

    def fn(NMBF, foffs0, foffs1, sg_offs, masks0=None, masks1=None):
        body = bodies[_resolve_colpass(core, NMBF.shape[0])]
        return body(NMBF, foffs0, foffs1, sg_offs, masks0, masks1)

    return fn


def _column_pass_fwd_fft_fn(core, subgrid_size, axis_name=None, finish=True,
                            collective="psum", n_shards=None):
    """The per-facet fft-chain column pass: the facet reduction is a
    lax.scan accumulating one [S, xM, xM] buffer (each step: one facet's
    contributions to ALL S subgrids, S-batched matmuls) — a
    vmap-over-S-of-sum-over-F materialises every (S, F) contribution
    block at once, which OOMs a 16 GiB chip at the 32k scale. With
    `axis_name`, F is the local facet shard and the reduction finishes
    with ONE collective (psum or ppermute ring) over the accumulated
    partials — the streamed pipeline's only collective.

    With ``finish=False`` the PRE-finish GRID-space partials [S, xM, xM]
    are returned (no masks consumed): the facet-slab path accumulates
    those across slabs and finishes ONCE per column group — at 64k the
    per-slab finish was 44% of all FLOPs.
    """
    p = core._p

    def fn(NMBF, foffs0, foffs1, sg_offs, masks0=None, masks1=None):
        def prep1(x, off1):
            return prepare_facet_math(p, core._Fb, core.yN_size, x, off1, 1)

        NMBF_BF = jax.vmap(prep1)(NMBF, foffs1)  # [F, m, yN]

        def facet_step(acc, xs):
            bf, f0, f1 = xs
            per_sg = jax.vmap(
                lambda so: facet_contrib_to_subgrid(core, bf, f0, f1, so[1])
            )(sg_offs)  # [S, xM, xM]
            return acc + per_sg, None

        S = sg_offs.shape[0]
        init = jax.numpy.zeros(
            (S, core.xM_size, core.xM_size) + NMBF.shape[3:],
            dtype=NMBF.dtype,
        )
        if axis_name is not None:
            # the carry mixes in facet-sharded offsets: tag it varying
            init = varying(init, axis_name)
        partials, _ = jax.lax.scan(
            facet_step, init, (NMBF_BF, foffs0, foffs1)
        )
        if axis_name is not None:
            partials = _collective_sum(
                partials, axis_name, collective, n_shards
            )
        if not finish:
            return partials

        def fin(summed, sg_off_pair, m0, m1):
            return finish_masked_subgrid(
                core, summed, sg_off_pair, subgrid_size, m0, m1
            )

        return jax.vmap(fin)(partials, sg_offs, masks0, masks1)

    return fn


@functools.lru_cache(maxsize=None)
def _column_pass_fwd_j(core, subgrid_size):
    return _jit()(
        _scoped(
            "swiftly/fwd.column_pass",
            _column_pass_fwd_fn(core, subgrid_size),
        )
    )


@functools.lru_cache(maxsize=None)
def _column_pass_fwd_sharded_cached(core, mesh, subgrid_size, collective):
    return _shmap(
        _scoped(
            "swiftly/fwd.column_pass",
            _column_pass_fwd_fn(
                core, subgrid_size, axis_name=FACET_AXIS,
                collective=collective, n_shards=_mesh_size(mesh),
            ),
        ),
        mesh,
        in_specs=(
            _P(FACET_AXIS), _P(FACET_AXIS), _P(FACET_AXIS),
            _P(), _P(), _P(),
        ),
        out_specs=_P(),
    )


def _column_pass_fwd_sharded(core, mesh, subgrid_size):
    """The sharded column pass under the CURRENT collective schedule.

    SWIFTLY_MESH_COLLECTIVE is resolved per CALL and keys the compiled-
    program cache, so one process can bench psum and ring back to back
    without a stale cached program shadowing the requested schedule."""
    return _column_pass_fwd_sharded_cached(
        core, mesh, subgrid_size, _resolve_collective_env(_mesh_size(mesh))
    )


def _column_pass_fwd_group_fn(core, subgrid_size, axis_name=None,
                              collective="psum", n_shards=None):
    """Sampled group buffer [F, G*m, yB] -> subgrids [G, S, xA, xA].

    vmaps the column pass over a whole sampled-DFT group: one dispatch
    per G columns instead of G, and the per-subgrid small-matmul stages
    gain a G-times larger batch dimension (the column pass is MXU-
    utilisation-bound at m-sized tiles, measured ~2.7 TFLOP/s per
    column alone on v5e).
    """
    m = core.xM_yN_size
    colfn = _column_pass_fwd_fft_fn(
        core, subgrid_size, axis_name, True, collective, n_shards
    )

    def fn(buf, foffs0, foffs1, sg_offs_g, masks0_g, masks1_g):
        F = buf.shape[0]
        G = sg_offs_g.shape[0]
        NMBF_g = jax.numpy.moveaxis(
            buf.reshape((F, G, m) + buf.shape[2:]), 1, 0
        )  # [G, F, m, yB(,2)]

        mode = _resolve_colpass(core, F)
        if mode in ("einsum", "pallas"):
            # operators hoisted across the group's columns; columns run
            # sequentially (lax.map) — each column's einsums are already
            # MXU-wide, and a G-batched vmap would scale the [F, xM, yN]
            # H transient by G (OOM at 32k G=9). Under the ring schedule
            # the sequential columns are exactly the interleave: column
            # k's chunk rotations have no dependence on column k+1's
            # contraction, so the rotation rides under the next column's
            # local matmuls.
            ops = _colpass_operators(core, foffs0, foffs1)
            body = (
                _colpass_einsum_body
                if mode == "einsum"
                else _colpass_pallas_body
            )

            def per_col(xs):
                NMBF, so, m0, m1 = xs
                if body is _colpass_pallas_body:
                    return body(
                        core, subgrid_size, ops, NMBF, foffs1, so, m0, m1,
                        axis_name, True, None, collective, n_shards,
                    )
                return body(
                    core, subgrid_size, ops, NMBF, foffs1, so, m0, m1,
                    axis_name, True, collective, n_shards,
                )

            return jax.lax.map(
                per_col, (NMBF_g, sg_offs_g, masks0_g, masks1_g)
            )

        def per_col(NMBF, so, m0, m1):
            return colfn(NMBF, foffs0, foffs1, so, m0, m1)

        return jax.vmap(per_col)(NMBF_g, sg_offs_g, masks0_g, masks1_g)

    return fn


@functools.lru_cache(maxsize=None)
def _column_pass_fwd_group_j(core, subgrid_size):
    return _jit()(
        _scoped(
            "swiftly/fwd.column_pass",
            _column_pass_fwd_group_fn(core, subgrid_size),
        )
    )


@functools.lru_cache(maxsize=None)
def _column_pass_fwd_group_sharded_cached(core, mesh, subgrid_size,
                                          collective):
    return _shmap(
        _scoped(
            "swiftly/fwd.column_pass",
            _column_pass_fwd_group_fn(
                core, subgrid_size, axis_name=FACET_AXIS,
                collective=collective, n_shards=_mesh_size(mesh),
            ),
        ),
        mesh,
        in_specs=(
            _P(FACET_AXIS), _P(FACET_AXIS), _P(FACET_AXIS),
            _P(), _P(), _P(),
        ),
        out_specs=_P(),
    )


def _column_pass_fwd_group_sharded(core, mesh, subgrid_size):
    """Group column pass under the CURRENT collective schedule (see
    `_column_pass_fwd_sharded` — same call-time resolution)."""
    return _column_pass_fwd_group_sharded_cached(
        core, mesh, subgrid_size, _resolve_collective_env(_mesh_size(mesh))
    )


def _bwd_scatter_rows(core, Z, sg_offs, axis_name=None):
    """One column's per-subgrid contribution blocks [S, F, m, m(,2)] ->
    the NAF_MNAF accumulator [F, m, yN(,2)] with ONE scatter-add.

    Replaces the per-subgrid lax.scan whose [F, m, yN] carry (302 MB at
    32k) crossed HBM once per subgrid — measured 2.9% of the matmul
    ceiling for the whole backward column pass (scripts/roofline.py
    --bwd). The destination index of block row j for subgrid offset
    scaled is (yN//2 - m//2 + scaled + ((j - scaled) mod m)) mod yN —
    the roll+wrapped-embed of `add_to_facet_math` as one index map
    (the same window arithmetic as `sampled_row_indices`); duplicate
    indices (overlapping windows) accumulate in the scatter.
    """
    import jax.numpy as jnp

    from ..ops.core import scaled_offset

    m, yN = core.xM_yN_size, core.yN_size
    S = Z.shape[0]
    F = Z.shape[1]
    scaled = scaled_offset(sg_offs[:, 1], yN, core.N)  # [S]
    j = jnp.arange(m)
    idx = (
        yN // 2 - m // 2 + scaled[:, None]
        + jnp.mod(j[None, :] - scaled[:, None], m)
    ) % yN  # [S, m]
    Zm = jnp.moveaxis(Z, 0, 2)  # [F, m, S, m(,2)]
    Zm = Zm.reshape((F, m, S * m) + Z.shape[4:])
    zeros = jnp.zeros((F, m, yN) + Z.shape[4:], dtype=Z.dtype)
    if axis_name is not None:
        zeros = varying(zeros, axis_name)
    return zeros.at[:, :, idx.reshape(-1)].add(Zm)


def _bwd_colpass_operators(core, foffs0, foffs1):
    """Backward (adjoint) column-pass operators, built in-trace from an
    identity block.

    E0 [F, m, xM(,2)]: the axis-0 `extract_from_subgrid_math` chain with
    the prepare-fft folded in (fft along an axis commutes with the other
    axis's ops). E1 [F, xM, m(,2)]: the axis-1 chain in row-basis layout
    (E1[f, b, j] = op1_f[j, b]).
    """
    import jax.numpy as jnp

    from ..ops.core import extract_from_subgrid_math

    p = core._p
    m, xM = core.xM_yN_size, core.xM_size
    if _planar(core):
        eye = (
            jnp.zeros((xM, xM, 2), core.dtype)
            .at[:, :, 0]
            .set(jnp.eye(xM, dtype=core.dtype))
        )
    else:
        eye = jnp.eye(xM, dtype=core.dtype)

    def e0(off0):
        return extract_from_subgrid_math(
            p, core._Fn, m, xM, core.N, p.fft(eye, 0), off0, 0
        )

    def e1(off1):
        return extract_from_subgrid_math(
            p, core._Fn, m, xM, core.N, p.fft(eye, 1), off1, 1
        )

    return jax.vmap(e0)(foffs0), jax.vmap(e1)(foffs1)


def _column_pass_bwd_einsum_fn(
    core, facet_size, axis_name=None, use_pallas=False
):
    """Operator-matrix backward column pass (adjoint of the forward
    einsum pass): the per-(facet, subgrid) extract chains collapse into
    two K=xM einsums; the per-subgrid scatter into the [F, m, yN]
    accumulator stays a scan (its positions are per-subgrid).

    ``use_pallas`` swaps the per-block einsum pair for the fused kernel
    (`colpass_pallas`, reduce_f=False: Z_sf = E0_f @ emb_s @ E1_f with
    the embedded subgrid broadcast over the facet axis) — everything
    around it (Sb blocking, scatter, finish) is shared."""
    import jax.numpy as jnp

    p = core._p
    xM = core.xM_size

    def fn(subgrids, sg_offs, foffs0, foffs1, masks1):
        E0, E1 = _bwd_colpass_operators(core, foffs0, foffs1)

        def emb_one(sg, so):
            x = p.wrapped_embed(sg, xM, so[0], 0)
            return p.wrapped_embed(x, xM, so[1], 1)

        S = sg_offs.shape[0]
        Sb = min(_colpass_sblock(), S)
        nb = -(-S // Sb)
        Sb = -(-S // nb)  # rebalanced: pad < nb, never a near-full block
        pad = nb * Sb - S
        sg_p, so_p = subgrids, sg_offs
        if pad:
            # zero-padded subgrids contribute exactly nothing
            zpad = jnp.zeros(
                (pad,) + subgrids.shape[1:], dtype=subgrids.dtype
            )
            sg_p = jnp.concatenate([subgrids, zpad])
            so_p = jnp.concatenate(
                [sg_offs, jnp.repeat(sg_offs[-1:], pad, 0)]
            )

        def block(xs):
            sg_blk, so_blk = xs
            emb = jax.vmap(emb_one)(sg_blk, so_blk)  # [Sb, xM, xM(,2)]
            if use_pallas:
                from ..ops.pallas_kernels import (
                    colpass_pallas, pallas_interpret,
                )

                bm, bn, bk = _colpass_blocks()
                Zr, Zi = colpass_pallas(
                    E0[..., 0], E0[..., 1],
                    emb[:, None, ..., 0], emb[:, None, ..., 1],
                    E1[..., 0], E1[..., 1],
                    reduce_f=False, bm=bm, bn=bn, bk=bk,
                    interpret=pallas_interpret(),
                )
                return jnp.stack([Zr, Zi], axis=-1)  # [Sb, F, m, m, 2]
            Y = _ceinsum(core, "fia,sab->sfib", E0, emb)
            return _ceinsum(core, "sfib,fbj->sfij", Y, E1)  # [Sb,F,m,m]

        if nb == 1:
            Z = block((sg_p, so_p))
        else:
            Z = jax.lax.map(
                block,
                (
                    sg_p.reshape((nb, Sb) + sg_p.shape[1:]),
                    so_p.reshape((nb, Sb) + so_p.shape[1:]),
                ),
            )
            Z = Z.reshape((nb * Sb,) + Z.shape[2:])
        # padded rows are zero blocks: the scatter adds nothing for them
        acc = _bwd_scatter_rows(core, Z, so_p, axis_name)

        def fin(a, off1, m1):
            x = finish_facet_math(p, core._Fb, facet_size, a, off1, 1)
            return _mask_along(p, x, m1, 1)

        return jax.vmap(fin)(acc, foffs1, masks1)

    return fn


def _column_pass_bwd_fn(core, facet_size, axis_name=None):
    """A column's subgrids [S, xA, xA] -> NAF_BMNAF rows [F, m, yB].

    Trace-time dispatcher (einsum vs fused-pallas vs fft chain) on the
    program's facet count — `resolve_colpass_bwd`, overridable with
    SWIFTLY_COLPASS_BWD. All bodies produce identical finished rows, so
    unlike the forward no caller pairing is needed."""
    bodies = {
        "einsum": _column_pass_bwd_einsum_fn(core, facet_size, axis_name),
        "pallas": _column_pass_bwd_einsum_fn(
            core, facet_size, axis_name, use_pallas=True
        ),
        "fft": _column_pass_bwd_fft_fn(core, facet_size, axis_name),
    }

    def fn(subgrids, sg_offs, foffs0, foffs1, masks1):
        body = bodies[_resolve_colpass_bwd(core, foffs0.shape[0])]
        return body(subgrids, sg_offs, foffs0, foffs1, masks1)

    return fn


def _column_pass_bwd_fft_fn(core, facet_size, axis_name=None):
    """The per-facet fft-chain backward column pass: batched prepare +
    per-(subgrid, facet) extract chains, then ONE scatter-add into the
    accumulator layout. (The previous per-subgrid `lax.scan` fold moved
    the [F, m, yN] carry through HBM once per subgrid — 2.9% of the
    matmul ceiling, the slowest stage in the whole pipeline; the [S, F,
    m, m] contribution stack is only ~350 MB at 32k, so materialising
    it and scattering once is strictly better.)"""
    from ..ops.core import prepare_subgrid_math
    from .batched import subgrid_contrib_to_facet

    import jax.numpy as jnp

    p = core._p

    def fn(subgrids, sg_offs, foffs0, foffs1, masks1):
        def prep_one(sg, so):
            return prepare_subgrid_math(p, core.xM_size, sg, so)

        def per_sg(pp):
            return jax.vmap(
                lambda f0, f1: subgrid_contrib_to_facet(core, pp, f0, f1)
            )(foffs0, foffs1)  # [F, m, m(,2)]

        def block_z(sg_b, so_b):
            prepped = jax.vmap(prep_one)(sg_b, so_b)  # [Sb, xM, xM]
            return jax.vmap(per_sg)(prepped)  # [Sb, F, m, m(,2)]

        # the [S, F, m, m] contribution stack is blocked by Sb like the
        # einsum body's gather transient; Sb is rebalanced to ceil(S/nb)
        # so the zero-pad never exceeds nb-1 rows (a raw 256-block split
        # of S=293 would pad 219 dead rows — 1.75x the stage's FLOPs)
        S = sg_offs.shape[0]
        Sb = min(_colpass_sblock(), S)
        nb = -(-S // Sb)
        Sb = -(-S // nb)
        if nb == 1:
            NAF_MNAFs = _bwd_scatter_rows(
                core, block_z(subgrids, sg_offs), sg_offs, axis_name
            )
        else:
            pad = nb * Sb - S
            sg_p, so_p = subgrids, sg_offs
            if pad:
                # zero-padded subgrids scatter exactly nothing
                sg_p = jnp.concatenate(
                    [subgrids,
                     jnp.zeros((pad,) + subgrids.shape[1:], subgrids.dtype)]
                )
                so_p = jnp.concatenate(
                    [sg_offs, jnp.repeat(sg_offs[-1:], pad, 0)]
                )

            def fold(acc, xs):
                sg_b, so_b = xs
                return (
                    acc
                    + _bwd_scatter_rows(
                        core, block_z(sg_b, so_b), so_b, axis_name
                    ),
                    None,
                )

            F = foffs0.shape[0]
            init = jnp.zeros(
                (F, core.xM_yN_size, core.yN_size) + subgrids.shape[3:],
                dtype=subgrids.dtype,
            )
            if axis_name is not None:
                init = varying(init, axis_name)
            NAF_MNAFs, _ = jax.lax.scan(
                fold,
                init,
                (
                    sg_p.reshape((nb, Sb) + sg_p.shape[1:]),
                    so_p.reshape((nb, Sb) + so_p.shape[1:]),
                ),
            )

        def fin(acc, off1, m1):
            x = finish_facet_math(p, core._Fb, facet_size, acc, off1, 1)
            return _mask_along(p, x, m1, 1)

        return jax.vmap(fin)(NAF_MNAFs, foffs1, masks1)

    return fn


@functools.lru_cache(maxsize=None)
def _column_pass_bwd_j(core, facet_size):
    return _jit()(
        _scoped(
            "swiftly/bwd.column_pass",
            _column_pass_bwd_fn(core, facet_size),
        )
    )


@functools.lru_cache(maxsize=None)
def _column_pass_bwd_group_j(core, facet_size):
    """A whole column GROUP's backward column passes as one dispatch:
    subgrids [G, S, xA, xA(,2)] -> rows [G, F, m, yB(,2)]. Per-dispatch
    latency on tunnel runtimes makes per-column dispatch the dominant
    cost of the backward leg (measured ~0.1 s per chain)."""
    fn = _column_pass_bwd_fn(core, facet_size)
    return _jit()(
        _scoped(
            "swiftly/bwd.column_pass",
            jax.vmap(fn, in_axes=(0, 0, None, None, None)),
        )
    )


@functools.lru_cache(maxsize=None)
def _column_pass_bwd_sharded(core, mesh, facet_size):
    """Facet-sharded backward column pass (subgrids replicated; the split
    and fold are shard-local, no collectives)."""
    return _shmap(
        _scoped(
            "swiftly/bwd.column_pass",
            _column_pass_bwd_fn(core, facet_size, axis_name=FACET_AXIS),
        ),
        mesh,
        in_specs=(
            _P(), _P(), _P(FACET_AXIS), _P(FACET_AXIS), _P(FACET_AXIS),
        ),
        out_specs=_P(FACET_AXIS),
    )


def _facet_pass_bwd_fn(core, facet_size, axis_name=None):
    """NAF_BMNAF column-blocks [K, F, m, Cb] -> facet blocks [F, yB, Cb]."""
    p = core._p

    def fn(blocks, col_offs0, foffs0, masks0):
        def fold(carry, xs):
            blk, off0 = xs  # [F, m, Cb]
            emb = jax.vmap(
                lambda c: add_to_facet_math(p, core.yN_size, core.N, c, off0, 0)
            )(blk)
            return carry + emb, None

        F = foffs0.shape[0]
        init = jax.numpy.zeros(
            (F, core.yN_size) + blocks.shape[3:], dtype=blocks.dtype
        )
        if axis_name is not None:
            init = varying(init, axis_name)
        acc, _ = jax.lax.scan(fold, init, (blocks, col_offs0))

        def fin(a, off0, m0):
            x = finish_facet_math(p, core._Fb, facet_size, a, off0, 0)
            return _mask_along(p, x, m0, 0)

        return jax.vmap(fin)(acc, foffs0, masks0)

    return fn


@functools.lru_cache(maxsize=None)
def _facet_pass_bwd_j(core, facet_size):
    return _jit()(
        _scoped(
            "swiftly/bwd.facet_pass",
            _facet_pass_bwd_fn(core, facet_size),
        )
    )


@functools.lru_cache(maxsize=None)
def _facet_pass_bwd_sharded(core, mesh, facet_size):
    return _shmap(
        _scoped(
            "swiftly/bwd.facet_pass",
            _facet_pass_bwd_fn(core, facet_size, axis_name=FACET_AXIS),
        ),
        mesh,
        in_specs=(
            _P(None, FACET_AXIS), _P(), _P(FACET_AXIS), _P(FACET_AXIS),
        ),
        out_specs=_P(FACET_AXIS),
    )


# -- sampled-DFT facet pass -------------------------------------------------
#
# The forward facet pass per output row r of subgrid column offset sigma is
# a LINEAR map of the facet column f[j] (j < yB):
#
#   NMBF[r] = roll(wrapped_extract(ifft(wrapped_embed(Fb*f, yN, delta)),
#                                  m, s), s)[r]
#           = (1/yN) sum_j Fb[j] f[j] w^{(e0 + j) * kt_r},  w = e^{+2pi i/yN}
#
# with s = sigma*yN/N, kt_r = ((yN//2 - m//2 + s + ((r - s) mod m)) mod yN)
# - yN//2 the extracted spectral row index and e0 = delta - yB//2 the
# embedding shift (wrapped_embed start yN//2 - yB//2 + delta, minus the
# ifft centre yN//2). The phase separates: w^{e0*kt} (per facet, per row)
# times w^{j*kt} (facet-independent). So the WHOLE pass for any set of
# output rows is one complex matmul against A[r, j] = Fb[j]/yN * w^{j*kt_r}
# plus a per-facet diagonal phase — compute scales with rows actually
# needed, which makes column-group chunking free (no FFT recompute), and
# the FLOPs land on the MXU as a single large einsum.


def sampled_row_indices(core, col_offs0):
    """Centred spectral row indices kt [G*m] for a group of subgrid
    column offsets (int32; validated against the FFT-based pass by tests).
    """
    m = core.xM_yN_size
    yN = core.yN_size
    r = np.arange(m)
    rows = []
    for off0 in col_offs0:
        s = int(off0) * yN // core.N
        k = (yN // 2 - m // 2 + s + ((r - s) % m)) % yN
        rows.append(k - yN // 2)
    return np.concatenate(rows).astype(np.int32)


def _mulmod(a, b, yN):
    """(a*b) mod yN in int32, exact for any yN <= 2**16 (all catalogue
    sizes).

    A direct int32 product overflows once yN*yB exceeds 2**31 (e.g. the
    64k configs); int64 is unreliable here because jax silently downcasts
    it without x64. Instead reduce both operands mod yN and split b into
    8-bit limbs: every partial product stays below yN * 2**8 <= 2**24.
    """
    import jax.numpy as jnp

    if yN > 1 << 16:  # pragma: no cover - no such catalogue entry
        raise ValueError(f"phase computation requires yN <= 65536, got {yN}")
    a = jnp.mod(a, yN)
    b = jnp.mod(b, yN)
    b_hi, b_lo = b >> 8, b & 0xFF
    hi = jnp.mod(a * b_hi, yN) << 8
    return jnp.mod(hi + a * b_lo, yN)


def _sampled_phases(core, residues):
    import jax.numpy as jnp

    theta = (2 * np.pi / core.yN_size) * residues
    return jnp.cos(theta), jnp.sin(theta)


def _sampled_A_real(core, yB, dt, krows):
    """The sampled-DFT phase matrix pair (A_re, A_im) [R, yB] for real
    facets (krows-dependent only; factored from the pass body so a
    caller that batches multiple slabs against one krows set can build
    it once)."""
    import jax.numpy as jnp

    yN = core.yN_size
    fb = core._p.extract_mid(core._Fb, yB, 0) / yN  # [yB] real
    j = jnp.arange(yB, dtype=jnp.int32)
    a_cos, a_sin = _sampled_phases(
        core, _mulmod(krows[:, None], j[None, :], yN)
    )
    return (a_cos * fb[None, :]).astype(dt), (a_sin * fb[None, :]).astype(dt)


def _sampled_apply_real(core, A_re, A_im, Fr, e0, krows):
    """Apply a prebuilt sampled phase matrix to a real facet slab
    [F, yB, yB] -> rows [F, R, yB, 2] (the per-facet e0 phase rotation
    included). The `_facet_pass_sampled_fn(real)` body."""
    import jax.numpy as jnp

    yN = core.yN_size
    dt = Fr.dtype
    from ..ops.planar_backend import matmul_precision

    prec = matmul_precision()
    f = lambda a, b: jnp.einsum("rj,fjc->frc", a, b, precision=prec)
    out_re = f(A_re, Fr)
    out_im = f(A_im, Fr)
    p_cos, p_sin = _sampled_phases(
        core, _mulmod(e0.astype(jnp.int32)[:, None], krows[None, :], yN)
    )  # [F, R]
    p_cos = p_cos.astype(dt)[..., None]
    p_sin = p_sin.astype(dt)[..., None]
    return jnp.stack(
        [
            out_re * p_cos - out_im * p_sin,
            out_re * p_sin + out_im * p_cos,
        ],
        axis=-1,
    )


@functools.lru_cache(maxsize=None)
def _facet_pass_sampled_fn(core, real_facets=False):
    """facets [F, yB, Y(,2)] -> sampled contribution rows [F, R, Y(,2)].

    `krows` are centred spectral indices (from `sampled_row_indices`),
    `e0` the per-facet embedding shifts (facet_off0 - yB//2). One einsum
    per call; works for the full column set or any chunk of it. Body
    builder shared by the single-device jit and the facet-sharded
    shard_map variant.

    With ``real_facets`` (planar backend only) the facets arrive as a
    single real plane [F, yB, yB] — the zero imaginary plane's two
    einsums are dropped, halving both the FLOPs and the facet upload
    volume. Exact, not an approximation: point-source facet models are
    real-valued (reference ``make_facet_from_sources``), and the caller
    verifies the imaginary plane is identically zero before choosing
    this path.
    """
    import jax.numpy as jnp

    yN = core.yN_size

    def phases(residues):
        theta = (2 * np.pi / yN) * residues
        return jnp.cos(theta), jnp.sin(theta)

    if real_facets:
        if not _planar(core):  # pragma: no cover - guarded by caller
            raise ValueError("real_facets requires the planar backend")

        def fn(Fr, e0, krows):
            A_re, A_im = _sampled_A_real(core, Fr.shape[1], Fr.dtype, krows)
            return _sampled_apply_real(core, A_re, A_im, Fr, e0, krows)

    elif _planar(core):
        # Planes arrive as SEPARATE arrays (Fr, Fi), not a trailing axis:
        # slicing a stacked [F, yB, yB, 2] inside the program would
        # materialise multi-GiB plane copies next to the resident stack.

        def fn(Fr, Fi, e0, krows):
            yB = Fr.shape[1]
            dt = Fr.dtype
            fb = core._p.extract_mid(core._Fb, yB, 0) / yN  # [yB] real
            j = jnp.arange(yB, dtype=jnp.int32)
            a_cos, a_sin = phases(_mulmod(krows[:, None], j[None, :], yN))
            A_re = (a_cos * fb[None, :]).astype(dt)
            A_im = (a_sin * fb[None, :]).astype(dt)
            from ..ops.planar_backend import matmul_precision

            prec = matmul_precision()
            f = lambda a, b: jnp.einsum(
                "rj,fjc->frc", a, b, precision=prec
            )
            out_re = f(A_re, Fr) - f(A_im, Fi)
            out_im = f(A_re, Fi) + f(A_im, Fr)
            p_cos, p_sin = phases(
                _mulmod(
                    e0.astype(jnp.int32)[:, None], krows[None, :], yN
                )
            )  # [F, R]
            p_cos = p_cos.astype(dt)[..., None]
            p_sin = p_sin.astype(dt)[..., None]
            return jnp.stack(
                [
                    out_re * p_cos - out_im * p_sin,
                    out_re * p_sin + out_im * p_cos,
                ],
                axis=-1,
            )

    else:

        def fn(facets, e0, krows):
            yB = facets.shape[1]
            fb = core._p.extract_mid(core._Fb, yB, 0) / yN
            j = jnp.arange(yB, dtype=jnp.int32)
            a_cos, a_sin = phases(_mulmod(krows[:, None], j[None, :], yN))
            A = (a_cos + 1j * a_sin).astype(core.dtype) * fb[None, :]
            out = jnp.einsum("rj,fjc->frc", A, facets)
            p_cos, p_sin = phases(
                _mulmod(
                    e0.astype(jnp.int32)[:, None], krows[None, :], yN
                )
            )
            phi = (p_cos + 1j * p_sin).astype(core.dtype)
            return out * phi[..., None]

    return fn


@functools.lru_cache(maxsize=None)
def _facet_pass_sampled_j(core, real_facets=False):
    return _jit()(
        _scoped(
            "swiftly/fwd.sampled_facet_pass",
            _facet_pass_sampled_fn(core, real_facets),
        )
    )


@functools.lru_cache(maxsize=None)
def _facet_pass_sampled_sharded(core, mesh, real_facets=False):
    """Facet-sharded sampled-DFT facet pass: each device's einsum covers
    its local facets only (no collectives; the facet sum happens later in
    the column pass psum)."""
    if real_facets:
        n_arrays = 1  # single real plane
    else:
        n_arrays = 2 if _planar(core) else 1  # planes vs complex facets
    in_specs = tuple([_P(FACET_AXIS)] * n_arrays) + (_P(FACET_AXIS), _P())
    return _shmap(
        _scoped(
            "swiftly/fwd.sampled_facet_pass",
            _facet_pass_sampled_fn(core, real_facets),
        ),
        mesh,
        in_specs=in_specs,
        out_specs=_P(FACET_AXIS),
    )


# -- sampled-DFT backward facet pass (the exact adjoint) --------------------
#
# The backward facet pass along axis 0 is, per facet f and output row i:
#
#   out[f, i] = fb[i] * wrapped_extract(fft(sum_k wrapped_embed(
#                   roll(rows_k[f], -s_k), yN, s_k)), yB, delta_f)[i]
#
# Tracing one element rows_k[f, r] through embed+roll shows it lands at
# spectral position q_k(r) = (kt_r + yN//2) mod yN — the SAME kt indices
# the forward extracts (sampled_row_indices). The centred fft then gives
#
#   out[f, i] = fb[i] * sum_k sum_r rows_k[f, r] * w^{-kt_r (e0_f + i)}
#
# (w = e^{+2pi i/yN}, e0_f = facet_off0 - yB//2, NO 1/yN — fft is
# unnormalised where the forward's ifft carried the 1/yN). So the whole
# backward facet pass is the conjugate-phase transpose of the forward's
# sampled matmul: one einsum per column (group) accumulating directly
# into the [F, yB, yB] image-space facet accumulator — which is the SIZE
# OF THE OUTPUT, the minimal possible device state. No NAF_all buffer,
# no host round trip, no d2h until the final (verified-on-device) facets.


def _fold_row_block(F, yB, itemsize):
    """Static output-row block size for the adjoint fold's scan.

    The fold's einsum transients are [F, B, yB]-shaped; bounding B keeps
    each one to ~SWIFTLY_FOLD_BLOCK_MB (default 192) regardless of yB —
    the unblocked fold materialised a full [F, yB, yB, 2] (~2x the
    accumulator, ~18 GiB at 32k) next to the donated accumulator, which
    is exactly what OOM'd the 32k round trip on a 16 GiB chip.
    """
    import os

    target = float(os.environ.get("SWIFTLY_FOLD_BLOCK_MB", "192")) * 1e6
    per_row = max(1, F * yB * itemsize)
    B = int(target // per_row)
    if B >= yB:
        return yB
    return max(1, (B // 128) * 128 or B)


@functools.lru_cache(maxsize=None)
def _bwd_sampled_fold_fn(core, use_pallas=False, interpret=False):
    """acc [F, yB, yB(,2)] += adjoint-sampled fold of rows [F, R, yB(,2)].

    `rows` are a column group's NAF_BMNAF rows concatenated along R (the
    output of the backward column pass, already finished+masked along
    axis 1); `krows` their centred spectral indices; `e0` the per-facet
    embedding shifts. Validated against the FFT-based `_facet_pass_bwd`
    by tests/test_streamed.py.

    The fold accumulates in bounded output-row blocks (`_fold_row_block`)
    via a lax.scan whose carry is the donated accumulator: per block one
    [F, B, yB]-shaped einsum lands in acc through a dynamic slice update,
    so peak transient memory is a few blocks, not a second full
    accumulator. The final (clamped) block re-covers rows the previous
    block already folded; `keep` zeroes those contributions, making the
    tiling exact for any yB.

    ``row0`` (traced int32) is the ROW-SLAB offset: the accumulator may
    cover only output rows [row0, row0 + acc.shape[1]) of the facet —
    the "ri" einsum index restricts trivially, so a facet whose full
    [yB, yB] accumulator exceeds HBM (one 128k facet: 16.2 GiB) splits
    into HBM-sized row slabs, each an independent backward pass over
    the same subgrid stream. Whole-facet callers pass row0 = 0; the
    full facet width is read off the rows' pass-through j axis.

    With ``use_pallas`` (planar only; `ops.pallas_kernels.pallas_enabled`
    resolves the opt-in at trace time like SWIFTLY_COLPASS) each block's
    einsum pair + row-weight scale + accumulate runs as ONE fused
    `bwd_fold_pallas` grid program with the accumulator block pinned in
    VMEM — the facet axis folds into the kernel's j axis, so the fused
    matmuls stay MXU-deep at any facet count. The fused kernel tiles
    the contraction, so its partial-sum ORDER differs from the einsum
    body: results agree to f32 sum-reorder tolerance (~1e-5 relative,
    pinned by tests/test_pallas.py), not bit-identically. ``interpret``
    routes through the Pallas interpreter (CPU validation).
    """
    import jax.numpy as jnp

    yN = core.yN_size

    def phases(residues):
        theta = (2 * np.pi / yN) * residues
        return jnp.cos(theta), jnp.sin(theta)

    if use_pallas and not _planar(core):  # pragma: no cover - guarded
        raise ValueError("the Pallas fold requires the planar backend")

    if _planar(core) and use_pallas:
        from ..ops.pallas_kernels import bwd_fold_pallas

        def fn(acc, rows, e0, krows, row0):
            F, Rs = acc.shape[0], acc.shape[1]
            yB = rows.shape[2]  # full facet width (pass-through j axis)
            R = rows.shape[1]
            dt = acc.dtype
            fb = core._p.extract_mid(core._Fb, yB, 0)  # [yB] real
            p_cos, p_sin = phases(
                _mulmod(e0.astype(jnp.int32)[:, None], krows[None, :], yN)
            )
            p_cos = p_cos.astype(dt)[..., None]
            p_sin = p_sin.astype(dt)[..., None]
            Rr, Ri = rows[..., 0], rows[..., 1]
            # the [R, F*yB] layout folds the facet axis into the kernel's
            # output-column axis (hoisted out of the block scan — the
            # rotated planes are block-invariant)
            rr_flat = jnp.moveaxis(
                Rr * p_cos + Ri * p_sin, 0, 1
            ).reshape(R, F * yB)
            ri_flat = jnp.moveaxis(
                Ri * p_cos - Rr * p_sin, 0, 1
            ).reshape(R, F * yB)
            B = min(_fold_row_block(F, yB, np.dtype(dt).itemsize), Rs)
            n_blk = -(-Rs // B)
            fbj = jnp.asarray(fb, dt)

            def body(carry, xs):
                i0, start = xs
                ii = start + jnp.arange(B, dtype=jnp.int32)  # slab-rel
                keep = (ii >= i0).astype(dt)
                i_abs = row0 + ii  # absolute row: phases + Fb weight
                b_cos, b_sin = phases(
                    _mulmod(krows[:, None], i_abs[None, :], yN)
                )
                w = (
                    jax.lax.dynamic_slice_in_dim(fbj, row0 + start, B)
                    * keep
                )
                z = jnp.int32(0)
                cur = jax.lax.dynamic_slice(
                    carry, (z, start, z, z), (F, B, yB, 2)
                )
                out_r, out_i = bwd_fold_pallas(
                    jnp.moveaxis(cur[..., 0], 0, 1).reshape(B, F * yB),
                    jnp.moveaxis(cur[..., 1], 0, 1).reshape(B, F * yB),
                    b_cos.astype(dt),
                    b_sin.astype(dt),
                    rr_flat,
                    ri_flat,
                    w[:, None].astype(dt),
                    interpret=interpret,
                )
                new = jnp.stack(
                    [
                        jnp.moveaxis(out_r.reshape(B, F, yB), 0, 1),
                        jnp.moveaxis(out_i.reshape(B, F, yB), 0, 1),
                    ],
                    axis=-1,
                )
                return (
                    jax.lax.dynamic_update_slice(
                        carry, new, (z, start, z, z)
                    ),
                    None,
                )

            i0s = jnp.arange(n_blk, dtype=jnp.int32) * B
            starts = jnp.minimum(i0s, Rs - B)
            acc, _ = jax.lax.scan(body, acc, (i0s, starts))
            return acc

    elif _planar(core):

        def fn(acc, rows, e0, krows, row0):
            F, Rs = acc.shape[0], acc.shape[1]
            yB = rows.shape[2]  # full facet width (pass-through j axis)
            dt = acc.dtype
            fb = core._p.extract_mid(core._Fb, yB, 0)  # [yB] real, no 1/yN
            # conjugate per-facet phase: rows * w^{-e0_f kt_r}
            p_cos, p_sin = phases(
                _mulmod(e0.astype(jnp.int32)[:, None], krows[None, :], yN)
            )  # [F, R]
            p_cos = p_cos.astype(dt)[..., None]
            p_sin = p_sin.astype(dt)[..., None]
            Rr, Ri = rows[..., 0], rows[..., 1]
            Rr2 = Rr * p_cos + Ri * p_sin
            Ri2 = Ri * p_cos - Rr * p_sin
            from ..ops.planar_backend import matmul_precision

            prec = matmul_precision()
            f = lambda a, b: jnp.einsum(
                "ri,frj->fij", a, b, precision=prec
            )
            B = min(_fold_row_block(F, yB, np.dtype(dt).itemsize), Rs)
            n_blk = -(-Rs // B)
            fbj = jnp.asarray(fb, dt)

            def body(carry, xs):
                i0, start = xs
                ii = start + jnp.arange(B, dtype=jnp.int32)  # slab-rel
                keep = (ii >= i0).astype(dt)
                i_abs = row0 + ii  # absolute row: phases + Fb weight
                b_cos, b_sin = phases(
                    _mulmod(krows[:, None], i_abs[None, :], yN)
                )
                Bc = b_cos.astype(dt)
                Bs = b_sin.astype(dt)
                out_re = f(Bc, Rr2) + f(Bs, Ri2)
                out_im = f(Bc, Ri2) - f(Bs, Rr2)
                w = (
                    jax.lax.dynamic_slice_in_dim(fbj, row0 + start, B)
                    * keep
                )
                out = jnp.stack([out_re, out_im], axis=-1)
                out = out * w[None, :, None, None]
                z = jnp.int32(0)
                cur = jax.lax.dynamic_slice(
                    carry, (z, start, z, z), (F, B, yB, 2)
                )
                return (
                    jax.lax.dynamic_update_slice(
                        carry, cur + out, (z, start, z, z)
                    ),
                    None,
                )

            i0s = jnp.arange(n_blk, dtype=jnp.int32) * B
            starts = jnp.minimum(i0s, Rs - B)
            acc, _ = jax.lax.scan(body, acc, (i0s, starts))
            return acc

    else:

        def fn(acc, rows, e0, krows, row0):
            F, Rs = acc.shape[0], acc.shape[1]
            yB = rows.shape[2]  # full facet width (pass-through j axis)
            fb = core._p.extract_mid(core._Fb, yB, 0)
            p_cos, p_sin = phases(
                _mulmod(e0.astype(jnp.int32)[:, None], krows[None, :], yN)
            )
            phi = (p_cos - 1j * p_sin).astype(core.dtype)  # [F, R]
            rows2 = rows * phi[..., None]
            B = min(
                _fold_row_block(F, yB, np.dtype(core.dtype).itemsize), Rs
            )
            n_blk = -(-Rs // B)
            fbj = jnp.asarray(fb)

            def body(carry, xs):
                i0, start = xs
                ii = start + jnp.arange(B, dtype=jnp.int32)  # slab-rel
                keep = ii >= i0
                i_abs = row0 + ii  # absolute row: phases + Fb weight
                b_cos, b_sin = phases(
                    _mulmod(krows[:, None], i_abs[None, :], yN)
                )
                Bm = (b_cos - 1j * b_sin).astype(core.dtype)  # [R, B]
                out = jnp.einsum("ri,frj->fij", Bm, rows2)
                w = jnp.where(
                    keep,
                    jax.lax.dynamic_slice_in_dim(fbj, row0 + start, B),
                    0,
                )
                out = out * w[None, :, None].astype(core.dtype)
                z = jnp.int32(0)
                cur = jax.lax.dynamic_slice(
                    carry, (z, start, z), (F, B, yB)
                )
                return (
                    jax.lax.dynamic_update_slice(
                        carry, cur + out, (z, start, z)
                    ),
                    None,
                )

            i0s = jnp.arange(n_blk, dtype=jnp.int32) * B
            starts = jnp.minimum(i0s, Rs - B)
            acc, _ = jax.lax.scan(body, acc, (i0s, starts))
            return acc

    return fn


@functools.lru_cache(maxsize=None)
def _bwd_sampled_fold_j(core, use_pallas=False, interpret=False):
    return _jit(donate=(0,))(
        _scoped(
            "swiftly/bwd.sampled_fold",
            _bwd_sampled_fold_fn(core, use_pallas, interpret),
        )
    )


def resolve_fold_kernel(core, meshed=False) -> str:
    """Sampled-fold kernel body: "pallas" when the opt-in
    (SWIFTLY_PALLAS=1) applies — planar backend, single device — else
    "einsum". Read at trace time like SWIFTLY_COLPASS (the lru-cached
    jits bake the choice in)."""
    from ..ops.pallas_kernels import pallas_enabled

    if pallas_enabled() and _planar(core) and not meshed:
        return "pallas"
    return "einsum"


@functools.lru_cache(maxsize=None)
def _sampled_finish_j(core):
    """Apply the axis-0 facet masks to the sampled accumulator (the Fb
    weighting and spectral extraction already happened in the fold).

    The accumulator is DONATED: it is the size of the whole facet stack
    (9.8 GiB at 32k) and the caller never reuses it — an undonated
    finish materialises a second stack next to it, which is exactly what
    OOM'd the 32k round trip at the finish step."""

    def fn(acc, masks0):
        m = masks0[:, :, None]
        if _planar(core):
            m = m[..., None]
        return acc * m

    return _jit(donate=(0,))(_scoped("swiftly/bwd.finish", fn))


@functools.lru_cache(maxsize=None)
def _bwd_sampled_fold_sharded(core, mesh):
    """Facet-sharded fold: each device updates its local facets' image
    accumulator (no collectives — rows and acc share the facet axis)."""
    return _shmap(
        _scoped("swiftly/bwd.sampled_fold", _bwd_sampled_fold_fn(core)),
        mesh,
        in_specs=(
            _P(FACET_AXIS), _P(FACET_AXIS), _P(FACET_AXIS), _P(), _P(),
        ),
        out_specs=_P(FACET_AXIS),
        donate=(0,),
    )


# -- FFT (spectral-embed) backward fold --------------------------------------
#
# The sampled fold's adjoint DFT costs 8 * R_g * yB^2 * F per column group
# — R_g grows with the group, so fold FLOPs are ~flat per COLUMN
# (1.7e14 at 32k, the single largest block of the backward's wall-clock,
# measured 13.7% of peak). But the identical accumulation runs as the
# reference-shaped adjoint chain: scatter-embed each column's rows at its
# spectral window (`add_to_facet_math` — duplicate positions accumulate),
# ONE matmul-FFT finish (`finish_facet_math`), add into the donated image
# accumulator. Cost per GROUP is F * fft(yN over yB) + embeds — flat in
# group size — so at fold groups of 3+ columns it beats the sampled fold
# outright and keeps improving with bigger groups. Exactness: every step
# is the linear op the `_facet_pass_bwd` path runs (tested equal to the
# sampled fold), and fft(sum of embeds) == sum over groups by linearity.
# The [F, yN, Cj] spectral transient is bounded by chunking the
# pass-through output axis j (clamped starts + `keep` masking make any
# yB exact, the `_fold_row_block` pattern).


def _fft_fold_chunk(core, F, yB) -> int:
    """Static j-chunk width for the FFT fold's spectral transient
    [F, yN, Cj(,2)] — ~SWIFTLY_FFT_FOLD_CHUNK_MB (default 96) regardless
    of config; lane-aligned like `_fold_row_block`. The matmul-FFT keeps
    ~3 chunk-sized intermediates live, so the fold's peak transient is
    ~3x this target — 96 MB fits the roundtrip reserve that the sampled
    fold's 192 MB row blocks calibrated (384 MB OOM'd the 32k roundtrip
    at col_group=3)."""
    import os

    target = float(os.environ.get("SWIFTLY_FFT_FOLD_CHUNK_MB", "96")) * 1e6
    dsize = np.dtype(core.dtype).itemsize * (2 if _planar(core) else 1)
    per_col = max(1, F * core.yN_size * dsize)
    C = int(target // per_col)
    if C >= yB:
        return yB
    return max(1, (C // 128) * 128 or C)


def _bwd_fft_fold_chunk_fn(core, Cj, axis_name=None):
    """One j-chunk of the FFT fold: acc [F, yB, yB(,2)] += embed+fft+
    finish of rows_g[:, :, :, start:start+Cj].

    Dispatched once per chunk from a host loop with the accumulator
    donated across dispatches (the sampled fold's proven pattern) — a
    lax.scan carrying the multi-GiB accumulator through this body either
    lost input/output aliasing (compile-time "Used 18.07G of 15.75G") or
    hung the remote AOT compiler outright. `j0`/`start` are traced
    device scalars so every chunk reuses ONE compiled program; the
    clamped final chunk re-covers columns the previous chunk already
    folded and `keep` zeroes those, making the tiling exact for any yB.

    Emits the same accumulator contract as `_bwd_sampled_fold_fn` (Fb
    weighting and spectral extraction applied; axis-0 masks left to the
    finish), so the two folds are drop-in interchangeable per group.
    """
    import jax.numpy as jnp

    p = core._p
    yN = core.yN_size

    def fn(acc, rows_g, col_offs0, foffs0, j0, start):
        g = rows_g.shape[0]
        F, yB = acc.shape[0], acc.shape[1]
        tail = rows_g.shape[4:]
        z = jnp.int32(0)
        blk = jax.lax.dynamic_slice(
            rows_g,
            (z, z, z, start) + (z,) * len(tail),
            (g, F, rows_g.shape[2], Cj) + tail,
        )  # [g, F, m, Cj(,2)]
        spec = jnp.zeros((F, yN, Cj) + tail, dtype=rows_g.dtype)
        if axis_name is not None:
            spec = varying(spec, axis_name)
        # unrolled over the group's columns (g <= the feeding group cap)
        for k in range(g):
            spec = spec + jax.vmap(
                lambda c, k=k: add_to_facet_math(
                    p, yN, core.N, c, col_offs0[k], 0
                )
            )(blk[k])

        def fin(sp, off0):
            return finish_facet_math(p, core._Fb, yB, sp, off0, 0)

        out = jax.vmap(fin)(spec, foffs0)  # [F, yB, Cj(,2)]
        j = start + jnp.arange(Cj, dtype=jnp.int32)
        keep = (j >= j0).astype(rows_g.dtype)
        out = out * keep[None, None, :].reshape(
            (1, 1, Cj) + (1,) * len(tail)
        )
        cur = jax.lax.dynamic_slice(
            acc, (z, z, start) + (z,) * len(tail), (F, yB, Cj) + tail
        )
        return jax.lax.dynamic_update_slice(
            acc, cur + out, (z, z, start) + (z,) * len(tail)
        )

    return fn


@functools.lru_cache(maxsize=None)
def _bwd_fft_fold_chunk_j(core, Cj):
    return _jit(donate=(0,))(
        _scoped("swiftly/bwd.fft_fold", _bwd_fft_fold_chunk_fn(core, Cj))
    )


@functools.lru_cache(maxsize=None)
def _bwd_fft_fold_chunk_sharded(core, mesh, Cj):
    """Facet-sharded FFT fold chunk (embed + fft are facet-local; no
    collectives — rows and acc share the facet axis)."""
    return _shmap(
        _scoped(
            "swiftly/bwd.fft_fold",
            _bwd_fft_fold_chunk_fn(core, Cj, axis_name=FACET_AXIS),
        ),
        mesh,
        in_specs=(
            _P(FACET_AXIS), _P(None, FACET_AXIS), _P(), _P(FACET_AXIS),
            _P(), _P(),
        ),
        out_specs=_P(FACET_AXIS),
        donate=(0,),
    )


# -- Cooley-Tukey sampled backward fold --------------------------------------
#
# The sampled fold evaluates out[f, i, j] = sum_r rows2[f, r, j] *
# W^{-kt_r * i} (W = e^{+2pi i/yN}) as one dense [i, r] DFT per group —
# 8 * R_g * yB^2 * F FLOPs, ~flat per COLUMN. Factoring the kernel the
# Cooley-Tukey way over kt_r = Q*a_r + b_r and i = q*P + p (P = yN/Q):
#
#   W^{-kt i} = e^{-2pi i a p / P} * e^{-2pi i b p / yN} * e^{-2pi i b q / Q}
#
# turns the fold into three DENSE stages with no scatters or rolls:
#   1. group rows by b-lane (a constant gather; a column's m consecutive
#      kt values hit each b exactly ceil(m/Q) times) and contract the
#      per-lane a-phases:        G[f,b,p,j]  (K = g*ceil(m/Q))
#   2. elementwise twiddle e^{-2pi i b p / yN}
#   3. one [q, b] DFT matmul:    out[f,q,p,j] -> reshape i = q*P + p
#      (K = Q = 128, flat in group size g)
# Stage 3 dominates at ~8 * Q * yB * yB * F FLOPs per group — R_g/Q times
# fewer than the direct fold (3-6x at production group sizes), and the
# MXU shapes are deep. Exactness: pure index algebra, no approximation;
# pinned against the sampled fold by tests at every backend.


def _ct_fold_tables(core, col_offs0):
    """Host-side index tables for the CT fold of one column group.

    Returns (Q, P, kmax, r_idx, a_vals): `r_idx[c, b, k]` is the global
    row index (into R = g*m concatenated rows) of the k-th row of column
    c landing in b-lane b (0 for pads), `a_vals[c, b, k]` its a-value in
    [0, P) (or -1 for pads — the device masks those contributions).
    Exact int64 host arithmetic (the in-trace version of this indexing is
    what the int32-overflow class preys on).
    """
    import math

    yN = core.yN_size
    m = core.xM_yN_size
    Q = math.gcd(128, yN)
    P = yN // Q
    kmax = -(-m // Q) if m >= Q else 1
    g = len(col_offs0)
    kt = sampled_row_indices(core, col_offs0).astype(np.int64)  # [g*m]
    r_idx = np.zeros((g, Q, kmax), dtype=np.int32)
    a_vals = np.full((g, Q, kmax), -1, dtype=np.int32)
    fill = np.zeros((g, Q), dtype=np.int32)
    for c in range(g):
        for rp in range(m):
            r = c * m + rp
            b = int(kt[r] % Q)
            a = int((kt[r] // Q) % P)
            k = fill[c, b]
            r_idx[c, b, k] = r
            a_vals[c, b, k] = a
            fill[c, b] += 1
    return Q, P, kmax, r_idx, a_vals


def _ct_fold_width(yB, all_planes_bytes) -> int:
    """Static j-width of one CT fold launch: the largest divisor of yB
    keeping ALL facets' concurrently-scheduled stage planes near
    SWIFTLY_CT_FOLD_MB (default 4096 MB). The TPU AOT compiler schedules
    every unrolled block concurrently (optimization_barrier is stripped;
    scan carries lose aliasing), so per-launch footprint is controlled
    by width alone."""
    import os

    target = float(os.environ.get("SWIFTLY_CT_FOLD_MB", "4096")) * 1e6
    want = max(1, int(np.ceil(all_planes_bytes / target)))
    for n in range(want, yB + 1):
        if yB % n == 0:
            return yB // n
    return 1


@functools.lru_cache(maxsize=None)
def _bwd_ct_fold_fn(core, Q, P, kmax, W, axis_name=None):
    """acc [F, yB, yB(,2)] += one j-window [j0, j0+W) of the CT-factored
    adjoint-sampled fold of concatenated column rows [F, R, yB(,2)]
    (same input layout and accumulator contract as
    `_bwd_sampled_fold_fn`); the caller loops yB/W windows, donating the
    accumulator across launches.

    The program is FULLY STATIC (no lax.scan: every loop-carried
    formulation of the multi-GiB accumulator with a non-trivial body
    lost XLA:TPU's carry aliasing — compile-time "Used 18.07G of
    15.75G" — or hung the remote AOT compiler), and its width W is sized
    so that ALL facets' stage planes fit HBM even fully
    concurrently-scheduled (the compiler strips optimization_barrier and
    overlaps every block).
    """
    import jax.numpy as jnp

    yN = core.yN_size
    planar = _planar(core)

    def fn(acc, rows, e0, krows, r_idx, a_vals, j0):
        F, yB = acc.shape[0], acc.shape[1]
        g = r_idx.shape[0]
        fdt = acc.dtype if planar else core._Fb.real.dtype
        Qi = -(-yB // P)
        yB_pad = Qi * P

        # e0 pre-rotation: rows2 = rows * W^{-e0_f kt_r} (the sampled
        # fold's own formula, exact int32 via _mulmod)
        p_cos, p_sin = _sampled_phases(
            core, _mulmod(e0.astype(jnp.int32)[:, None], krows[None, :], yN)
        )
        # stage-1 a-phases: T[c, b, k, p] = exp(-2pi i a p / P), zeroed
        # on pads (a = -1)
        pj = jnp.arange(P, dtype=jnp.int32)
        a_safe = jnp.maximum(a_vals, 0)
        theta1 = (-2 * np.pi / P) * jnp.mod(
            a_safe[..., None] * pj, P
        ).astype(fdt)
        mask = (a_vals >= 0).astype(fdt)[..., None]
        T_re = jnp.cos(theta1) * mask
        T_im = jnp.sin(theta1) * mask
        # stage-2 twiddle W2[b, p] = exp(-2pi i b p / yN): b*p < Q*P =
        # yN, int32-exact
        bj = jnp.arange(Q, dtype=jnp.int32)
        theta2 = (-2 * np.pi / yN) * (bj[:, None] * pj[None, :]).astype(fdt)
        W2_re, W2_im = jnp.cos(theta2), jnp.sin(theta2)
        # stage-3 DFT D[q, b] = exp(-2pi i q b / Q)
        qj = jnp.arange(Qi, dtype=jnp.int32)
        theta3 = (-2 * np.pi / Q) * jnp.mod(
            qj[:, None] * bj[None, :], Q
        ).astype(fdt)
        D_re, D_im = jnp.cos(theta3), jnp.sin(theta3)
        fb = core._p.extract_mid(core._Fb, yB, 0)  # [yB] real, no 1/yN
        fbj = jnp.asarray(fb.real if not planar else fb, fdt)
        flat_idx = r_idx.reshape(-1)  # [g*Q*kmax] constant gather

        from ..ops.planar_backend import matmul_precision

        prec = matmul_precision()

        def ein(spec, A, B):
            return jnp.einsum(spec, A, B, precision=prec)

        def fold_one(facet_rows, ws):
            """One facet's j-slice: gathered rows (planes or complex)
            [g, Q, kmax, w] -> finished [w-slice of out rows]."""
            if planar:
                grc, gic = facet_rows
                G_re = ein("cbkp,cbkj->bpj", T_re, grc) - ein(
                    "cbkp,cbkj->bpj", T_im, gic
                )
                G_im = ein("cbkp,cbkj->bpj", T_re, gic) + ein(
                    "cbkp,cbkj->bpj", T_im, grc
                )
                G2_re = (
                    G_re * W2_re[:, :, None] - G_im * W2_im[:, :, None]
                )
                G2_im = (
                    G_im * W2_re[:, :, None] + G_re * W2_im[:, :, None]
                )
                O_re = ein("qb,bpj->qpj", D_re, G2_re) - ein(
                    "qb,bpj->qpj", D_im, G2_im
                )
                O_im = ein("qb,bpj->qpj", D_re, G2_im) + ein(
                    "qb,bpj->qpj", D_im, G2_re
                )
                out = jnp.stack(
                    [
                        O_re.reshape(yB_pad, ws)[:yB],
                        O_im.reshape(yB_pad, ws)[:yB],
                    ],
                    axis=-1,
                )
                return out * fbj[:, None, None]
            (gth,) = facet_rows
            T = (T_re + 1j * T_im).astype(core.dtype)
            G = jnp.einsum("cbkp,cbkj->bpj", T, gth)
            W2 = (W2_re + 1j * W2_im).astype(core.dtype)
            G2 = G * W2[:, :, None]
            D = (D_re + 1j * D_im).astype(core.dtype)
            out = jnp.einsum("qb,bpj->qpj", D, G2).reshape(yB_pad, ws)[
                :yB
            ]
            return out * fbj.astype(core.dtype)[:, None]

        z = jnp.int32(0)
        ztail = (z,) * (len(acc.shape) - 3)
        for f in range(F):
            if planar:
                blkf = jax.lax.dynamic_slice(
                    rows, (jnp.int32(f), z, j0, z),
                    (1, rows.shape[1], W, 2),
                )[0]
                Rr, Ri = blkf[..., 0], blkf[..., 1]
                Rr2 = Rr * p_cos[f, :, None] + Ri * p_sin[f, :, None]
                Ri2 = Ri * p_cos[f, :, None] - Rr * p_sin[f, :, None]
                facet_rows = (
                    jnp.take(Rr2, flat_idx, axis=0).reshape(
                        (g, Q, kmax, W)
                    ),
                    jnp.take(Ri2, flat_idx, axis=0).reshape(
                        (g, Q, kmax, W)
                    ),
                )
            else:
                blkf = jax.lax.dynamic_slice(
                    rows, (jnp.int32(f), z, j0), (1, rows.shape[1], W)
                )[0]
                phi = (p_cos[f] - 1j * p_sin[f]).astype(core.dtype)
                facet_rows = (
                    jnp.take(blkf * phi[:, None], flat_idx, axis=0)
                    .reshape((g, Q, kmax, W)),
                )
            out = fold_one(facet_rows, W)
            # explicit slice/update (NOT .at[...].add, whose interior
            # slice lowers to scatter): the DUS chain is what the
            # compiler in-places through the donated acc
            cur = jax.lax.dynamic_slice(
                acc, (jnp.int32(f), z, j0) + ztail,
                (1, yB, W) + acc.shape[3:],
            )
            acc = jax.lax.dynamic_update_slice(
                acc, cur + out[None], (jnp.int32(f), z, j0) + ztail
            )
        return acc

    return fn


@functools.lru_cache(maxsize=None)
def _bwd_ct_fold_j(core, Q, P, kmax, W):
    return _jit(donate=(0,))(
        _scoped("swiftly/bwd.ct_fold", _bwd_ct_fold_fn(core, Q, P, kmax, W))
    )


@functools.lru_cache(maxsize=None)
def _bwd_ct_fold_sharded(core, mesh, Q, P, kmax, W):
    """Facet-sharded CT fold (all stages facet-local; no collectives)."""
    return _shmap(
        _scoped(
            "swiftly/bwd.ct_fold",
            _bwd_ct_fold_fn(core, Q, P, kmax, W, axis_name=FACET_AXIS),
        ),
        mesh,
        in_specs=(
            _P(FACET_AXIS), _P(FACET_AXIS), _P(FACET_AXIS), _P(),
            _P(), _P(), _P(),
        ),
        out_specs=_P(FACET_AXIS),
        donate=(0,),
    )


def resolve_fold_mode() -> str:
    """Backward fold body: SWIFTLY_FOLD = sampled | ct | fft | auto.

    "auto" -> sampled. The alternatives cut fold FLOPs substantially
    (ct: CT-factored, ~5x fewer at fold groups of 3; fft: spectral embed
    + matmul-FFT, ~2x) and both are exact (tests pin all three), but on
    the tunnel-attached v5e neither REALIZES the win: the AOT compiler
    in-places the multi-GiB accumulator only through the sampled fold's
    2-einsum scan body (every richer loop body lost carry aliasing —
    compile "Used 18.07G of 15.75G" — or hung the compiler;
    optimization_barrier is stripped, so unrolled programs schedule all
    blocks concurrently, and width-limited launch chains pay the ~70 ms
    per-dispatch floor x yB/W launches). Measured: sampled 0.52 s/fold
    (g=2) vs fft 1.71 s (g=3, 22 launches) vs ct compile-OOM at every
    one-launch shape. docs/performance.md has the full ledger.
    """
    import os

    mode = os.environ.get("SWIFTLY_FOLD", "auto")
    if mode not in ("ct", "fft", "sampled", "auto"):
        raise ValueError(
            f"SWIFTLY_FOLD must be ct|fft|sampled|auto, got {mode!r}"
        )
    return "sampled" if mode == "auto" else mode


# -- device-side sparse facet synthesis -------------------------------------


@functools.lru_cache(maxsize=None)
def _synth_slab_j(core, Fg, yB):
    """Scatter (facet, row, col, val) pixels into a zeroed real slab
    [Fg, yB, yB] — the device-side synthesis of point-source-model
    facets (`ops.oracle.SparseRealFacet`). Uploading coordinates instead
    of planes turns facet-slab streaming from h2d-bound (2 GB per 64k
    slab, once per column group) into compute-bound."""
    import jax.numpy as jnp

    dt = _np_dtype(core)

    def fn(f, r, c, v):
        z = jnp.zeros((Fg, yB, yB), dtype=dt)
        return z.at[f, r, c].add(v)

    return _jit()(_scoped("swiftly/fwd.facet_synth", fn))


# -- facet-group forward column step ----------------------------------------
#
# At N >= 65536 the facet stack exceeds HBM (36.5 GB planar at 64k), so
# the sampled-DFT path streams FACET GROUPS: columns are processed in
# groups of G, and within a column group the facets arrive in slabs of
# `facet_group`; each slab's PRE-FINISH contribution is ADDED into a
# per-column-group [G, S, xM, xM] accumulator (every stage of the
# transform is linear in the facets, so cross-slab accumulation is
# exact), and the finish (iFFT/crop/masks) runs ONCE per column group —
# finishing per slab cost n_slabs-1 extra finish passes, 44% of all
# FLOPs at 64k. Device residency: one facet slab + the accumulator +
# one sampled group buffer — bounded regardless of N.


def _column_group_step_fn(core, subgrid_size, chunk, colpass):
    """One facet slab's PRE-FINISH contribution, added into the group acc.

    acc [n_chunks, chunk, S, xM, xM(,2)]; buf [Fg, G*m, yB(,2)] is the
    slab's sampled rows for the whole column group (G = n_chunks*chunk).
    Columns are scanned `chunk` at a time to bound the per-step
    transient. The finish (iFFT/crop/masks) is NOT applied here: it
    runs ONCE per group (`_column_group_finish_j`) after all slabs
    accumulated — finishing per slab cost n_slabs-1 extra finish passes,
    44% of all FLOPs at 64k.

    `colpass` (einsum|pallas|fft) is EXPLICIT here: the fft body
    accumulates partials in a different space (grid, vs image for
    einsum/pallas), so the executor resolves the choice once (from its
    facet_group) and passes the same value to this step and to
    `_column_group_finish_j`.
    """
    m = core.xM_yN_size
    matrix_mode = colpass in ("einsum", "pallas")
    colfn = (
        None if matrix_mode
        else _column_pass_fwd_fft_fn(core, subgrid_size, finish=False)
    )
    matrix_body = (
        _colpass_einsum_body if colpass == "einsum" else _colpass_pallas_body
    )

    def fn(acc, buf, foffs0, foffs1, sg_offs_g):
        Fg = buf.shape[0]
        n_chunks = acc.shape[0]
        G = n_chunks * acc.shape[1]
        NMBF_g = jax.numpy.moveaxis(
            buf.reshape((Fg, G, m) + buf.shape[2:]), 1, 0
        )  # [G, Fg, m, yB(,2)]
        NMBF_c = NMBF_g.reshape((n_chunks, acc.shape[1]) + NMBF_g.shape[1:])

        if matrix_mode:
            # operator build hoisted out of the chunk scan (loop-invariant)
            ops = _colpass_operators(core, foffs0, foffs1)

            def one_col(nm, so):
                return matrix_body(
                    core, subgrid_size, ops, nm, foffs1, so, None, None,
                    finish=False,
                )

            def step(carry, xs):
                c, nm, so = xs
                out = jax.vmap(one_col)(nm, so)  # [chunk, S, xM, xM(,2)]
                return carry.at[c].add(out), None
        else:

            def step(carry, xs):
                c, nm, so = xs
                out = jax.vmap(colfn, in_axes=(0, None, None, 0))(
                    nm, foffs0, foffs1, so
                )  # [chunk, S, xM, xM(,2)]
                return carry.at[c].add(out), None

        idx = jax.numpy.arange(n_chunks)
        acc, _ = jax.lax.scan(step, acc, (idx, NMBF_c, sg_offs_g))
        return acc

    return fn


@functools.lru_cache(maxsize=None)
def _column_group_step_j(core, subgrid_size, chunk, colpass):
    return _jit(donate=(0,))(
        _scoped(
            "swiftly/fwd.slab_step",
            _column_group_step_fn(core, subgrid_size, chunk, colpass),
        )
    )


@functools.lru_cache(maxsize=None)
def _fused_sparse_slab_step_j(core, subgrid_size, chunk, Fg, yB, colpass):
    """ONE program per facet slab: sparse synthesis -> sampled-DFT pass
    -> column-group step, with the group accumulator donated through.

    The tunnel runtime pays ~0.1 s of latency per dispatch chain
    (measured, scripts/roofline.py); the unfused slab path cost three
    dispatches per slab. Fusing also lets XLA schedule the scatter and
    einsum together and drops the intermediate slab buffer's round trip
    through HBM allocation. Fusing FURTHER — the whole slab loop as one
    lax.scan program per column group — was measured 3x SLOWER at 64k
    (188.6 s vs 61.7 s full cover): the nested while-loops (slab scan >
    chunk scan > S-block map) serialize XLA's scheduling, so one
    dispatch per slab with the depth-2 checksum pipeline stands."""
    import jax.numpy as jnp

    sam = _facet_pass_sampled_fn(core, real_facets=True)
    step = _column_group_step_fn(core, subgrid_size, chunk, colpass)
    dt = _np_dtype(core)

    def fn(acc, f, r, c, v, e0, krows, foffs0, foffs1, so_c):
        slab = jnp.zeros((Fg, yB, yB), dtype=dt).at[f, r, c].add(v)
        buf = sam(slab, e0, krows)
        return step(acc, buf, foffs0, foffs1, so_c)

    return _jit(donate=(0,))(_scoped("swiftly/fwd.slab_step", fn))


def _column_group_finish_fn(core, subgrid_size, colpass):
    """Finish a whole group's accumulated partials in one program:
    [n_chunks, chunk, S, xM, xM(,2)] -> finished subgrids
    [n_chunks, chunk, S, xA, xA(,2)]. The einsum and pallas column
    passes accumulate IMAGE-space partials (iFFTs folded into their
    operators), so their finish is crop + masks; the fft pass
    accumulates grid-space partials and finishes with the crop iFFTs.
    `colpass` must be the value the executor passed to the
    `_column_group_step_fn` that filled the accumulator."""
    einsum_mode = colpass in ("einsum", "pallas")

    def fn(acc, sg_offs_g, masks0_g, masks1_g):
        def fin(summed, so, m0, m1):
            if einsum_mode:
                return _crop_masked_subgrid(
                    core, summed, so, subgrid_size, m0, m1
                )
            return finish_masked_subgrid(
                core, summed, so, subgrid_size, m0, m1
            )

        per_col = jax.vmap(fin)  # over S
        per_chunk = jax.vmap(per_col)  # over chunk
        return jax.vmap(per_chunk)(acc, sg_offs_g, masks0_g, masks1_g)

    return fn


@functools.lru_cache(maxsize=None)
def _column_group_finish_j(core, subgrid_size, colpass):
    # the accumulator is NOT donated: the finish crops xM -> xA, so no
    # output ever matches the donated buffer's shape and XLA ignored the
    # donation with a "Some donated buffers were not usable:
    # f32[...,xM,xM,2]" warning per compile (BENCH_r05 tail). The buffer
    # frees at the caller's `del acc` exactly as before.
    return _jit()(
        _scoped(
            "swiftly/fwd.group_finish",
            _column_group_finish_fn(core, subgrid_size, colpass),
        )
    )




# ---------------------------------------------------------------------------
# Shared plumbing
# ---------------------------------------------------------------------------


class _StreamedBase:
    def __init__(self, swiftly_config, facet_configs, col_block, residency):
        from ..api import _FacetStack

        self.config = swiftly_config
        self.core = swiftly_config.core
        self.mesh = getattr(swiftly_config, "mesh", None)
        if self.core.backend in ("numpy", "native"):
            raise ValueError(
                "Streamed execution requires a device backend "
                "('jax' or 'planar')"
            )
        if residency not in ("host", "device", "sampled"):
            raise ValueError(
                f"residency must be host|device|sampled, got {residency}"
            )
        if not facet_configs:
            raise ValueError(
                "facet_configs must be non-empty (the streamed paths "
                "size their programs from the first facet)"
            )
        self.residency = residency
        self.stack = _FacetStack(
            facet_configs, pad_to=_mesh_size(self.mesh)
        )
        self.col_block = int(col_block)
        yB = self.stack.size
        self._n_blocks = -(-yB // self.col_block)
        self._yB_pad = self._n_blocks * self.col_block
        self._foffs0 = self._place(np.asarray(self.stack.offs0))
        self._foffs1 = self._place(np.asarray(self.stack.offs1))
        rdt = self.core._Fb.dtype
        # realised once: per-call conversion/upload would sit on the hot
        # per-column accumulation path
        self._masks0_dev = self._place(np.asarray(self.stack.masks0, rdt))
        self._masks1_dev = self._place(np.asarray(self.stack.masks1, rdt))

    def _place(self, arr, facet_axis: int = 0):
        """Upload an array, facet-sharding `facet_axis` over the mesh (or
        plain default placement without one). Multihost-safe: on a pod
        slice each process supplies only its facet shard (see
        `mesh.place_facet_sharded`)."""
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(arr)
        from .mesh import place_facet_sharded

        return place_facet_sharded(arr, self.mesh, facet_axis)

    def _alloc_buffer(self, n_cols):
        F, m, yB = len(self.stack), self.core.xM_yN_size, self._yB_pad
        shape = (n_cols, F, m, yB) + _tail(self.core)
        return np.zeros(shape, dtype=_np_dtype(self.core))


def _whole_group_yield(groups, grp, G, arr):
    """(per_col_items, group_array) for a whole-group yield: real items
    per column, and the group array with the short final group's padded
    (repeated-last-column) entries sliced off — folding those would
    double-count."""
    per_col = [
        [it for it in groups[off0] if it[0] is not None] for off0 in grp
    ]
    return per_col, (arr if len(grp) == G else arr[: len(grp)])


def _group_full_columns(subgrid_configs):
    """Group configs by off0, padding ragged columns to equal length.

    Sparse/irregular covers leave columns with unequal subgrid counts;
    the stacked column programs need one static S. Short columns are
    padded with zero-mask configs whose rows are computed then discarded
    — exact (masks zero the padded outputs) and cheap (padding is at
    most one column's worth of work). Padded entries carry index None
    and sit at the END of each column, so program rows [0:n_real] always
    match the real items.
    """
    from ..api import _group_columns, _pad_ragged_columns

    groups, rectangular = _group_columns(
        list(enumerate(subgrid_configs)),
        key=lambda item: item[1],
        require_one_size=True,
    )
    if not rectangular:
        size = next(iter(groups.values()))[0][1].size
        _pad_ragged_columns(groups, size)
    return groups


class CachedColumnFeed:
    """On-demand lookups into a recorded subgrid stream.

    The sequential sibling (`StreamedForward._replay_spilled_groups`)
    feeds backward passes the whole stream in order; this feed is the
    SERVING-path view of the same `utils.spill.SpillCache`: it indexes
    every recorded subgrid by ``(off0, off1, size)`` at construction,
    and `lookup` returns one host row — a RAM slice or a single-row
    memmap read for disk-backed entries — so an individual request is
    answered without a device dispatch and without materialising a
    whole group stack.

    Exactness contract: a hit is a verbatim copy of the recorded
    stream's row (the cache stores plain float arrays), so a feed-served
    request is bit-identical to the streamed forward that recorded it.
    A config whose offsets match but whose masks differ from the
    recorded one is a MISS (masks are part of the result), as is any
    config the stream never covered. A hit whose backing entry has been
    evicted since indexing raises LookupError — consumers
    (`serve.SubgridService`) treat that as the signal to fall back to
    recomputation, the serving twin of the cache's degrade-to-replay
    contract.

    Version pinning: the feed captures the cache's ``stream_version``
    at construction (the `delta.FacetDeltaLedger` stamp). Once an
    incremental facet update moves the cache's version, every lookup
    raises LookupError — a feed indexed before the patch can never
    serve a row recorded (or patched) for a different facet stack;
    consumers rebuild the feed (`serve.SubgridService
    .post_facet_update`) or fall back to compute.
    """

    def __init__(self, spill, *, index=None, stream_version=None):
        if not getattr(spill, "complete", False):
            raise ValueError(
                "CachedColumnFeed requires a COMPLETE spill cache "
                "(begin_fill/put/end_fill with nothing evicted); an "
                "incomplete stream would silently miss-serve"
            )
        self._spill = spill
        self.stream_version = int(
            getattr(spill, "stream_version", 0)
            if stream_version is None else stream_version
        )
        # views over one shared stream (`cache.SharedStreamTier`) pass
        # a prebuilt index so N replicas don't re-scan the stream's
        # metadata N times; plain feeds build their own
        self._index = self.build_index(spill) if index is None else index
        self.hits = 0
        self.misses = 0
        self.evicted = 0
        self.stale = 0

    @staticmethod
    def build_index(spill):
        """``(off0, off1, size) -> (k, c, s, recorded config)`` over a
        complete recorded stream — the per-subgrid lookup table. Built
        once per stream and shareable across feeds: patch-mode facet
        updates rewrite entry PAYLOADS in place, so row coordinates
        (and therefore this index) survive them; only a re-record
        (replay) invalidates it."""
        index = {}
        for k in range(len(spill)):
            for c, col in enumerate(spill.meta(k)):
                for s, (_i, sg) in enumerate(col):
                    index[(sg.off0, sg.off1, sg.size)] = (k, c, s, sg)
        return index

    def __len__(self):
        return len(self._index)

    @staticmethod
    def _masks_match(a, b):
        ma = np.ones(a.size) if a.mask0 is None else np.asarray(a.mask0)
        mb = np.ones(b.size) if b.mask0 is None else np.asarray(b.mask0)
        if not np.array_equal(ma, mb):
            return False
        ma = np.ones(a.size) if a.mask1 is None else np.asarray(a.mask1)
        mb = np.ones(b.size) if b.mask1 is None else np.asarray(b.mask1)
        return np.array_equal(ma, mb)

    def _gate(self):
        """The serve gate: raises LookupError unless the backing stream
        is safe to read at this feed's pinned version (not mid-patch,
        still complete, version unmoved). Factored out of `lookup` so
        views that front this feed with a hot-row L1
        (`cache.FabricFeedView`) can run the SAME gate before serving
        an L1 row — an L1 hit must never outlive the version or bypass
        a patch window."""
        if getattr(self._spill, "patching", False):
            self.stale += 1
            if _metrics.enabled():
                _metrics.count("spill.feed_stale")
            raise LookupError(
                "cached stream is mid-update (a facet patch or replay "
                "is rewriting its entries); fall back to compute and "
                "rebuild the feed once the update lands"
            )
        if not getattr(self._spill, "complete", False):
            self.evicted += 1
            if _metrics.enabled():
                _metrics.count("spill.feed_evictions")
            raise LookupError(
                "recorded stream is no longer complete (a reset or "
                "eviction dropped its entries since this feed was "
                "indexed); fall back to compute"
            )
        current = int(getattr(self._spill, "stream_version", 0))
        if current != self.stream_version:
            self.stale += 1
            if _metrics.enabled():
                _metrics.count("spill.feed_stale")
            raise LookupError(
                f"cached stream version moved "
                f"({self.stream_version} -> {current}); this feed "
                "indexes a superseded facet stack — rebuild it"
            )

    def lookup(self, config):
        """The recorded host row for ``config``, or None on a miss;
        raises LookupError when the index hit an evicted entry or the
        whole recorded stream was dropped (a ``reset`` cleared
        ``complete`` — counted as an eviction), when the cache's
        stream version moved since this feed was built (a facet
        update patched the rows — this feed is stale), or when the
        cache is mid-rewrite (``patching`` set by
        `utils.spill.SpillCache.begin_patch`, which also brackets a
        replay's reset-to-refill window) — a partially-patched stream
        must never serve, even to a concurrent reader that races the
        patcher."""
        self._gate()
        hit = self._index.get((config.off0, config.off1, config.size))
        if hit is None or not self._masks_match(config, hit[3]):
            self.misses += 1
            if _metrics.enabled():
                _metrics.count("spill.feed_misses")
            return None
        k, c, s, _cfg = hit
        try:
            row = self._spill.get_row(k, (c, s))
        except (IndexError, FileNotFoundError, OSError) as exc:
            self.evicted += 1
            if _metrics.enabled():
                _metrics.count("spill.feed_evictions")
            raise LookupError(
                f"recorded stream entry {k} for subgrid "
                f"({config.off0}, {config.off1}) was evicted"
            ) from exc
        self.hits += 1
        if _metrics.enabled():
            _metrics.count("spill.feed_hits")
        return row


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


class StreamedForward:
    """Facets -> subgrids with bounded device residency.

    :param swiftly_config: SwiftlyConfig (device backend)
    :param facet_tasks: list of (FacetConfig, facet_data) pairs
    :param col_block: facet columns per streamed block (device working-set
        knob; the analogue of the reference's queue/LRU sizing)
    :param residency: execution strategy — "host" (default) runs the
        FFT-based facet pass and buffers NMBF_all in host RAM (scales to
        any N that fits host RAM); "device" selects the facets-resident
        sampled-DFT path: facets upload once and stay in HBM, each column
        group's contribution rows are one einsum, and no NMBF buffer or
        host round-trip exists at all (requires the facet stack to fit
        HBM)
    """

    def __init__(self, swiftly_config, facet_tasks, col_block=512,
                 residency="host", col_group=None, facet_group=None):
        if residency == "sampled":
            raise ValueError(
                "residency='sampled' is a StreamedBackward strategy; the "
                "forward equivalent is residency='device' (sampled DFT)"
            )
        self._base = _StreamedBase(
            swiftly_config, [cfg for cfg, _ in facet_tasks], col_block,
            residency,
        )
        core = self.core = self._base.core
        self.stack = self._base.stack
        # Facet data held host-side in device layout, one array per facet
        # (never stacked: the stack is larger than any single block).
        # All-real facets (planar) are stored as single real planes —
        # half the host RAM and half the upload volume; the sampled path
        # then also skips the zero imaginary plane's einsums. A task's
        # data may be a CALLABLE returning the facet (lazy construction:
        # at 64k one complex128 facet is 8 GB — materialising all of them
        # before conversion would double the host footprint).
        store, real_flags, sparse_flags = [], [], []
        from ..ops.oracle import SparseRealFacet

        sparse_ok = (
            _planar(core)
            and self._base.residency == "device"
            and self._base.mesh is None
        )
        for _, d in facet_tasks:
            raw = d() if callable(d) else d
            if isinstance(raw, SparseRealFacet):
                # keep sparse where the device-synthesis paths can use
                # it (planar single-device sampled executors); densify
                # for everything else
                if sparse_ok:
                    store.append(raw)
                    real_flags.append(True)
                    sparse_flags.append(True)
                    continue
                raw = raw.densify(_np_dtype(core))
            plane = _real_plane_or_none(core, raw)
            if plane is not None:
                store.append(plane)
                real_flags.append(True)
            else:
                store.append(_to_host_layout(core, raw))
                real_flags.append(False)
            sparse_flags.append(False)
            del raw
        # all-or-nothing: mixed sparse/dense stacks densify the sparse
        # entries (the synthesis programs scatter the WHOLE slab/stack)
        self._facets_sparse = bool(sparse_flags) and all(sparse_flags)
        if not self._facets_sparse and any(sparse_flags):
            for i, (s, is_sp) in enumerate(zip(store, sparse_flags)):
                if is_sp:
                    store[i] = s.densify(_np_dtype(core))
        self._facets_real = all(real_flags)
        if not self._facets_real and any(real_flags):
            # mixed: re-expand the real planes to planar pairs
            for i, (s, is_real) in enumerate(zip(store, real_flags)):
                if is_real:
                    pair = np.zeros(s.shape + (2,), dtype=s.dtype)
                    pair[..., 0] = s
                    store[i] = pair
        self._facet_data = store
        self._sparse_pad = None  # fixed per-facet pixel pad (one compile)
        self.col_group = col_group
        # facet_group: max facets device-resident at once (sampled path).
        # None = auto (all resident if the stack fits the HBM budget,
        # else slabs of 1 streamed per column group).
        self.facet_group = facet_group
        self._dev_facets = None
        self._nmbf = None
        self._col_index = None
        self.last_plan = None  # set by the sampled-path generators
        # extra device bytes the CALLER keeps resident during streaming
        # (e.g. an uploaded oracle-sample stack); subtracted from the HBM
        # budget the auto-sizers see
        self.hbm_headroom = 0
        # extra per-group output stacks the auto-sizers must price: the
        # spill-cache fill keeps ONE extra finished [G, S, xA, xA] stack
        # live (the previous group, until its d2h copy lands)
        self.spill_out_stacks = 0

    # -- sparse synthesis --------------------------------------------------

    def _sparse_pixels(self, i0, i1):
        """Concatenated (facet, row, col, val) pixel arrays for facets
        [i0, i1), facet index relative to i0, zero-padded to a fixed
        per-facet maximum so every slab shares ONE compiled scatter
        program (padding scatters value 0 at (0,0,0) — exact)."""
        n_real = self._base.stack.n_real
        if self._sparse_pad is None:
            self._sparse_pad = max(
                [d.n_pixels for d in self._facet_data] + [1]
            )
        width = i1 - i0
        pad_to = self._sparse_pad * width
        f = np.zeros(pad_to, np.int32)
        r = np.zeros(pad_to, np.int32)
        c = np.zeros(pad_to, np.int32)
        v = np.zeros(pad_to, _np_dtype(self.core))
        k = 0
        for j, i in enumerate(range(i0, min(i1, n_real))):
            sp = self._facet_data[i]
            n = sp.n_pixels
            f[k : k + n] = j
            r[k : k + n] = sp.rows
            c[k : k + n] = sp.cols
            v[k : k + n] = sp.vals
            k += n
        return f, r, c, v

    def synth_facet_device(self, i):
        """Facet i's dense real plane [yB, yB], synthesised on device
        (sparse mode only) — e.g. the round-trip reference for on-device
        RMS checks without a multi-GB upload."""
        if not self._facets_sparse:
            raise ValueError("synth_facet_device requires sparse facets")
        yB = self._base.stack.size
        fn = _synth_slab_j(self.core, 1, yB)
        return fn(*self._sparse_pixels(i, i + 1))[0]

    # -- facet pass --------------------------------------------------------

    def _facet_block(self, j0):
        """Host-side [F, yB, Cb(,2)] block of all facets' columns."""
        core, stack = self.core, self._base.stack
        Cb = self._base.col_block
        yB = stack.size
        shape = (len(stack), yB, Cb) + _tail(core)
        block = np.zeros(shape, dtype=_np_dtype(core))
        j1 = min(j0 + Cb, yB)
        for i, data in enumerate(self._facet_data):
            if self._facets_real and _planar(core):
                block[i, :, : j1 - j0, 0] = data[:, j0:j1]
            else:
                block[i, :, : j1 - j0] = data[:, j0:j1]
        return block

    def _build_nmbf(self, col_offs0):
        import jax.numpy as jnp

        base = self._base
        core = base.core
        if base.mesh is not None:
            fwd = _facet_pass_fwd_sharded(core, base.mesh)
        else:
            fwd = _facet_pass_fwd_j(core)
        col_offs0_j = jnp.asarray(col_offs0)
        buf = base._alloc_buffer(len(col_offs0))
        Cb = base.col_block
        pending = []  # (j0, device result) — simple 2-deep pipeline
        for j0 in range(0, base._yB_pad, Cb):
            with _metrics.stage("fwd.facet_pass") as st:
                block = self._facet_block(j0)
                st.bytes_moved = int(block.nbytes)  # h2d upload volume
                out = fwd(base._place(block), base._foffs0, col_offs0_j)
            pending.append((j0, out))
            if len(pending) > 1:
                pj, pout = pending.pop(0)
                with _metrics.stage("fwd.d2h") as st:
                    host = np.asarray(pout)
                    st.bytes_moved = int(host.nbytes)
                buf[:, :, :, pj : pj + Cb] = host
        for pj, pout in pending:
            with _metrics.stage("fwd.d2h") as st:
                host = np.asarray(pout)
                st.bytes_moved = int(host.nbytes)
            buf[:, :, :, pj : pj + Cb] = host
        self._nmbf = buf
        self._col_index = {int(off0): k for k, off0 in enumerate(col_offs0)}

    def _nmbf_column(self, k):
        """The k'th column's [F, m, yB] rows as a device array
        (facet-sharded on a mesh)."""
        yB = self._base.stack.size
        return self._base._place(self._nmbf[k][:, :, :yB])

    # -- column pass -------------------------------------------------------

    def _column_program(self, colfn, NMBF, items):
        from ..api import _subgrid_masks

        import jax.numpy as jnp

        base = self._base
        core = base.core
        rdt = core._Fb.dtype
        sg_offs = jnp.asarray([(sg.off0, sg.off1) for _, sg in items])
        ms = [_subgrid_masks(sg) for _, sg in items]
        return colfn(
            NMBF,
            base._foffs0,
            base._foffs1,
            sg_offs,
            jnp.asarray(np.stack([m[0] for m in ms]), rdt),
            jnp.asarray(np.stack([m[1] for m in ms]), rdt),
        )

    def _sampled_generator(self, groups, size, whole_groups=False):
        """Select the sampled-path generator (facets-resident vs
        facet-slab-streamed) — the ONE place the facet_group heuristic
        lives for both per-column and whole-group streaming."""
        fg = self.facet_group
        if fg is None and not self._facet_stack_fits():
            fg = 1
        if fg is not None and fg < self._base.stack.n_total:
            return self._grouped_device_columns(
                groups, size, fg, whole_groups=whole_groups
            )
        return self._device_columns(
            groups, size, whole_groups=whole_groups
        )

    def stream_column_groups(self, subgrid_configs, spill=None):
        """Yield (per_col_items, group_subgrids) per COLUMN GROUP of the
        sampled-DFT paths: `per_col_items` is a list (one entry per
        column) of [(input_index, SubgridConfig), ...] and
        `group_subgrids` the whole group's DEVICE array
        [G, S, xA, xA(,2)]. For consumers that process groups in one
        dispatch (e.g. `StreamedBackward.add_subgrid_group`) — slicing
        per column and re-dispatching per column pays the tunnel's
        per-dispatch latency G+ times over.

        With ``spill`` (a `utils.spill.SpillCache`) the stream is
        PERSISTED: the first call runs ONE forward pass, copying each
        group's finished stack d2h one group behind the compute (the
        copy overlaps the next group's dispatch chain), and every later
        call with a complete cache yields the SAME stream from host RAM
        (or disk) with the next group's h2d upload prefetched ahead of
        the consumer — no forward replay. A facet- or row-slab-
        partitioned backward (P consume passes) thus costs 1 forward +
        P cache feeds instead of P forwards + P backwards. If the
        stream exceeds the cache budget the fill gives up and every
        call replays the forward (exact, just the old cost model).
        """
        subgrid_configs = list(subgrid_configs)
        groups = _group_full_columns(subgrid_configs)
        size = subgrid_configs[0].size
        if self._base.residency != "device":
            raise ValueError(
                "stream_column_groups is a sampled-path (residency="
                "'device') API"
            )
        spill_tag = (
            len(subgrid_configs), size,
            (subgrid_configs[0].off0, subgrid_configs[0].off1),
            (subgrid_configs[-1].off0, subgrid_configs[-1].off1),
        )
        if spill is not None and spill.complete:
            if spill.tag != spill_tag:
                raise ValueError(
                    f"spill cache holds a different subgrid stream "
                    f"(tag {spill.tag} != {spill_tag}); reset() it or "
                    "pass the cover it was recorded for"
                )
            if _metrics.enabled():
                _metrics.count("spill.replay_feeds")
            n_yielded = 0
            try:
                for item in self._replay_spilled_groups(spill):
                    yield item
                    n_yielded += 1
                return
            except OSError as exc:
                # degradation ladder: a cached group stayed unreadable
                # past its retries mid-feed — fall back to replaying the
                # forward and resume the stream at the exact group the
                # cache failed on (groups stream in deterministic
                # order). Costs one forward pass; never a wrong answer.
                logger.warning(
                    "spill cache read failed at group %d (%s: %s); "
                    "replaying the forward for the rest of this pass",
                    n_yielded, type(exc).__name__, exc,
                )
                _degrade.record(
                    "spill", "replay_fallback",
                    f"group {n_yielded}: {type(exc).__name__}: {exc}",
                )
                spill.gave_up = True
                spill.complete = False
                if _metrics.enabled():
                    _metrics.count("spill.fallback_replays")
                    _metrics.count("fwd.passes")
                for k, item in enumerate(
                    self._sampled_generator(groups, size, whole_groups=True)
                ):
                    if k >= n_yielded:
                        yield item
                return
        if spill is not None and spill.gave_up:
            # a previous fill overflowed the budget: re-recording would
            # overflow again — replay the forward without the d2h cost
            if _metrics.enabled():
                _metrics.count("spill.fallback_replays")
            spill = None
        if _metrics.enabled():
            _metrics.count("fwd.passes")
        gen = self._sampled_generator(groups, size, whole_groups=True)
        if spill is None:
            yield from gen
            return
        self.spill_out_stacks = 1  # the sizers price the held-back stack
        try:
            spill.begin_fill(tag=spill_tag)
            prev = None
            for per_col, out_g in gen:
                # store group k-1 while group k's dispatch chain runs:
                # the d2h pull waits only on k-1's compute, so transfer
                # and compute overlap at depth 1
                if prev is not None:
                    self._spill_store(spill, *prev)
                prev = (per_col, out_g)
                yield per_col, out_g
            if prev is not None:
                self._spill_store(spill, *prev)
            spill.end_fill()
        finally:
            self.spill_out_stacks = 0

    def cached_feed(self, spill):
        """A `CachedColumnFeed` over a stream this forward recorded —
        the on-demand serving view (`swiftly_tpu.serve`) of the same
        cache the partitioned backward consumes sequentially. Requires
        a complete fill (one prior `stream_column_groups(spill=...)`
        pass)."""
        return CachedColumnFeed(spill)

    def _spill_store(self, spill, per_col, out_g):
        """Copy one yielded group's stack to the cache (d2h + put)."""
        if spill.gave_up:
            return  # an earlier eviction voided the fill: skip the d2h

        def pull():
            _fault_point("transfer.d2h")
            with _metrics.stage("spill.write") as st:
                arr = np.asarray(out_g)
                st.bytes_moved = int(arr.nbytes)
            return arr

        host = _retry(pull, site="transfer.d2h")
        if spill.put(per_col, host) and _metrics.enabled():
            _metrics.count("spill.writes")
            _metrics.count("spill.bytes_written", int(host.nbytes))

    def _replay_spilled_groups(self, spill):
        """Yield the cached stream with double-buffered h2d prefetch:
        group k+1's upload is DISPATCHED before group k is yielded, so
        the wire runs under the consumer's compute on group k.

        The host-side cache read of group k+1 (a disk read for
        disk-backed entries — the serial cost that used to sit between
        yields, blocking the consumer's fold dispatch) additionally runs
        on a background thread while the consumer computes on group k
        (``SWIFTLY_SPILL_PREFETCH=0`` disables the thread; the read
        then happens inline exactly as before). Failure semantics are
        unchanged: a read that stays failed past its retries raises
        HERE, before the previous group's yield, so the caller's
        replay-fallback resumes at the right group."""
        import concurrent.futures

        import jax.numpy as jnp

        import os

        use_thread = (
            os.environ.get("SWIFTLY_SPILL_PREFETCH", "1") != "0"
            and len(spill) > 1
        )
        tctx = _trace.current()

        def read(k):
            # worker threads adopt the caller's span so the spill.read
            # stage nests under the right feed in the timeline
            if _trace.current() != tctx:
                _trace.adopt(tctx)
            with _metrics.stage("spill.read") as st:
                host = spill.get(k)
                st.bytes_moved = int(host.nbytes)
            return host

        ex = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="swiftly-spill-read"
            )
            if use_thread
            else None
        )
        pending = None
        try:
            fut = ex.submit(read, 0) if ex is not None else None
            for k in range(len(spill)):
                # the feed's group span closes before the yield (generator
                # contextvars leak to the consumer between yields)
                with _trace.span("spill.feed_group", cat="spill", group=k):
                    if fut is not None:
                        host = fut.result()
                        fut = (
                            ex.submit(read, k + 1)
                            if k + 1 < len(spill)
                            else None
                        )
                        if _metrics.enabled():
                            _metrics.count("spill.async_reads")
                    else:
                        host = read(k)

                    def upload():
                        _fault_point("transfer.h2d")
                        with _metrics.stage("spill.h2d") as st:
                            arr = jnp.asarray(host)
                            st.bytes_moved = int(host.nbytes)
                        return arr

                    dev = _retry(upload, site="transfer.h2d")
                if _metrics.enabled():
                    _metrics.count("spill.prefetch_hits")
                if pending is not None:
                    yield pending
                pending = (spill.meta(k), dev)
            if pending is not None:
                yield pending
        finally:
            if ex is not None:
                ex.shutdown(wait=False, cancel_futures=True)

    def stream_columns(self, subgrid_configs, device_arrays=False):
        """Yield (col_items, subgrids) per column; one device program each.

        `col_items` is the column's [(input_index, SubgridConfig), ...];
        `subgrids` the matching stacked [S, xA, xA(,2)] — a host array by
        default, or the raw device array with `device_arrays=True` (for
        on-device consumers: device->host bandwidth may be the bottleneck
        on remote-attached TPUs).
        """
        subgrid_configs = list(subgrid_configs)
        groups = _group_full_columns(subgrid_configs)
        size = subgrid_configs[0].size
        if _metrics.enabled():
            _metrics.count("fwd.passes")
        if self._base.residency == "device":
            gen = self._sampled_generator(groups, size)
        else:
            if self._base.mesh is not None:
                colfn = _column_pass_fwd_sharded(
                    self.core, self._base.mesh, size
                )
            else:
                colfn = _column_pass_fwd_j(self.core, size)
            gen = self._host_columns(groups, colfn)
        if device_arrays:
            yield from gen
            return
        def pull(arr):
            def once():
                _fault_point("transfer.d2h")
                with _metrics.stage("fwd.d2h") as st:
                    host = np.asarray(arr)
                    st.bytes_moved = int(host.nbytes)
                return host

            return _retry(once, site="transfer.d2h")

        pending = []
        for items, out in gen:
            pending.append((items, out))
            if len(pending) > 1:
                pitems, pout = pending.pop(0)
                yield pitems, pull(pout)
        for pitems, pout in pending:
            yield pitems, pull(pout)

    def _host_columns(self, groups, colfn):
        """Host-buffered NMBF_all: FFT facet pass + per-column upload."""
        col_offs0 = list(groups)
        if self._nmbf is None or any(
            int(o) not in self._col_index for o in col_offs0
        ):
            self._build_nmbf(col_offs0)
        cp_flops = coll_bytes = 0
        if _metrics.enabled():
            from ..utils.flops import column_pass_flops
            from ..utils.profiling import column_collective_bytes

            base = self._base
            first = next(iter(groups.values()))
            colpass = _resolve_colpass(
                self.core, base.stack.n_total // _mesh_size(base.mesh)
            )
            cp_flops = column_pass_flops(
                self.core, base.stack.n_real, len(first),
                first[0][1].size, colpass,
            )
            coll_bytes = column_collective_bytes(
                self.core, _mesh_size(base.mesh), len(first), "forward"
            )
        for off0 in col_offs0:
            prog_items = groups[off0]  # incl. zero-mask padding at the end
            items = [it for it in prog_items if it[0] is not None]
            with _metrics.stage("fwd.h2d") as st:
                NMBF = self._nmbf_column(self._col_index[int(off0)])
                st.bytes_moved = int(getattr(NMBF, "nbytes", 0))
            with _metrics.stage(
                "fwd.column_pass", flops=cp_flops, bytes_moved=coll_bytes
            ):
                out = self._column_program(colfn, NMBF, prog_items)
            yield items, out

    def _upload_resident_facets(self):
        """Upload (or device-synthesise) the resident facet stack for the
        sampled path — the one-time h2d cost of residency='device',
        recorded as the `fwd.facet_upload` stage."""
        base = self._base
        core = base.core
        yB = base.stack.size
        n_pad = base.stack.n_total - base.stack.n_real
        with _metrics.stage("fwd.facet_upload") as st:
            if self._facets_sparse:
                # synthesise the resident stack on device: kilobytes of
                # coordinates uploaded instead of the multi-GB planes
                fn = _synth_slab_j(core, base.stack.n_total, yB)
                self._dev_facets = (
                    fn(*self._sparse_pixels(0, base.stack.n_total)),
                )
            elif self._facets_real:
                host = np.ascontiguousarray(
                    np.stack(
                        self._facet_data
                        + [np.zeros_like(self._facet_data[0])] * n_pad
                    )
                )
                self._dev_facets = (base._place(host),)
            elif _planar(core):
                # upload re/im planes as separate contiguous arrays (the
                # sampled program must not slice them out of a stacked
                # array — that would copy the multi-GiB stack)
                planes = []
                for p in (0, 1):
                    host = np.ascontiguousarray(
                        np.stack(
                            [d[..., p] for d in self._facet_data]
                            + [np.zeros_like(self._facet_data[0][..., p])]
                            * n_pad
                        )
                    )
                    planes.append(base._place(host))
                self._dev_facets = tuple(planes)
            else:
                self._dev_facets = (
                    base._place(
                        np.stack(
                            [np.asarray(d) for d in self._facet_data]
                            + [np.zeros_like(np.asarray(self._facet_data[0]))]
                            * n_pad
                        )
                    ),
                )
            st.bytes_moved = sum(
                int(getattr(a, "nbytes", 0)) for a in self._dev_facets
            )

    def _device_columns(self, groups, subgrid_size, whole_groups=False):
        """Facets-resident sampled-DFT pass in column groups.

        Facets upload ONCE and stay on device; each group of G columns'
        contribution rows is one einsum dispatch (compute proportional to
        the rows extracted, so chunking is free), and the group's G
        column passes run as ONE vmapped dispatch; nothing round-trips
        through the host. Device residency = facets + one [F, G*m, yB]
        group buffer + two in-flight [G, S, xA, xA] output stacks.
        """
        import jax
        import jax.numpy as jnp

        base = self._base
        core = base.core
        yB = base.stack.size
        if self._dev_facets is None:
            self._upload_resident_facets()
        e0 = base._place(
            (base.stack.offs0 - yB // 2).astype(np.int32)
        )
        col_offs0 = list(groups)
        G = self.col_group or self._auto_col_group(len(col_offs0))
        self.last_plan = {
            "mode": "resident", "col_group": G,
            # resolve from the PER-SHARD facet count: on a mesh the
            # shard_map bodies see local facets only, and the recorded
            # body must be the executed one
            "colpass": _resolve_colpass(
                core, base.stack.n_total // _mesh_size(base.mesh)
            ),
        }
        if self.last_plan["colpass"] == "pallas":
            bm, bn, bk = _colpass_blocks()
            self.last_plan["colpass_blocks"] = {
                "bm": bm, "bn": bn, "bk": bk,
                "sblock": _colpass_sblock(),
            }
        colpass_stage = "fwd.column_pass" + (
            ".pallas" if self.last_plan["colpass"] == "pallas" else ""
        )
        if base.mesh is not None:
            self.last_plan["mesh_shards"] = _mesh_size(base.mesh)
            self.last_plan["collective"] = _resolve_collective_env(
                _mesh_size(base.mesh)
            )
            samfn = _facet_pass_sampled_sharded(
                core, base.mesh, self._facets_real
            )
            gcolfn = _column_pass_fwd_group_sharded(
                core, base.mesh, subgrid_size
            )
        else:
            samfn = _facet_pass_sampled_j(core, self._facets_real)
            gcolfn = _column_pass_fwd_group_j(core, subgrid_size)
        from ..api import _subgrid_masks

        rdt = core._Fb.dtype
        fp_flops = cp_flops = coll_bytes = 0
        if _metrics.enabled():
            from ..utils.flops import (
                column_pass_flops,
                sampled_facet_pass_flops,
            )
            from ..utils.profiling import column_collective_bytes

            _metrics.gauge("fwd.plan", dict(self.last_plan))
            S = len(next(iter(groups.values())))
            fp_flops = sampled_facet_pass_flops(
                core, base.stack.n_real, yB, G * core.xM_yN_size,
                self._facets_real,
            )
            cp_flops = G * column_pass_flops(
                core, base.stack.n_real, S, subgrid_size,
                self.last_plan["colpass"],
            )
            coll_bytes = G * column_collective_bytes(
                core, _mesh_size(base.mesh), S, "forward"
            )
        prev_tail = None  # backpressure marker: group g-1's output stack
        for g0 in range(0, len(col_offs0), G):
            grp = col_offs0[g0 : g0 + G]
            # pad a short final group to the full G (row indices repeat the
            # last column; its outputs are skipped below) — a smaller krows
            # shape would trigger a full recompile of the sampled program
            grp_padded = grp + [grp[-1]] * (G - len(grp))
            krows = jnp.asarray(sampled_row_indices(core, grp_padded))
            sg_offs_g, m0_g, m1_g = [], [], []
            for off0 in grp_padded:
                prog_items = groups[off0]  # incl. zero-mask padding
                sg_offs_g.append(
                    [(sg.off0, sg.off1) for _, sg in prog_items]
                )
                ms = [_subgrid_masks(sg) for _, sg in prog_items]
                m0_g.append([mk[0] for mk in ms])
                m1_g.append([mk[1] for mk in ms])
            # JAX dispatch is asynchronous: without a wait the host loop
            # races ahead and every group buffer stays live at once,
            # overcommitting HBM. The wait must be a genuine host
            # round-trip — on the tunnel-attached TPU runtime here,
            # block_until_ready returns before the queue drains, so pull
            # an 8-byte checksum of the previous group instead.
            # one trace span per column group (run → leg → pass →
            # COLUMN GROUP → stage); closed before the yield because a
            # generator's contextvars are visible to the consumer
            # between yields — the consumer's spans must not nest here
            with _trace.span(
                "fwd.column_group", cat="fwd",
                group=g0 // G, n_cols=len(grp),
            ):
                if prev_tail is not None:
                    with _metrics.stage("fwd.drain"):
                        np.asarray(prev_tail)
                with _metrics.stage(
                    "fwd.sampled_facet_pass", flops=fp_flops
                ):
                    buf = samfn(*self._dev_facets, e0, krows)
                with _metrics.stage(
                    colpass_stage, flops=cp_flops,
                    bytes_moved=coll_bytes,
                ):
                    out_g = gcolfn(
                        buf,
                        base._foffs0,
                        base._foffs1,
                        jnp.asarray(sg_offs_g),
                        jnp.asarray(np.asarray(m0_g), rdt),
                        jnp.asarray(np.asarray(m1_g), rdt),
                    )  # [G, S, xA, xA(,2)]
                prev_tail = jnp.sum(out_g)
            if _metrics.enabled():
                _metrics.count(
                    "fwd.subgrids",
                    sum(
                        1
                        for off0 in grp
                        for it in groups[off0]
                        if it[0] is not None
                    ),
                )
                _metrics.count("fwd.column_groups")
                if self.last_plan["colpass"] == "pallas":
                    _metrics.count("fwd.pallas_cols", len(grp))
            if whole_groups:
                yield _whole_group_yield(groups, grp, G, out_g)
                continue
            for gi, off0 in enumerate(grp):
                prog_items = groups[off0]
                items = [it for it in prog_items if it[0] is not None]
                yield items, out_g[gi]

    def _grouped_device_columns(
        self, groups, subgrid_size, facet_group, whole_groups=False
    ):
        """Sampled-DFT pass streaming FACET SLABS: stacks larger than HBM.

        Column groups of G are the outer loop; within one, facet slabs of
        `facet_group` upload in turn and each slab's FINISHED contribution
        is added into the group's [G, S, xA, xA] accumulator (exact —
        every stage incl. the finish iFFT/crop/masks is linear in the
        facets). Device residency is one slab + the accumulator + one
        sampled buffer, bounded regardless of N; the cost is re-uploading
        the facet stack once per column group (h2d, overlapped with
        compute by the depth-2 dispatch pipeline below).
        """
        import collections

        import jax.numpy as jnp

        from ..api import _subgrid_masks

        base = self._base
        core = base.core
        if base.mesh is not None:
            raise ValueError(
                "facet_group streaming is a single-device strategy; on a "
                "mesh the facet stack is already sharded across devices — "
                "add devices instead of slabs"
            )
        yB = base.stack.size
        F_total = base.stack.n_total
        Fg = int(facet_group)
        n_slabs = -(-F_total // Fg)
        F_pad = n_slabs * Fg
        rdt = core._Fb.dtype

        col_offs0 = list(groups)
        first_col = next(iter(groups.values()))
        S = len(first_col)
        # slab pipeline depth: 2 overlaps upload with compute; at scales
        # where two slabs alone would eat half the budget (128k: one slab
        # is 8.1 GiB) fall back to 1 slab in flight
        budget = self._hbm_budget()
        fsize = np.dtype(core.dtype).itemsize * (
            1 if self._facets_real else (2 if _planar(core) else 1)
        )
        slab_bytes = Fg * yB * yB * fsize
        depth = 2
        if budget is not None and 2 * slab_bytes > 0.5 * budget:
            depth = 1
        chunk = 4
        if self.col_group:
            # honour an explicit G exactly: pick the largest chunk that
            # divides it rather than silently rounding G down
            G = max(1, int(self.col_group))
            chunk = next(c for c in (4, 3, 2, 1) if G % c == 0)
        else:
            if budget is None:
                G = len(col_offs0)
                chunk = next(c for c in (4, 3, 2, 1) if G % c == 0)
            else:
                # evaluate every (chunk, G) pair: chunk scales the
                # in-step transients, so a SMALLER chunk can buy a
                # bigger G — and fewer groups (fewer sampled dispatches
                # at the tunnel's latency floor) dominates the cost.
                # Tie-break on larger chunk (batches the fft body's
                # small matmuls; harmless for the einsum body).
                G, chunk = max(
                    (
                        (
                            max(1, (Gc // c) * c if Gc >= c else Gc),
                            c,
                        )
                        for c in (4, 3, 2, 1)
                        for Gc in (
                            grouped_col_group_for_budget(
                                base, budget, len(col_offs0), S,
                                subgrid_size, self._facets_real, Fg, c,
                                slab_depth=depth, warn=False,
                                extra_out_stacks=self.spill_out_stacks,
                            ),
                        )
                    ),
                    key=lambda t: (t[0], t[1]),
                )
        chunk = min(chunk, G)
        G = max(1, (G // chunk) * chunk)
        if not self.col_group and budget is not None:
            # re-evaluate the SELECTED (post-clamp) pair with the
            # warning armed: the sweep probed quietly, and warning for
            # a chunk size that is never dispatched would cry wolf
            grouped_col_group_for_budget(
                base, budget, len(col_offs0), S, subgrid_size,
                self._facets_real, Fg, chunk, slab_depth=depth,
                extra_out_stacks=self.spill_out_stacks,
            )
        n_chunks = G // chunk
        colpass = _resolve_colpass(core, Fg)
        n_groups = -(-len(col_offs0) // G)
        # triple-buffered streaming: a background thread fills staging
        # buffer (d+1) % 3 (pure host memcpy) while the main thread
        # dispatches slab d's async h2d and compute — h2d(k+1) ∥
        # compute(k) ∥ d2h(k-1). Disabled for the sparse-synth path (no
        # host staging exists) and via SWIFTLY_STREAM_PREFETCH=0.
        import os as _os

        use_prefetch = (
            not self._facets_sparse
            and _os.environ.get("SWIFTLY_STREAM_PREFETCH", "1") != "0"
            and n_slabs * n_groups > 1
        )
        n_stage = 3 if use_prefetch else 2
        self.last_plan = {
            "mode": "grouped", "col_group": G, "facet_group": Fg,
            "n_slabs": n_slabs, "slab_depth": depth,
            "facet_source": (
                "device-synth-sparse" if self._facets_sparse else "host"
            ),
            "colpass": colpass,
            "stream_prefetch": use_prefetch,
        }
        if colpass == "pallas":
            bm, bn, bk = _colpass_blocks()
            self.last_plan["colpass_blocks"] = {
                "bm": bm, "bn": bn, "bk": bk,
                "sblock": _colpass_sblock(),
            }
        if base.mesh is not None:
            self.last_plan["collective"] = _resolve_collective_env(
                _mesh_size(base.mesh)
            )
        fp_flops = step_flops = coll_bytes = 0
        if _metrics.enabled():
            from ..utils.flops import (
                column_pass_flops,
                sampled_facet_pass_flops,
            )
            from ..utils.profiling import column_collective_bytes

            _metrics.gauge("fwd.plan", dict(self.last_plan))
            fp_flops = sampled_facet_pass_flops(
                core, Fg, yB, G * core.xM_yN_size, self._facets_real
            )
            # the whole column-pass pipeline's FLOPs attributed to the
            # slab step (the group finish's iFFT/crop share is folded in
            # — the two stages are one pipeline split only for memory)
            step_flops = G * column_pass_flops(
                core, Fg, S, subgrid_size, colpass
            )
            coll_bytes = G * column_collective_bytes(
                core, _mesh_size(base.mesh), S, "forward"
            )

        # per-slab facet metadata, padded with zero facets to F_pad
        offs0 = np.concatenate(
            [np.asarray(base.stack.offs0), np.zeros(F_pad - F_total, int)]
        )
        offs1 = np.concatenate(
            [np.asarray(base.stack.offs1), np.zeros(F_pad - F_total, int)]
        )
        e0 = (offs0 - yB // 2).astype(np.int32)

        # Rotating host staging: building a fresh np.stack per slab
        # grows host RSS by one slab per dispatch at hour scale
        # (slab-sized arenas are retained, and async h2d can pin
        # buffers) — fatal at 64k where a slab is 2 GB and a pass uploads
        # ~70 of them. A fixed ring of persistent buffers rotates
        # instead: two without the prefetch thread (buffer i%2 reused
        # only after slab i-2's checksum — transfer AND compute — was
        # pulled), three with it (the worker refills buffer (d+1)%3
        # while slab d dispatches; that buffer was last used by slab
        # d-2, whose checksum the depth-2 drain pulled before slab d
        # dispatched, so the h2d engine is done reading it).
        n_planes = 2 if (_planar(core) and not self._facets_real) else 1
        stage = (
            None
            if self._facets_sparse  # synthesised on device: no staging
            else [
                [
                    np.empty((Fg, yB, yB), dtype=_np_dtype(core))
                    for _ in range(n_planes)
                ]
                for _ in range(n_stage)
            ]
        )

        def host_slab(s0, slot):
            bufs = stage[slot]
            for k in range(Fg):
                i = s0 + k
                for pi, buf in enumerate(bufs):
                    if i >= base.stack.n_real:
                        buf[k] = 0
                    elif n_planes == 2:
                        buf[k] = self._facet_data[i][..., pi]
                    else:
                        buf[k] = self._facet_data[i]
            return tuple(bufs)

        samfn = _facet_pass_sampled_j(core, self._facets_real)
        stepfn = _column_group_step_j(core, subgrid_size, chunk, colpass)
        finfn = _column_group_finish_j(core, subgrid_size, colpass)
        fusedfn = (
            _fused_sparse_slab_step_j(
                core, subgrid_size, chunk, Fg, yB, colpass
            )
            if self._facets_sparse
            else None
        )
        tail = _tail(core)
        xM = core.xM_size
        # depth-2 completion pipeline: before uploading slab i, wait for
        # slab i-2's column step (8-byte checksum pull — block_until_ready
        # is not completion on tunnel runtimes), bounding live slabs to 2.
        pending = collections.deque()
        n_slab_dispatch = 0  # continuous across groups: staging slot
        total_dispatch = n_slabs * n_groups
        # the prefetch worker fills by GLOBAL dispatch index: every group
        # sweeps the same s0 sequence, so slab d stages facet rows
        # (d % n_slabs) * Fg regardless of which group consumes it
        tctx = _trace.current()

        def _fill(d):
            if _trace.current() != tctx:
                _trace.adopt(tctx)
            with _metrics.stage("fwd.slab_prefetch"):
                return host_slab((d % n_slabs) * Fg, d % n_stage)

        prefetch_ex = None
        prefetch_fut = None  # (dispatch index, future)
        if use_prefetch:
            import concurrent.futures

            prefetch_ex = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="swiftly-slab-stage"
            )
            prefetch_fut = (0, prefetch_ex.submit(_fill, 0))
        t_start = time.time()
        logger.info(
            "grouped stream: %d columns in groups of %d (chunk %d), "
            "%d facet slabs of %d per group%s",
            len(col_offs0), G, chunk, n_slabs, Fg,
            " (prefetch thread)" if use_prefetch else "",
        )
        try:
            for g0 in range(0, len(col_offs0), G):
                grp = col_offs0[g0 : g0 + G]
                # one trace span per column group (the tentpole hierarchy:
                # run → leg → pass → COLUMN GROUP → stage); entered/exited
                # explicitly so it closes BEFORE the yield — contextvars
                # set in a generator are visible to the consumer between
                # yields, and the consumer's spans must not nest in here
                grp_span = _trace.span(
                    "fwd.column_group", cat="fwd",
                    group=g0 // G, n_cols=len(grp),
                )
                grp_span.__enter__()
                grp_padded = grp + [grp[-1]] * (G - len(grp))
                krows = jnp.asarray(sampled_row_indices(core, grp_padded))
                sg_offs_g, m0_g, m1_g = [], [], []
                for off0 in grp_padded:
                    prog_items = groups[off0]  # incl. zero-mask padding
                    sg_offs_g.append(
                        [(sg.off0, sg.off1) for _, sg in prog_items]
                    )
                    ms = [_subgrid_masks(sg) for _, sg in prog_items]
                    m0_g.append([mk[0] for mk in ms])
                    m1_g.append([mk[1] for mk in ms])

                def _chunked(x, dt=None):
                    a = jnp.asarray(np.asarray(x), dt)
                    return a.reshape((n_chunks, chunk) + a.shape[1:])

                so_c = _chunked(sg_offs_g)
                m0_c = _chunked(m0_g, rdt)
                m1_c = _chunked(m1_g, rdt)
                # PRE-finish accumulator ([.., xM, xM], 1.31x the finished
                # size): the finish runs once per group, not once per slab
                acc = jnp.zeros(
                    (n_chunks, chunk, S, xM, xM) + tail,
                    dtype=_np_dtype(core),
                )
                slab_dev = None
                for s0 in range(0, F_pad, Fg):
                    while len(pending) >= depth:
                        with _metrics.stage("fwd.drain"):
                            np.asarray(pending.popleft())
                    # drop the previous slab BEFORE uploading the next: at
                    # depth 1 both must never be live together
                    # slot from a CONTINUOUS dispatch counter, not the
                    # per-group slab index: with odd slabs-per-group a
                    # group-local slot would reuse the buffer of the
                    # previous group's final slab before its checksum (h2d
                    # + compute completion) was pulled
                    slab_dev = None  # noqa: F841 - releases device buffers
                    if fusedfn is not None:
                        # one dispatch: synth + sampled pass + column step
                        with _metrics.stage(
                            "fwd.slab_step",
                            flops=fp_flops + step_flops,
                            bytes_moved=coll_bytes,
                        ):
                            acc = fusedfn(
                                acc,
                                *self._sparse_pixels(s0, s0 + Fg),
                                jnp.asarray(e0[s0 : s0 + Fg]),
                                krows,
                                jnp.asarray(offs0[s0 : s0 + Fg]),
                                jnp.asarray(offs1[s0 : s0 + Fg]),
                                so_c,
                            )
                    else:
                        d = n_slab_dispatch
                        with _metrics.stage("fwd.slab_upload") as st:
                            bufs = None
                            if (
                                prefetch_fut is not None
                                and prefetch_fut[0] == d
                            ):
                                # bounded wait: a wedged fill thread must
                                # degrade to a counted miss (inline fill of
                                # the same slot with the same bytes), never
                                # stall the stream — host_slab is a pure
                                # memcpy, so 120 s is ~2 orders above any
                                # real slab
                                try:
                                    bufs = prefetch_fut[1].result(
                                        timeout=120.0
                                    )
                                    _metrics.count(
                                        "fwd.slab_prefetch_hits"
                                    )
                                except concurrent.futures.TimeoutError:
                                    prefetch_fut[1].cancel()
                                prefetch_fut = None
                            if bufs is None:
                                if use_prefetch:
                                    _metrics.count(
                                        "fwd.slab_prefetch_misses"
                                    )
                                bufs = host_slab(s0, d % n_stage)
                            slab_dev = tuple(
                                base._place(a) for a in bufs
                            )
                            st.bytes_moved = sum(
                                int(a.nbytes) for a in slab_dev
                            )
                        # h2d for slab d is dispatched: the worker may now
                        # refill buffer (d+1) % 3 — last used by slab d-2,
                        # whose checksum the drain above already pulled
                        if prefetch_ex is not None and d + 1 < total_dispatch:
                            prefetch_fut = (
                                d + 1,
                                prefetch_ex.submit(_fill, d + 1),
                            )
                        with _metrics.stage(
                            "fwd.sampled_facet_pass", flops=fp_flops
                        ):
                            buf = samfn(
                                *slab_dev,
                                jnp.asarray(e0[s0 : s0 + Fg]),
                                krows,
                            )
                        with _metrics.stage(
                            "fwd.slab_step",
                            flops=step_flops,
                            bytes_moved=coll_bytes,
                        ):
                            acc = stepfn(
                                acc,
                                buf,
                                jnp.asarray(offs0[s0 : s0 + Fg]),
                                jnp.asarray(offs1[s0 : s0 + Fg]),
                                so_c,
                            )
                    n_slab_dispatch += 1
                    pending.append(jnp.sum(acc))
                    if logger.isEnabledFor(logging.INFO):
                        logger.info(
                            "  group %d/%d slab %d/%d dispatched  t=%.0fs "
                            "rss=%.1fGiB",
                            g0 // G + 1, -(-len(col_offs0) // G),
                            s0 // Fg + 1, n_slabs,
                            time.time() - t_start, _rss_gib(),
                        )
                # finish the whole group in one program (acc freed by the
                # `del` below — donation can't alias it into the cropped
                # output; the runtime orders the finish after the pending
                # slab steps on the same buffer, and the depth-2 checksum
                # pipeline keeps bounding live slabs)
                with _metrics.stage("fwd.group_finish"):
                    finished = finfn(acc, so_c, m0_c, m1_c)
                del acc
                grp_span.__exit__(None, None, None)
                if _metrics.enabled():
                    _metrics.count(
                        "fwd.subgrids",
                        sum(
                            1
                            for off0 in grp
                            for it in groups[off0]
                            if it[0] is not None
                        ),
                    )
                    _metrics.count("fwd.column_groups")
                    if colpass == "pallas":
                        _metrics.count("fwd.pallas_cols", len(grp))
                if whole_groups:
                    flat = finished.reshape((G,) + finished.shape[2:])
                    yield _whole_group_yield(groups, grp, G, flat)
                    continue
                for gi, off0 in enumerate(grp):
                    prog_items = groups[off0]
                    items = [it for it in prog_items if it[0] is not None]
                    yield items, finished[gi // chunk, gi % chunk]
        finally:
            if prefetch_ex is not None:
                prefetch_ex.shutdown(wait=False, cancel_futures=True)

    def _hbm_budget(self):
        """Per-device HBM budget in bytes (None = unlimited, e.g. CPU).

        Delegates to the unified parser `plan.hbm_budget_bytes`
        (SWIFTLY_HBM_BUDGET if set, else the usable capacity from
        `utils.profiling.probe_hbm_bytes`, else 14e9 as a last resort);
        the executor keeps its historical CPU-is-unlimited semantics
        (``honor_env_on_cpu=False``)."""
        from ..plan.model import hbm_budget_bytes

        return hbm_budget_bytes(
            headroom=self.hbm_headroom, default=14e9,
            honor_env_on_cpu=False,
        )

    def _facet_stack_fits(self):
        """Whether the whole facet stack can stay device-resident with
        room for at least a one-column working set."""
        budget = self._hbm_budget()
        if budget is None:
            return True
        return (
            facet_stack_bytes(self._base, self._facets_real) + 3e9 <= budget
        )

    def _auto_col_group(self, n_cols):
        """Largest column-group whose buffer + transients fit the budget
        (facets-resident sampled path). On CPU the full column set is one
        group."""
        budget = self._hbm_budget()
        if budget is None:
            return n_cols
        return col_group_for_budget(
            self._base, budget, n_cols, real=self._facets_real,
            extra_out_stacks=self.spill_out_stacks,
        )

    def all_subgrids(self, subgrid_configs):
        """Every subgrid, in request order, as one host array [n, xA, xA]."""
        out = None
        for items, subgrids in self.stream_columns(subgrid_configs):
            if out is None:
                out = np.zeros(
                    (len(subgrid_configs),) + subgrids.shape[1:],
                    dtype=subgrids.dtype,
                )
            for s, (i, _) in enumerate(items):
                out[i] = subgrids[s]
        return out


def facet_stack_bytes(base, real=False):
    """Device bytes of the (padded) resident facet stack."""
    core = base.core
    itemsize = np.dtype(core.dtype).itemsize
    per_el = itemsize if real else itemsize * (2 if _planar(core) else 1)
    yB = base.stack.size
    F = base.stack.n_total // _mesh_size(base.mesh)
    return F * yB * yB * per_el


def grouped_col_group_for_budget(
    base, budget, n_cols, S, subgrid_size, real, facet_group, chunk,
    slab_depth=2, warn=True, extra_out_stacks=0,
):
    """Largest column-group G for the facet-slab-streamed sampled path.

    Live per unit G: the slab's sampled buffer [Fg, m, yB] plus its
    in-step [G, Fg, m, yB] transpose, and the finished accumulator row
    [S, xA, xA]. Flat: `slab_depth` facet slabs in flight (the upload
    pipeline; 1 at scales where two slabs alone overflow HBM), the
    per-chunk scan transients ([chunk, S, xM, xM] carry + prep1 rows),
    and a trig/fragmentation reserve. ``warn=False`` evaluates quietly —
    the executor's (G, chunk) sweep probes chunks it may not select and
    re-warns only for the chosen pair. ``extra_out_stacks`` prices
    additional caller-held [S, xA, xA]-per-unit-G output stacks: the
    spill-cache fill holds the previous group's finished stack until
    its d2h copy lands (`StreamedForward.spill_out_stacks`), and a
    consumer pinning group stacks for other reasons can account for
    them the same way.

    CALIBRATION BASIS (r5): the consumer-transient term was relaxed from
    3x to 2x [S, xA, xA] against measured 128k boundaries on a 16 GiB
    v5e — G=4 streams green where the 3x model allowed only G=2, and
    the OOM edge sits at G=6 with two groups in flight. Configs between
    the calibrated points sit closer to that edge, with the bench's
    `_oom_soft` shrink-and-retry as the backstop; the operator escape
    hatch is ``SWIFTLY_HBM_BUDGET`` (explicit byte budget — lower it to
    move any config away from the edge, raise it on bigger-HBM parts).
    See docs/observability.md for how to read the plan gauges a run
    records.
    """
    core = base.core
    dsize = np.dtype(core.dtype).itemsize * (2 if _planar(core) else 1)
    fsize = np.dtype(core.dtype).itemsize * (1 if real else 2)
    yB = base.stack.size
    m = core.xM_yN_size
    xM = core.xM_size
    xA = subgrid_size
    slab_b = slab_depth * facet_group * yB * yB * fsize
    grouped_colpass = _resolve_colpass(core, facet_group)
    if grouped_colpass == "einsum":
        # per column in the chunk vmap: prep1 rows, the H buffer plus its
        # wrap-extended gather copy, and one [Sb, Fg, xM, m] gather block
        Sb = min(_colpass_sblock(), S)
        Sb = -(-S // -(-S // Sb))  # executed blocks are rebalanced
        chunk_b = (
            chunk * S * xM * xM
            + chunk * facet_group * (
                m * core.yN_size
                + xM * (2 * core.yN_size + m)
                + Sb * xM * m
            )
        ) * dsize
    elif grouped_colpass == "pallas":
        # the fused kernel has NO H buffer (the prepare matmul runs
        # inside the grid program) and its gather block is [Sb, Fg, m,
        # m] — counted twice for the kernel's padded operand copies
        Sb = min(_colpass_sblock(), S)
        Sb = -(-S // -(-S // Sb))  # executed blocks are rebalanced
        chunk_b = (
            chunk * S * xM * xM
            + chunk * facet_group * (
                m * core.yN_size + 2 * Sb * m * m
            )
        ) * dsize
    else:
        chunk_b = (
            chunk * S * xM * xM + chunk * facet_group * m * core.yN_size
        ) * dsize
    # 4x the group buffer: the sampled pass materialises out_re/out_im
    # and their stacked pair next to the [Fg, G*m, yB] buffer and its
    # in-step transpose. The accumulator is pre-finish [S, xM, xM];
    # the finished group array plus the depth-2 pipeline's in-flight
    # copy add 2x [S, xA, xA]. (Was 3x after the BENCH_r04 32k OOMs;
    # recalibrated against measured 128k runs — G=4 streams green where
    # the 3x model allowed only G=2, and the OOM boundary sits at G=6
    # with two groups in flight.)
    per_G = (
        4 * facet_group * m * yB + S * xM * xM
        + (2 + extra_out_stacks) * S * xA * xA
    ) * dsize
    reserve = 0.6e9
    headroom = budget - slab_b - chunk_b - reserve
    if warn and headroom <= per_G:
        # a provably-unfittable plan must not proceed silently: the
        # minimum group still gets dispatched (fail-soft callers catch
        # the OOM and resize), but the operator is told why
        logger.warning(
            "HBM budget %.2f GiB cannot fit even one %d-column chunk "
            "(flat costs %.2f GiB + %.2f GiB per column group); "
            "proceeding with the minimum group — expect OOM, reduce "
            "facet_group or raise SWIFTLY_HBM_BUDGET",
            budget / 2**30, chunk,
            (slab_b + chunk_b + reserve) / 2**30, per_G / 2**30,
        )
    # no chunk rounding here: the caller picks the (G, chunk) pair —
    # rounding G down to a chunk multiple at this level cost 64k a
    # third of its group size
    G = int(headroom // per_G)
    return max(1, min(G, ((n_cols + chunk - 1) // chunk) * chunk))


def col_group_for_budget(base, budget, n_cols, real=False,
                         extra_out_stacks=0):
    """Largest sampled-DFT column-group G whose working set fits `budget`
    bytes on one device (facet stack + per-G transients).

    Live per unit G (every G-proportional buffer counts here so the
    sizing scales to devices with more HBM than the calibration point):
      - sampled group buffer [F, m, yB] and its in-program [G,F,m,yB]
        transpose                              -> 2 * F*m*yB
      - prep1 output [F, m, yN]                -> F*m*yN
      - the scan carry [S, xM, xM]             -> S*xM^2
      - two in-flight output stacks [S,xA,xA]  -> 2 * S*xA^2
    plus a flat reserve for trig tables and fragmentation. The reserve
    is calibrated against measured 32k runs on a 16 GiB v5e: G=4 fits
    and is fastest (17.5 s vs 18.5 s at G=2); the pre-scan vmap layout
    OOM'd (see `_column_pass_fwd_fn`). On a mesh the facet stack and
    group buffers are sharded: everything counts PER DEVICE.
    """
    core = base.core
    dsize = np.dtype(core.dtype).itemsize * (2 if _planar(core) else 1)
    yB = base.stack.size
    facets_b = facet_stack_bytes(base, real)
    F = len(base.stack) // _mesh_size(base.mesh)
    reserve = 0.4e9  # calibrated: yields G=4 at the v5e 14e9 default
    m = core.xM_yN_size
    xA = base.config.max_subgrid_size
    xM = core.xM_size
    S = -(-core.N // xA)
    resident_colpass = _resolve_colpass(core, F)
    if resident_colpass in ("einsum", "pallas"):
        # the einsum/pallas group fn maps columns SEQUENTIALLY, so the
        # column transients (prep1 rows, gather block, image partials
        # — plus for einsum the H buffer + its wrap-extended copy) are
        # flat — only the sampled group buffer (with its einsum plane
        # transients and in-program transpose) and the in-flight output
        # stacks scale with G
        Sb = min(_colpass_sblock(), S)
        Sb = -(-S // -(-S // Sb))  # executed blocks are rebalanced
        if resident_colpass == "einsum":
            flat_col = (
                F * m * core.yN_size
                + F * xM * (2 * core.yN_size + m)
                + Sb * F * xM * m
                + S * xM * xM
            ) * dsize
        else:
            # pallas: no H buffer; [Sb, F, m, m] gather block counted
            # twice for the kernel's padded operand copies
            flat_col = (
                F * m * core.yN_size
                + 2 * Sb * F * m * m
                + S * xM * xM
            ) * dsize
        col_b = (
            3 * F * m * yB + (2 + extra_out_stacks) * S * xA * xA
        ) * dsize
        headroom = budget - facets_b - reserve - flat_col
    else:
        col_b = (
            2 * F * m * yB + F * m * core.yN_size
            + S * xM * xM + (2 + extra_out_stacks) * S * xA * xA
        ) * dsize
        headroom = budget - facets_b - reserve
    if headroom <= col_b:
        logger.warning(
            "HBM budget %.2f GiB cannot fit the resident facet stack "
            "(%.2f GiB) plus one column group (%.2f GiB); proceeding "
            "with G=1 — expect OOM, use facet_group slab streaming or "
            "raise SWIFTLY_HBM_BUDGET",
            budget / 2**30, facets_b / 2**30, col_b / 2**30,
        )
    G = int(headroom // col_b)
    return max(1, min(n_cols, G))


# ---------------------------------------------------------------------------
# Feed-once/fold-many scheduling
# ---------------------------------------------------------------------------


def feed_backward_passes(forward, subgrid_configs, backwards, spill=None,
                         progress=None, feed_index=None):
    """Feed ONE pass over the subgrid stream to MANY backward passes.

    A facet × row-slab partitioned backward runs P independent
    `StreamedBackward` passes over the SAME subgrid stream; feeding each
    pass separately moves the whole cached stream host→device P times
    (the 64k ledger's dominant waste after the spill cache removed the
    forward replays). This helper is the feed-once/fold-many schedule:
    each cached column group is uploaded ONCE and every pending pass's
    adjoints for that group are applied on-device before the stream
    advances — (len(backwards) − 1)× of the feed's ``spill.h2d`` bytes
    gone. How many passes may share a feed is a plan decision
    (`plan.compiler.plan_backward_feed` sizes it so all the shared
    accumulators + fold pipelines fit the HBM budget next to the feed's
    working set); the caller chunks its pass list accordingly and calls
    this once per chunk.

    Works with any forward/backward pair that speaks the streamed API
    (`stream_column_groups` / `add_subgrid_group`) — the mesh engines
    (`swiftly_tpu.mesh`) inherit it, so the multi-chip backward consumes
    the same schedule.

    Instrumentation: the whole shared feed is one ``bwd.feed_group``
    trace span, and a ``bwd.feed_group`` stage records the wall spent
    BLOCKED ON THE FEED (generator advance: cache read + h2d dispatch,
    i.e. the part the async prefetch and the fold overlap hide) with the
    cache-fed h2d bytes attributed — the measured counterpart of the
    plan's ``bwd.feed_group`` stage prediction, refit by
    `plan.autotune` like any other stage. Counters: ``bwd.feed_groups``
    (feeds run) and ``bwd.feed_passes`` (passes served). When the
    caller stamps ``feed_index`` and a LATER feed (index > 0) runs
    uncached — the replay spill policy, where each feed past the first
    re-runs the forward — the blocked-on-feed wall is recorded as
    ``fwd.replay`` instead, the measured counterpart of the plan's
    replay pricing (`plan.model.price_backward`, ``allow_spill=False``).
    The plan-accuracy ledger (`obs.ledger`) joins both names.

    :param forward: a `StreamedForward` (or `mesh.MeshStreamedForward`)
    :param subgrid_configs: the cover every pass consumes
    :param backwards: the `StreamedBackward` passes sharing this feed
    :param spill: the shared `utils.spill.SpillCache` (pass 1 of the
        whole schedule records it; later feeds replay from it)
    :param progress: optional callable(n_subgrids_folded) — heartbeat
    :param feed_index: this feed's position in the schedule (0-based);
        lets an uncached later feed attribute its wall to
        ``fwd.replay`` (None: always ``bwd.feed_group``)
    :returns: number of column groups fed
    """
    backwards = list(backwards)
    if not backwards:
        return 0
    cached = spill is not None and getattr(spill, "complete", False)
    n_groups = 0
    feed_wall = 0.0
    feed_bytes = 0
    with _trace.span(
        "bwd.feed_group", cat="bwd", n_passes=len(backwards)
    ):
        gen = forward.stream_column_groups(subgrid_configs, spill=spill)
        while True:
            t0 = time.monotonic()
            try:
                per_col, group = next(gen)
            except StopIteration:
                break
            feed_wall += time.monotonic() - t0
            if cached:
                feed_bytes += int(getattr(group, "nbytes", 0))
            n_groups += 1
            cols = [[sg for _, sg in col] for col in per_col]
            for bwd in backwards:
                bwd.add_subgrid_group(cols, group)
            if progress is not None:
                progress(sum(len(c) for c in cols) * len(backwards))
    if _metrics.enabled():
        _metrics.count("bwd.feed_groups")
        _metrics.count("bwd.feed_passes", len(backwards))
        if feed_index is not None and feed_index > 0 and not cached:
            # uncached later feed: the forward re-ran to regenerate the
            # stream, so the blocked wall is replay cost — the plan's
            # fwd.replay stage, not shared-feed traffic
            _metrics.observe(
                "fwd.replay", feed_wall, bytes_moved=feed_bytes
            )
        else:
            _metrics.observe(
                "bwd.feed_group", feed_wall, bytes_moved=feed_bytes
            )
    return n_groups


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


class StreamedBackward:
    """Subgrids -> facets with bounded device residency.

    Subgrids are fed column-grouped in any order; repeated columns
    accumulate (every fold is linear). `finish()` streams the column
    buffer back through the device to emit the facet stack.

    :param residency: "host" buffers per-column NAF rows in host RAM;
        "device" keeps them as device arrays (both sized K*[F, m, yB]);
        "sampled" folds each column's rows STRAIGHT into a device
        [F, yB, yB] image-space facet accumulator via the adjoint
        sampled-DFT einsum (see `_bwd_sampled_fold_fn`) — device state
        equals the OUTPUT size, the strategy for 32k+ scale where the
        per-column row set (K*F*m*yB ~ 30 GB at 32k) fits neither HBM
        nor the d2h budget of a tunnel-attached chip.
    :param fold_group: ("sampled") columns folded per einsum dispatch —
        batches the adjoint contraction depth to fold_group*m rows.
    :param row_slab: ("sampled") optional (r0, r1) OUTPUT-ROW SLAB: the
        image-space accumulator covers only facet rows [r0, r1) — the
        adjoint fold's "ri" index restricts trivially, so a facet whose
        whole accumulator exceeds HBM (one 128k facet: 16.2 GiB) splits
        into row slabs, each an independent pass over the same subgrid
        stream (pair with the spill cache so the forward runs once).
        `finish()` then emits [F, r1 - r0, yB] slabs; slabs concatenated
        along axis 1 equal the whole-facet backward (pinned by tests).
    """

    def __init__(self, swiftly_config, facet_configs, col_block=512,
                 residency="host", fold_group=4, row_slab=None):
        self._base = _StreamedBase(
            swiftly_config, facet_configs, col_block, residency
        )
        self.core = self._base.core
        self.stack = self._base.stack
        self._naf = {}  # off0 -> host/device [F, m, yB_pad(,2)] rows
        self._acc = None  # ("sampled") device [F, yB, yB(,2)] accumulator
        self._fold_group = max(1, int(fold_group))
        self._fold_mode = resolve_fold_mode()  # sampled | ct | fft
        self._row_slab = None
        if row_slab is not None:
            r0, r1 = int(row_slab[0]), int(row_slab[1])
            yB = self._base.stack.size
            if residency != "sampled":
                raise ValueError("row_slab requires residency='sampled'")
            if self._fold_mode != "sampled":
                raise ValueError(
                    "row_slab requires the sampled fold body "
                    f"(SWIFTLY_FOLD=sampled|auto, got {self._fold_mode!r})"
                )
            if not (0 <= r0 < r1 <= yB):
                raise ValueError(
                    f"row_slab {(r0, r1)} outside the facet rows [0, {yB})"
                )
            self._row_slab = (r0, r1)
        self._pending_rows = []  # ("sampled") [(off0, rows [F, m, yB(,2)])]
        # ("sampled") depth-2 fold-completion pipeline: dispatch is
        # asynchronous and block_until_ready is not completion on tunnel
        # runtimes, so a checksum of each fold's output is pulled before
        # dispatching the fold after next — bounding live fold transients
        # and row buffers to two folds' worth (mirrors the forward's
        # _device_columns/_grouped_device_columns pattern).
        import collections

        self._fold_inflight = collections.deque()
        # ("sampled") column-pass completion pipeline: bounds live
        # NAF_BMNAF row buffers ([F, m, yB, 2], ~208 MB each at 32k) to
        # ~2 + fold_group — without it a caller feeding a whole column
        # group back-to-back keeps every column's rows live at once
        # (the BENCH_r04 32k roundtrip OOM ledger gap).
        self._rows_inflight = collections.deque()
        self._finished = False
        # (off0, off1) of every folded subgrid — the resume ledger the
        # autosave snapshots and `restore_streamed_backward_state`
        # repopulates, so a resumed feed loop knows what to skip
        self.processed = []
        self._autosave = None

    def enable_autosave(self, path, every_subgrids=0, every_s=0.0):
        """Periodic checkpointing driven by the feed itself: snapshot to
        `path` (atomic, checksummed, keep-N rotated — `utils.checkpoint`)
        every `every_subgrids` folded subgrids and/or every `every_s`
        seconds of wall clock, whichever fires first. The snapshot
        carries this session's ``processed`` ledger, so a killed run
        resumes via `restore_streamed_backward_state` + skipping the
        processed keys. Zero overhead beyond a counter until a save is
        due. Pass neither to disable."""
        every_subgrids = int(every_subgrids)
        every_s = float(every_s)
        if every_subgrids <= 0 and every_s <= 0:
            self._autosave = None
            return
        self._autosave = {
            "path": str(path),
            "every_n": every_subgrids,
            "every_s": every_s,
            "since": 0,
            "last_t": time.monotonic(),
        }

    def _autosave_tick(self, n_folded):
        a = self._autosave
        if a is None:
            return
        a["since"] += n_folded
        now = time.monotonic()
        due = (a["every_n"] > 0 and a["since"] >= a["every_n"]) or (
            a["every_s"] > 0 and now - a["last_t"] >= a["every_s"]
        )
        if not due:
            return
        from ..utils.checkpoint import save_streamed_backward_state

        save_streamed_backward_state(a["path"], self, self.processed)
        a["since"] = 0
        a["last_t"] = time.monotonic()
        _metrics.count("ckpt.autosaves")
        _trace.instant("ckpt.autosave_tick", cat="ckpt",
                       processed=len(self.processed))

    def _bwd_cp_flops(self, n_subgrids, subgrid_size):
        """Analytic FLOPs of one backward column pass over `n_subgrids`
        (stage attribution; 0 when metrics are disabled)."""
        if not _metrics.enabled():
            return 0
        from ..utils.flops import bwd_column_pass_flops

        base = self._base
        colpass = _resolve_colpass_bwd(
            self.core, base.stack.n_total // _mesh_size(base.mesh)
        )
        return bwd_column_pass_flops(
            self.core, base.stack.n_real, n_subgrids, base.stack.size,
            subgrid_size, colpass,
        )

    def add_subgrids(self, tasks):
        """Fold (SubgridConfig, subgrid_data) pairs into the accumulators."""
        if self._finished:
            raise RuntimeError("finish() was already called")
        groups = {}
        for sg, data in tasks:
            groups.setdefault(sg.off0, []).append((sg, data))
        for group in groups.values():
            self.add_subgrid_stack([sg for sg, _ in group],
                                   [d for _, d in group])

    def add_subgrid_stack(self, sg_configs, subgrids):
        """Fold one column's subgrids, given as a stack.

        :param sg_configs: the column's SubgridConfigs (one shared off0)
        :param subgrids: matching [S, xA, xA(,2)] — a DEVICE array (e.g.
            straight from `StreamedForward.stream_columns(...,
            device_arrays=True)`, no host round trip), or any host
            array/list of per-subgrid arrays.
        """
        import jax.numpy as jnp

        if self._finished:
            raise RuntimeError("finish() was already called")
        _fault_point("bwd.feed")
        base = self._base
        core = base.core
        off0s = {sg.off0 for sg in sg_configs}
        if len(off0s) != 1:
            raise ValueError(
                f"add_subgrid_stack takes ONE column, got offsets {off0s}"
            )
        off0 = off0s.pop()
        yB = base.stack.size
        h2d_bytes = 0
        if hasattr(subgrids, "sharding"):  # already a placed jax array
            subgrids = jnp.asarray(subgrids)
        else:
            subgrids = jnp.stack(
                [jnp.asarray(_to_host_layout(core, d)) for d in subgrids]
            )
            h2d_bytes = int(subgrids.nbytes)
        sg_offs = jnp.asarray([(sg.off0, sg.off1) for sg in sg_configs])
        if base.mesh is not None:
            colfn = _column_pass_bwd_sharded(core, base.mesh, yB)
        else:
            colfn = _column_pass_bwd_j(core, yB)
        if base.residency == "sampled":
            # genuine completion pull of the column before last (8-byte
            # host round trip) before dispatching another column pass
            while len(self._rows_inflight) >= 2:
                with _metrics.stage("bwd.drain"):
                    np.asarray(self._rows_inflight.popleft())
        cp_bytes = h2d_bytes
        if _metrics.enabled():
            from ..utils.profiling import column_collective_bytes

            cp_bytes += column_collective_bytes(
                core, _mesh_size(base.mesh), len(sg_configs), "backward",
                subgrid_size=sg_configs[0].size,
            )
            _metrics.count("bwd.subgrids_folded", len(sg_configs))
        with _metrics.stage(
            "bwd.column_pass",
            flops=self._bwd_cp_flops(len(sg_configs), sg_configs[0].size),
            bytes_moved=cp_bytes,
        ):
            rows = colfn(
                subgrids,
                sg_offs,
                base._foffs0,
                base._foffs1,
                base._masks1_dev,
            )  # [F, m, yB] (facet-sharded on a mesh)
        key = int(off0)
        if base.residency == "sampled":
            self._rows_inflight.append(jnp.sum(rows[:, 0]))
            self._pending_rows.append((key, rows))
            if len(self._pending_rows) >= self._fold_group:
                self._flush_folds()
            self.processed.extend(
                (sg.off0, sg.off1) for sg in sg_configs
            )
            self._autosave_tick(len(sg_configs))
            return
        pad = base._yB_pad - yB
        if pad:
            widths = [(0, 0), (0, 0), (0, pad)] + [
                (0, 0) for _ in _tail(core)
            ]
            rows = jnp.pad(rows, widths)
        if base.residency == "device":
            prev = self._naf.get(key)
            self._naf[key] = rows if prev is None else prev + rows
        else:
            if key in self._naf:
                self._naf[key] += np.asarray(rows)
            else:
                self._naf[key] = np.array(rows)  # writable copy
        self.processed.extend((sg.off0, sg.off1) for sg in sg_configs)
        self._autosave_tick(len(sg_configs))

    def _ensure_acc(self):
        import jax.numpy as jnp

        base = self._base
        if self._acc is None:
            r0, r1 = self._row_slab or (0, base.stack.size)
            shape = (
                base.stack.n_total, r1 - r0, base.stack.size
            ) + _tail(base.core)
            if base.mesh is not None:
                self._acc = base._place(
                    np.zeros(shape, dtype=_np_dtype(base.core))
                )
            else:
                self._acc = jnp.zeros(shape, dtype=_np_dtype(base.core))

    def _drain_folds(self, depth=1):
        """Pull fold checksums down to `depth` in flight (genuine 8-byte
        host round trips — see _fold_inflight comment in __init__)."""
        while len(self._fold_inflight) > depth:
            with _metrics.stage("bwd.drain"):
                np.asarray(self._fold_inflight.popleft())

    def _fold_rows(self, offs, rows_cat):
        """("sampled") one adjoint fold of concatenated column rows
        [F, P*m, yB(,2)] into the image-space accumulator — the direct
        adjoint-sampled einsum by default (measured fastest on the
        tunnel runtime; docs/performance.md), the CT-factored body with
        SWIFTLY_FOLD=ct."""
        import jax.numpy as jnp

        base = self._base
        core = base.core
        yB = base.stack.size
        self._ensure_acc()
        e0 = getattr(self, "_e0_dev", None)
        if e0 is None:
            e0 = self._e0_dev = base._place(
                (np.asarray(base.stack.offs0) - yB // 2).astype(np.int32)
            )
        krows = jnp.asarray(sampled_row_indices(core, offs))
        self._drain_folds()
        if self._fold_mode == "ct":
            Q, P, kmax, r_idx, a_vals = _ct_fold_tables(core, offs)
            F = base.stack.n_total // _mesh_size(base.mesh)
            itemsize = np.dtype(_np_dtype(core)).itemsize
            planes = 2 * F * core.yN_size * yB * (
                itemsize if _planar(core) else itemsize // 2
            )
            W = _ct_fold_width(yB, planes)
            if base.mesh is not None:
                foldfn = _bwd_ct_fold_sharded(
                    core, base.mesh, Q, P, kmax, W
                )
            else:
                foldfn = _bwd_ct_fold_j(core, Q, P, kmax, W)
            ri, av = jnp.asarray(r_idx), jnp.asarray(a_vals)
            with _metrics.stage("bwd.ct_fold"):
                for j0 in range(0, yB, W):
                    self._acc = foldfn(
                        self._acc, rows_cat, e0, krows, ri, av,
                        jnp.int32(j0),
                    )
        else:
            if base.mesh is not None:
                foldfn = _bwd_sampled_fold_sharded(core, base.mesh)
            else:
                from ..ops.pallas_kernels import pallas_interpret

                kernel = resolve_fold_kernel(core)
                foldfn = _bwd_sampled_fold_j(
                    core, kernel == "pallas", pallas_interpret()
                )
                if kernel == "pallas" and _metrics.enabled():
                    _metrics.count("bwd.pallas_folds")
            fold_flops = 0
            if _metrics.enabled():
                from ..utils.flops import bwd_fold_flops

                fold_flops = bwd_fold_flops(
                    core, base.stack.n_real, yB, int(rows_cat.shape[1])
                )
                if self._row_slab is not None:
                    # fold FLOPs scale with the output rows computed
                    r0, r1 = self._row_slab
                    fold_flops = int(fold_flops * (r1 - r0) / yB)
            row0 = jnp.int32((self._row_slab or (0, 0))[0])
            with _metrics.stage("bwd.sampled_fold", flops=fold_flops):
                self._acc = foldfn(self._acc, rows_cat, e0, krows, row0)
        # the checksum slice depends on the whole fold having executed
        self._fold_inflight.append(jnp.sum(self._acc[:, 0]))

    def _fold_rows_fft(self, offs, rows_g):
        """("sampled", fft fold) one FFT-based adjoint fold of a column
        group's rows [g, F, m, yB(,2)] into the image accumulator —
        dispatched as one donation-chained program per j-chunk."""
        import jax.numpy as jnp

        base = self._base
        core = base.core
        yB = base.stack.size
        self._ensure_acc()
        offs_dev = jnp.asarray(np.asarray(offs, dtype=np.int32))
        F = base.stack.n_total // _mesh_size(base.mesh)
        Cj = min(_fft_fold_chunk(core, F, yB), yB)
        if base.mesh is not None:
            foldfn = _bwd_fft_fold_chunk_sharded(core, base.mesh, Cj)
        else:
            foldfn = _bwd_fft_fold_chunk_j(core, Cj)
        self._drain_folds()
        with _metrics.stage("bwd.fft_fold"):
            for ci in range(-(-yB // Cj)):
                j0 = ci * Cj
                start = min(j0, yB - Cj)
                self._acc = foldfn(
                    self._acc, rows_g, offs_dev, base._foffs0,
                    jnp.int32(j0), jnp.int32(start),
                )
        self._fold_inflight.append(jnp.sum(self._acc[:, 0]))

    def _flush_folds(self):
        """("sampled") fold the pending columns' rows into the image-space
        accumulator: one fold over the pending group, via the body
        `resolve_fold_mode` selected (sampled einsum by default)."""
        import jax.numpy as jnp

        if not self._pending_rows:
            return
        offs = [o for o, _ in self._pending_rows]
        if self._fold_mode == "fft":
            rows_g = jnp.stack([r for _, r in self._pending_rows])
            self._fold_rows_fft(offs, rows_g)
        else:
            rows_cat = (
                self._pending_rows[0][1]
                if len(self._pending_rows) == 1
                else jnp.concatenate(
                    [r for _, r in self._pending_rows], axis=1
                )
            )  # [F, P*m, yB(,2)]
            self._fold_rows(offs, rows_cat)
        self._pending_rows = []

    def add_subgrid_group(self, col_sg_lists, subgrids_group):
        """("sampled") fold a whole forward column GROUP in TWO
        dispatches: one vmapped column pass over the group's stacked
        subgrids and one adjoint fold over the G*m concatenated rows —
        feeding the same group per column pays the tunnel's per-dispatch
        latency 2G+ times (the dominant backward-leg cost, measured).

        :param col_sg_lists: per-column lists of SubgridConfigs (one
            shared off0 each). Columns may hold FEWER configs than the
            group array's S rows — the trailing rows are the forward's
            zero-mask padding, which is exactly zero and folds to zero
            whatever offsets are assumed for it.
        :param subgrids_group: device [G, S, xA, xA(,2)], e.g. one yield
            of `StreamedForward.stream_column_groups`.
        """
        import jax.numpy as jnp

        if self._finished:
            raise RuntimeError("finish() was already called")
        if self._base.residency != "sampled":
            raise ValueError(
                "add_subgrid_group requires residency='sampled'"
            )
        _fault_point("bwd.feed")
        base = self._base
        if base.mesh is not None:
            # per-column sharded path (the group-batched column pass is
            # single-device; on a mesh the latency it amortises is not
            # the bottleneck anyway) — but fold batching and the
            # autosave tick still follow the GROUP contract: pending
            # folds flush at both group boundaries and the autosave
            # fires once per group, so a kill+resume refeeds whole
            # groups with fold batching identical before and after
            # (the same bit-identity contract as the single-device
            # group path below; per-column ticks would let a snapshot
            # land mid-group and straddle fold concatenations).
            self._flush_folds()
            autosave, self._autosave = self._autosave, None
            n_group = 0
            try:
                for gi, col in enumerate(col_sg_lists):
                    self.add_subgrid_stack(
                        col, subgrids_group[gi][: len(col)]
                    )
                    n_group += len(col)
            finally:
                self._autosave = autosave
            self._flush_folds()
            self._autosave_tick(n_group)
            return
        core = base.core
        yB = base.stack.size
        S = subgrids_group.shape[1]
        offs, sg_offs = [], []
        for col in col_sg_lists:
            off0s = {sg.off0 for sg in col}
            if len(off0s) != 1:
                raise ValueError(
                    f"each group entry must be ONE column, got {off0s}"
                )
            off0 = off0s.pop()
            offs.append(int(off0))
            pairs = [(sg.off0, sg.off1) for sg in col]
            pairs += [(off0, 0)] * (S - len(pairs))  # zero-pad rows
            sg_offs.append(pairs)
        # flush any pending per-column rows first so fold order follows
        # feed order (accumulation is exact either way — linearity)
        self._flush_folds()
        colfn = _column_pass_bwd_group_j(core, yB)
        sg_offs_np = np.asarray(sg_offs)
        # batch cap = fold_group: an uncapped group's [G, F, m, yB] rows
        # plus the fold's rotated copies would blow the headroom the
        # forward's sizers were given (rows are ~208 MB per 32k column;
        # bench.py's roundtrip headroom term (2*fold_group+2)*row_bytes
        # covers this capped batch's live set, validated green at 32k)
        cap = max(1, int(self._fold_group))
        G = len(offs)
        for j in range(0, G, cap):
            # no separate rows checksum here: each chunk's fold consumes
            # its rows immediately, so the fold pipeline's depth-2 pull
            # (_fold_rows) transitively bounds live rows to two chunks'
            # worth — a separate rows pull would add one ~0.1 s tunnel
            # round trip per chunk for backpressure the fold already
            # provides (37 chunks = ~4 s of the 32k backward leg)
            g = len(offs[j : j + cap])
            if _metrics.enabled():
                _metrics.count("bwd.subgrids_folded", g * S)
            with _metrics.stage(
                "bwd.column_pass",
                flops=g * self._bwd_cp_flops(S, int(subgrids_group.shape[2])),
            ):
                rows = colfn(
                    jnp.asarray(subgrids_group[j : j + cap]),
                    jnp.asarray(sg_offs_np[j : j + cap]),
                    base._foffs0,
                    base._foffs1,
                    base._masks1_dev,
                )  # [g, F, m, yB(,2)]
            if self._fold_mode == "fft":
                # the FFT fold takes per-column rows directly; its cost
                # is flat in g, so the whole chunk folds in one dispatch
                self._fold_rows_fft(offs[j : j + cap], rows)
                continue
            rows_cat = jnp.moveaxis(rows, 0, 1).reshape(
                (rows.shape[1], rows.shape[0] * rows.shape[2])
                + rows.shape[3:]
            )  # [F, g*m, yB(,2)]
            self._fold_rows(offs[j : j + cap], rows_cat)
        # the whole group folded: ledger + autosave AT GROUP BOUNDARIES
        # only — the processed set then always covers whole groups, so a
        # resumed feed loop skips group-by-group and fold batching (per
        # cap chunk within each group) is identical before and after a
        # kill (the chaos drill's bit-identity rests on this)
        n_group = 0
        for col in col_sg_lists:
            self.processed.extend((sg.off0, sg.off1) for sg in col)
            n_group += len(col)
        self._autosave_tick(n_group)

    def finish_device(self):
        """("sampled") the finished facet stack [F_total, yB, yB(,2)] as a
        DEVICE array — callers at 32k+ scale verify/consume it on device
        (a full host pull is d2h-bound on tunnel-attached chips)."""
        if self._base.residency != "sampled":
            raise ValueError("finish_device() requires residency='sampled'")
        if self._finished:
            raise RuntimeError("finish() was already called")
        self._flush_folds()
        if self._acc is None:
            raise RuntimeError("No subgrids were added")
        fn = _sampled_finish_j(self.core)
        masks0 = self._base._masks0_dev
        if self._row_slab is not None:
            # the finish mask is over the output-row axis: slice it to
            # the slab (the j axis and everything else stay full-width)
            r0, r1 = self._row_slab
            masks0 = masks0[:, r0:r1]
        acc, self._acc = self._acc, None  # donated to the finish program
        with _metrics.stage("bwd.finish"):
            out = fn(acc, masks0)
        self._finished = True
        return out

    def finish(self):
        """Emit the finished facet stack [F, yB, yB(,2)] (host array)."""
        import jax.numpy as jnp

        if self._base.residency == "sampled":
            return np.asarray(self.finish_device())[: self.stack.n_real]
        if self._finished:
            raise RuntimeError("finish() was already called")
        base = self._base
        core = base.core
        stack = base.stack
        yB = stack.size
        Cb = base.col_block
        col_offs0 = sorted(self._naf)
        if not col_offs0:
            raise RuntimeError("No subgrids were added")
        if base.mesh is not None:
            finfn = _facet_pass_bwd_sharded(core, base.mesh, yB)
        else:
            finfn = _facet_pass_bwd_j(core, yB)
        col_offs0_j = jnp.asarray(col_offs0)
        masks0 = base._masks0_dev
        facets = np.zeros(
            (len(stack), yB, yB) + _tail(core), dtype=_np_dtype(core)
        )
        pending = []
        for j0 in range(0, base._yB_pad, Cb):
            if base.residency == "device":
                blocks = jnp.stack(
                    [
                        jax.lax.dynamic_slice_in_dim(
                            self._naf[o], j0, Cb, axis=2
                        )
                        for o in col_offs0
                    ]
                )
            else:
                blocks = base._place(
                    np.stack(
                        [self._naf[o][:, :, j0 : j0 + Cb] for o in col_offs0]
                    ),
                    facet_axis=1,
                )
            with _metrics.stage("bwd.facet_pass"):
                out = finfn(blocks, col_offs0_j, base._foffs0, masks0)
            pending.append((j0, out))
            if len(pending) > 1:
                pj, pout = pending.pop(0)
                j1 = min(pj + Cb, yB)
                with _metrics.stage("bwd.d2h") as st:
                    host = np.asarray(pout)
                    st.bytes_moved = int(host.nbytes)
                facets[:, :, pj:j1] = host[:, :, : j1 - pj]
        for pj, pout in pending:
            j1 = min(pj + Cb, yB)
            if j1 > pj:
                with _metrics.stage("bwd.d2h") as st:
                    host = np.asarray(pout)
                    st.bytes_moved = int(host.nbytes)
                facets[:, :, pj:j1] = host[:, :, : j1 - pj]
        self._finished = True
        return facets[: stack.n_real]
