"""Device-mesh construction and multi-host initialisation.

The execution fabric of the framework: where the reference distributes
tasks over a Dask scheduler/worker cluster (api.py:133-147), the TPU build
lays facets out over a `jax.sharding.Mesh` axis and lets XLA insert the
collectives (psum over ICI within a slice, DCN across slices).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

FACET_AXIS = "facet"

COLLECTIVES = ("psum", "ring")

__all__ = [
    "COLLECTIVES",
    "FACET_AXIS",
    "bootstrap_from_env",
    "facet_sharding",
    "mesh_size",
    "initialize_multihost",
    "place_facet_sharded",
    "make_facet_mesh",
    "pad_to_shards",
    "replicated_sharding",
    "resolve_collective",
]


def resolve_collective(n_shards: int | None = None) -> str:
    """The facet-axis reduction schedule a sharded column pass runs.

    ``SWIFTLY_MESH_COLLECTIVE`` ∈ {psum, ring, auto} (default auto):

    - ``psum`` — one blocking ``lax.psum`` per column group; XLA lowers
      it to its own all-reduce.  Deterministic tree order, the exactness
      reference.
    - ``ring`` — reduce-scatter + all-gather built from 2(n−1)
      ``lax.ppermute`` chunk rotations, so each step moves 1/n of the
      buffer and the schedule interleaves with neighbouring compute
      instead of serializing after it.  Same result up to reduction
      order (documented tolerance in docs/multichip.md).
    - ``auto`` — psum.  The conservative default: the ring only wins
      when its measured ``mesh.ring_step`` rate says so, and that
      decision lives in the plan compiler (calibrated-coefficient gated,
      like the colpass candidates); the engine follows the plan by
      exporting the choice through this env knob, not by guessing here.

    Read at CALL time (not trace time) so one process can bench psum and
    ring back to back; the sharded kernel caches key on the resolved
    value.  A one-shard "mesh" always degrades to psum — there is no
    ring of one.
    """
    mode = os.environ.get("SWIFTLY_MESH_COLLECTIVE", "auto")
    if mode not in ("psum", "ring", "auto"):
        raise ValueError(
            f"SWIFTLY_MESH_COLLECTIVE must be psum|ring|auto, got {mode!r}"
        )
    if n_shards is not None and n_shards <= 1:
        return "psum"
    if mode == "auto":
        return "psum"
    return mode


def make_facet_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1D mesh over the facet stack axis.

    :param n_devices: number of devices to use (default: all available)
    :param devices: explicit device list (overrides n_devices)
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"Requested a {n_devices}-device mesh but only "
                    f"{len(devices)} devices are available"
                )
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (FACET_AXIS,))


def facet_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading (facet-stack) axis over the mesh."""
    return NamedSharding(mesh, PartitionSpec(FACET_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding on the mesh."""
    return NamedSharding(mesh, PartitionSpec())


def mesh_size(mesh) -> int:
    """Device count of a (possibly absent) mesh."""
    return 1 if mesh is None else mesh.devices.size


def varying(x, axis_name: str):
    """Tag `x` as varying over a shard_map axis.

    shard_map tracks which values vary per shard; a `jnp.zeros` scan
    carry created inside the mapped body starts out unvarying and fails
    the carry-type check once the scan body mixes in shard-varying data.

    The tagging primitive moved across jax releases (`pcast` since 0.6,
    `pvary` in some 0.5.x); on older jax (0.4.x) shard_map has no
    varying-type tracking at all, so the identity is exactly right.
    """
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, (axis_name,), to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, (axis_name,))
    return x


def pad_to_shards(n: int, n_shards: int) -> int:
    """Facet count padded up to a multiple of the mesh size.

    Zero-padded facets contribute zeros to every linear accumulation, so
    padding is exact (not approximate)."""
    return ((n + n_shards - 1) // n_shards) * n_shards


def place_facet_sharded(arr, mesh: Mesh, facet_axis: int = 0):
    """Place the GLOBAL array `arr` facet-sharded over the mesh,
    multihost-safely.

    Single-process: a plain `device_put` with the facet sharding. On a
    multi-host pod slice (jax.process_count() > 1) a global device_put
    would address devices this process cannot reach; instead each
    process materialises only its addressable shards of the global host
    array (`jax.make_array_from_callback` slices them out), so no
    cross-host transfer of the stack ever happens.
    """
    arr = np.asarray(arr)
    spec = [None] * arr.ndim
    spec[facet_axis] = FACET_AXIS
    sharding = NamedSharding(mesh, PartitionSpec(*spec))
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def initialize_multihost(coordinator=None, num_processes=None, process_id=None):
    """Initialise JAX distributed runtime for multi-host (pod-slice) runs.

    On TPU pods with standard orchestration all arguments are discovered
    automatically; arguments are for manual (e.g. GPU/CPU cluster) setups.
    Safe to call once per process before any device use.
    """
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def bootstrap_from_env():
    """Env-driven `jax.distributed` bootstrap — the process-spanning
    mesh's entry point (docs/multichip.md "Multi-process bootstrap").

    Reads ``SWIFTLY_COORDINATOR`` (host:port of process 0's
    coordinator), ``SWIFTLY_NUM_PROCESSES`` and ``SWIFTLY_PROCESS_ID``
    and calls `initialize_multihost` with whatever is set. With NONE of
    them set this is a no-op returning ``None`` — single-process runs
    (and TPU pods whose orchestrator auto-discovers all three) need no
    environment at all. Returns the resolved
    ``{coordinator, num_processes, process_id}`` dict when a bootstrap
    happened, so callers can log what they joined.

    Must run before any device use in the process;
    ``__graft_entry__.dryrun_distributed`` drives a real 2-process
    CPU bootstrap through exactly this path.
    """
    coordinator = os.environ.get("SWIFTLY_COORDINATOR") or None
    num_processes = os.environ.get("SWIFTLY_NUM_PROCESSES") or None
    process_id = os.environ.get("SWIFTLY_PROCESS_ID") or None
    if coordinator is None and num_processes is None and process_id is None:
        return None
    if num_processes is not None:
        num_processes = int(num_processes)
    if process_id is not None:
        process_id = int(process_id)
    initialize_multihost(
        coordinator=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return {
        "coordinator": coordinator,
        "num_processes": num_processes,
        "process_id": process_id,
    }
