"""Parallel execution: batched kernels, device meshes, sharded pipelines,
out-of-core streamed executors."""

from . import batched, sharded, streamed
from .streamed import StreamedBackward, StreamedForward

__all__ = [
    "StreamedBackward",
    "StreamedForward",
    "batched",
    "sharded",
    "streamed",
]
