"""Parallel execution: batched kernels, device meshes, sharded pipelines,
out-of-core streamed executors."""

from . import batched, sharded, streamed
from .streamed import CachedColumnFeed, StreamedBackward, StreamedForward

__all__ = [
    "CachedColumnFeed",
    "StreamedBackward",
    "StreamedForward",
    "batched",
    "sharded",
    "streamed",
]
