"""Parallel execution: batched kernels, device meshes, sharded pipelines,
out-of-core streamed executors.

This package namespace is the supported import surface for the mesh and
sharded API — ``from swiftly_tpu.parallel import make_facet_mesh,
FACET_AXIS`` (or the executor-level `swiftly_tpu.mesh` engine). Deep
module imports (``swiftly_tpu.parallel.mesh.make_facet_mesh``) still
work but are deprecated as an import style: every public name is
re-exported here so call sites stop depending on the internal module
split.
"""

from . import batched, mesh, sharded, streamed
from .mesh import (
    FACET_AXIS,
    facet_sharding,
    initialize_multihost,
    make_facet_mesh,
    mesh_size,
    pad_to_shards,
    place_facet_sharded,
    replicated_sharding,
)
from .sharded import (
    backward_all_sharded,
    forward_all_sharded,
    split_accumulate_sharded,
    split_subgrid_sharded,
    subgrid_from_columns_sharded,
    subgrids_from_columns_sharded,
)
from .streamed import (
    CachedColumnFeed,
    StreamedBackward,
    StreamedForward,
    feed_backward_passes,
)

__all__ = [
    "CachedColumnFeed",
    "FACET_AXIS",
    "StreamedBackward",
    "StreamedForward",
    "feed_backward_passes",
    "backward_all_sharded",
    "batched",
    "facet_sharding",
    "forward_all_sharded",
    "initialize_multihost",
    "make_facet_mesh",
    "mesh",
    "mesh_size",
    "pad_to_shards",
    "place_facet_sharded",
    "replicated_sharding",
    "sharded",
    "split_accumulate_sharded",
    "split_subgrid_sharded",
    "streamed",
    "subgrid_from_columns_sharded",
    "subgrids_from_columns_sharded",
]
