"""Parallel execution: batched kernels, device meshes, sharded pipelines."""

from . import batched

__all__ = ["batched"]
