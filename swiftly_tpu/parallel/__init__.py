"""Parallel execution: batched kernels, device meshes, sharded pipelines."""

from . import batched, sharded

__all__ = ["batched", "sharded"]
