"""Per-stage metrics registry: counters, gauges, stage timers.

Design constraints, in order:

1. **Zero cost off.** The engine's hot loops call ``metrics.stage(...)``
   per dispatch; disabled (the default) that is one attribute check and
   the return of a shared no-op context manager — no allocation, no
   clock read, no string work. A disabled run is indistinguishable from
   an uninstrumented one (< 1 us per site against multi-ms dispatches).
2. **One stage vocabulary.** Enabled, each stage timer also enters a
   ``jax.profiler.TraceAnnotation`` of the same name, so the host-side
   walls in ``export()`` and the device timeline in a Perfetto trace
   (``utils.profiling.trace``) index by identical stage names.
3. **Honest attribution.** JAX dispatch is asynchronous: a host timer
   around a dispatch measures dispatch + backpressure, not device
   compute. The engine therefore instruments its *completion pulls* as
   their own ``*.drain`` stages; per-stage MFU (analytic FLOPs from
   ``utils.flops`` divided by host wall) is exact on synchronous
   backends (CPU tests) and a dispatch-side attribution on async
   runtimes — the run-level ``total`` block is always meaningful, and
   the trace holds the per-op device truth. docs/observability.md
   spells this out.

Stage timing keeps streaming aggregates (count/total/min/max) plus a
bounded sample ring for p99 (capacity 8192; beyond that, samples
overwrite round-robin — quantiles stay representative for the uniform
dispatch streams this engine emits). All mutation is lock-guarded:
``MemorySampler`` and heartbeat threads may record concurrently.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import recorder as _recorder
from . import trace as _trace

__all__ = [
    "MetricsRegistry",
    "enabled",
    "enable",
    "disable",
    "get_registry",
    "stage",
    "observe",
    "count",
    "gauge",
    "gauge_max",
    "event",
    "export",
    "reset",
]

_P99_RING = 8192  # per-stage sample capacity (see module docstring)


class _NullStage:
    """The shared disabled-path context manager (no state, no work).

    Attribute writes are swallowed so call sites may set
    ``st.flops``/``st.bytes_moved`` inside the block (for values only
    known after the work) without branching on enablement."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __setattr__(self, name, value):
        pass


_NULL_STAGE = _NullStage()


class _StageStats:
    __slots__ = (
        "count", "total_s", "min_s", "max_s", "flops", "bytes_moved",
        "samples", "_ring_i",
    )

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.min_s = float("inf")
        self.max_s = 0.0
        self.flops = 0
        self.bytes_moved = 0
        self.samples = []
        self._ring_i = 0

    def add(self, wall_s, flops, bytes_moved):
        self.count += 1
        self.total_s += wall_s
        if wall_s < self.min_s:
            self.min_s = wall_s
        if wall_s > self.max_s:
            self.max_s = wall_s
        self.flops += flops
        self.bytes_moved += bytes_moved
        if len(self.samples) < _P99_RING:
            self.samples.append(wall_s)
        else:
            self.samples[self._ring_i] = wall_s
            self._ring_i = (self._ring_i + 1) % _P99_RING


def _quantile_sorted(s, q):
    if not s:
        return 0.0
    return s[min(len(s) - 1, int(q * len(s)))]


def _p99(samples):
    return _quantile_sorted(sorted(samples), 0.99)


class _Stage:
    """One enabled stage timing: host wall + TraceAnnotation pairing.

    Also the metrics→trace bridge: when the span tracer (``obs.trace``)
    is on, each stage opens a trace span of the SAME name, so every
    instrumentation site in the engine feeds both systems with one
    ``with`` block and the Perfetto timeline uses the documented stage
    vocabulary. A stage may run with the registry disabled (tracing
    only) — it then records no registry state."""

    __slots__ = ("_reg", "name", "flops", "bytes_moved", "_t0", "_ann",
                 "_tspan")

    def __init__(self, reg, name, flops, bytes_moved):
        self._reg = reg
        self.name = name
        self.flops = flops
        self.bytes_moved = bytes_moved
        self._ann = None
        self._tspan = None

    def __enter__(self):
        reg = self._reg
        if reg._annotation_cls is not None:
            self._ann = reg._annotation_cls(self.name)
            self._ann.__enter__()
        if _trace._TRACER.enabled:
            self._tspan = _trace.span(self.name, cat="stage")
            self._tspan.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        wall = time.perf_counter() - self._t0
        if self._tspan is not None:
            if self.flops:
                self._tspan.set(flops=self.flops)
            if self.bytes_moved:
                self._tspan.set(bytes_moved=self.bytes_moved)
            self._tspan.__exit__(*exc)
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if self._reg.enabled:
            self._reg._record_stage(self.name, wall, self.flops,
                                    self.bytes_moved)
        rec = _recorder._RECORDER
        if rec.enabled:
            rec.record("stage", self.name, round(wall, 6))
        return False


class MetricsRegistry:
    """Counters, gauges and stage timers; a no-op unless enabled.

    One process-wide instance (``get_registry()``) serves the engine;
    independent instances are constructible for tests.
    """

    def __init__(self, enabled=False, jsonl_path=None):
        self._lock = threading.Lock()
        self._annotation_cls = None
        self._jsonl = None
        self._jsonl_path = None
        self._t_epoch = time.time()
        self._t0 = time.perf_counter()
        self.counters = {}
        self.gauges = {}
        self.gauges_max = {}
        self.stages = {}
        self.enabled = False
        if enabled:
            self.enable(jsonl_path)

    # -- lifecycle ---------------------------------------------------------

    def enable(self, jsonl_path=None):
        """Turn recording on; optionally start a JSONL event log.

        The TraceAnnotation class is resolved here (not per stage) so
        enabled-path overhead stays one attribute read; environments
        without ``jax.profiler`` degrade to host timers only.
        """
        with self._lock:
            self.enabled = True
            self._t_epoch = time.time()
            self._t0 = time.perf_counter()
            if self._annotation_cls is None:
                try:
                    from jax.profiler import TraceAnnotation

                    self._annotation_cls = TraceAnnotation
                except Exception:  # pragma: no cover - no jax.profiler
                    self._annotation_cls = None
            if jsonl_path:
                self._jsonl_path = str(jsonl_path)
                self._jsonl = open(self._jsonl_path, "a", buffering=1)
                self._emit({"kind": "open", "t_epoch": self._t_epoch})
        return self

    def disable(self):
        """Stop recording and close the event log (state is kept for
        export until ``reset()``)."""
        with self._lock:
            self.enabled = False
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None

    def reset(self):
        """Drop all recorded state (counters, gauges, stages)."""
        with self._lock:
            self.counters = {}
            self.gauges = {}
            self.gauges_max = {}
            self.stages = {}
            self._t0 = time.perf_counter()
            self._t_epoch = time.time()

    # -- recording ---------------------------------------------------------

    def stage(self, name, flops=0, bytes_moved=0):
        """Context manager timing one stage execution.

        ``flops``/``bytes_moved`` are the dispatch's analytic compute
        and data-movement attribution (accumulated into the stage).
        Disabled this returns a shared no-op object immediately —
        unless the span tracer is on (the stage runs as a trace-only
        span, no registry state) or the flight recorder is on (a
        recorder-only timer appends one ring event).
        """
        if not self.enabled and not _trace._TRACER.enabled:
            if _recorder._RECORDER.enabled:
                return _recorder._RecorderStage(name)
            return _NULL_STAGE
        return _Stage(self, name, flops, bytes_moved)

    def observe(self, name, wall_s, flops=0, bytes_moved=0):
        """Record an externally measured duration into a stage histogram.

        For durations the registry cannot bracket with ``stage(...)`` —
        e.g. a serving request's submit→completion latency, whose span
        crosses queueing, scheduling and dispatch. Lands in the same
        export/quantile machinery as timed stages.
        """
        if not self.enabled:
            return
        self._record_stage(name, wall_s, flops, bytes_moved)

    def count(self, name, n=1):
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name, value):
        if not self.enabled:
            return
        with self._lock:
            self.gauges[name] = value

    def gauge_max(self, name, value):
        """Peak-tracking gauge: keeps the MAX ever recorded, so
        watermark-style gauges (HBM peak, queue-depth high-water)
        survive ``export()`` on long runs instead of reporting
        whatever the last sample happened to be."""
        if not self.enabled:
            return
        with self._lock:
            cur = self.gauges_max.get(name)
            if cur is None or value > cur:
                self.gauges_max[name] = value

    def event(self, kind, **fields):
        """Append a free-form event to the JSONL log (no-op otherwise)."""
        if not self.enabled:
            return
        with self._lock:
            self._emit({"kind": kind, **fields})

    def _record_stage(self, name, wall_s, flops, bytes_moved):
        with self._lock:
            st = self.stages.get(name)
            if st is None:
                st = self.stages[name] = _StageStats()
            st.add(wall_s, flops, bytes_moved)
            self._emit(
                {
                    "kind": "stage",
                    "name": name,
                    "t_s": round(time.perf_counter() - self._t0, 6),
                    "wall_s": round(wall_s, 6),
                    "flops": flops,
                    "bytes": bytes_moved,
                }
            )

    def _emit(self, record):  # caller holds the lock
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(record) + "\n")

    # -- export ------------------------------------------------------------

    def export(self):
        """All recorded telemetry as one JSON-ready dict.

        Per stage: count, wall aggregates (total/min/mean/max/p99) and,
        where the instrumentation attributed analytic FLOPs, the derived
        ``tflops`` plus ``mfu_pct`` against the chip's peak
        (``utils.flops.peak_tflops``; absent when no peak is known —
        CPU, unknown device kinds without SWIFTLY_PEAK_TFLOPS).
        """
        peak = None
        with self._lock:
            if any(st.flops for st in self.stages.values()):
                try:
                    from ..utils.flops import peak_tflops

                    peak = peak_tflops()
                except Exception:  # pragma: no cover - no jax devices
                    peak = None
            stages = {}
            tot_wall = 0.0
            tot_flops = 0
            tot_bytes = 0
            for name in sorted(self.stages):
                st = self.stages[name]
                samples = sorted(st.samples)
                entry = {
                    "count": st.count,
                    "total_s": round(st.total_s, 6),
                    "min_s": round(st.min_s, 6),
                    "mean_s": round(st.total_s / st.count, 6),
                    "max_s": round(st.max_s, 6),
                    "p50_s": round(_quantile_sorted(samples, 0.50), 6),
                    "p99_s": round(_quantile_sorted(samples, 0.99), 6),
                }
                if st.flops:
                    entry["flops"] = st.flops
                    if st.total_s > 0:
                        tfl = st.flops / st.total_s / 1e12
                        entry["tflops"] = round(tfl, 4)
                        if peak:
                            entry["mfu_pct"] = round(100 * tfl / peak, 2)
                if st.bytes_moved:
                    entry["bytes"] = st.bytes_moved
                    if st.total_s > 0:
                        entry["gbps"] = round(
                            st.bytes_moved / st.total_s / 1e9, 3
                        )
                stages[name] = entry
                tot_wall += st.total_s
                tot_flops += st.flops
                tot_bytes += st.bytes_moved
            total = {
                "wall_s": round(tot_wall, 6),
                "flops": tot_flops,
                "bytes": tot_bytes,
            }
            if tot_flops and tot_wall > 0:
                tfl = tot_flops / tot_wall / 1e12
                total["tflops"] = round(tfl, 4)
                if peak:
                    total["mfu_pct"] = round(100 * tfl / peak, 2)
            if peak:
                total["peak_tflops"] = peak
            out = {
                "enabled": self.enabled,
                "t_epoch": self._t_epoch,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "gauges_max": dict(self.gauges_max),
                "stages": stages,
                "total": total,
            }
            if self._jsonl_path:
                out["jsonl_path"] = self._jsonl_path
            return out


# ---------------------------------------------------------------------------
# The process-wide registry + module-level conveniences (the engine's
# call-site API: `from ..obs import metrics` ... `metrics.stage(...)`).
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry(
    enabled=os.environ.get("SWIFTLY_METRICS", "0") not in ("", "0"),
    jsonl_path=os.environ.get("SWIFTLY_METRICS_JSONL") or None,
)


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY.enabled


def enable(jsonl_path=None):
    return _REGISTRY.enable(jsonl_path)


def disable():
    _REGISTRY.disable()


def reset():
    _REGISTRY.reset()


def stage(name, flops=0, bytes_moved=0):
    # keep the disabled path shallow: three attribute checks, shared no-op
    if not _REGISTRY.enabled and not _trace._TRACER.enabled:
        if _recorder._RECORDER.enabled:
            return _recorder._RecorderStage(name)
        return _NULL_STAGE
    return _Stage(_REGISTRY, name, flops, bytes_moved)


def observe(name, wall_s, flops=0, bytes_moved=0):
    _REGISTRY.observe(name, wall_s, flops, bytes_moved)


def count(name, n=1):
    _REGISTRY.count(name, n)


def gauge(name, value):
    _REGISTRY.gauge(name, value)


def gauge_max(name, value):
    _REGISTRY.gauge_max(name, value)


def event(kind, **fields):
    _REGISTRY.event(kind, **fields)


def export():
    return _REGISTRY.export()
