"""Progress heartbeat + incremental partial artifacts for long runs.

BENCH_r05 died at rc=124 with everything after the headline lost: the
driver killed the process mid-config and the remaining legs' records
existed only in memory. Two tools prevent a repeat:

* ``Heartbeat`` — rate/ETA reporting for hour-scale streaming loops
  (subgrids/s against a known total), throttled to one emission per
  ``interval_s``. Emissions go to the logger and, when the metrics
  registry is enabled, to the JSONL event log — so a trace of *how far
  a killed run got* survives on disk.
* ``PartialArtifactWriter`` — append-only JSONL flushing of finished
  records (one fsync'd line per leg): a killed multi-config bench still
  leaves every completed leg's full record on disk.
"""

from __future__ import annotations

import json
import logging
import os
import time

from . import metrics

logger = logging.getLogger(__name__)

__all__ = ["Heartbeat", "PartialArtifactWriter"]


class Heartbeat:
    """Throttled progress reporter for a loop over `total` units.

    ::

        hb = Heartbeat(total=len(subgrids), label="subgrids")
        for ... in stream:
            hb.update(len(items))
        hb.finish()
    """

    def __init__(self, total, label="units", interval_s=30.0,
                 log=None, tower=None, procfleet=None):
        self.total = int(total)
        self.label = label
        self.interval_s = float(interval_s)
        self.done = 0
        self._log = log or logger
        self._tower = tower
        self._procfleet = procfleet
        self._t0 = time.time()
        self._last_emit = 0.0  # first update() emits immediately

    def update(self, n=1, **fields):
        """Advance by `n` units; emit if the throttle interval passed.

        Extra ``fields`` ride along on the emission (e.g. the current
        column group index)."""
        self.done += int(n)
        now = time.time()
        if now - self._last_emit >= self.interval_s:
            self._emit(now, **fields)

    def finish(self, **fields):
        """Unconditional final emission (rate over the whole run)."""
        self._emit(time.time(), final=True, **fields)

    def _emit(self, now, final=False, **fields):
        self._last_emit = now
        elapsed = max(now - self._t0, 1e-9)
        rate = self.done / elapsed
        remaining = max(self.total - self.done, 0)
        eta_s = remaining / rate if rate > 0 else float("inf")
        if self._tower is not None:
            # fleet state rides along on every beat: replica count,
            # open alerts, queue depth, brownout rung — already-sampled
            # tower state, no source calls on this path
            fields = {**self._tower.heartbeat_fields(), **fields}
        if self._procfleet is not None:
            # process-fleet state: live workers, summed generations,
            # open alert count (`ProcessFleet.heartbeat_fields`)
            fields = {**self._procfleet.heartbeat_fields(), **fields}
        self._log.info(
            "%s %d/%d (%.2f/s, elapsed %.0fs%s)",
            self.label, self.done, self.total, rate, elapsed,
            "" if final or eta_s == float("inf")
            else f", ETA {eta_s:.0f}s",
        )
        metrics.event(
            "heartbeat",
            label=self.label,
            done=self.done,
            total=self.total,
            rate_per_s=round(rate, 4),
            elapsed_s=round(elapsed, 2),
            eta_s=None if eta_s == float("inf") else round(eta_s, 1),
            **fields,
        )


class PartialArtifactWriter:
    """Append finished records to a JSONL file, one durable line each.

    ``path=None`` (or "") disables — every method is then a no-op, so
    callers need no branching. Each ``append`` writes one line and
    fsyncs: a SIGKILL between legs loses at most the in-flight leg,
    never a finished one.
    """

    def __init__(self, path):
        self.path = str(path) if path else None

    def append(self, record):
        if not self.path:
            return
        line = json.dumps(record)
        with open(self.path, "a") as fh:
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    def read_all(self):
        """All records flushed so far (for tests / resumption tooling)."""
        if not self.path or not os.path.exists(self.path):
            return []
        with open(self.path) as fh:
            return [json.loads(ln) for ln in fh if ln.strip()]
