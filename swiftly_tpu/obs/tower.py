"""Fleet control tower: cross-source telemetry aggregation, windowed
signals, and SLO burn-rate alerts.

PRs 1/5 gave every *process* metrics and traces; the system has since
become a fleet — N serve replicas behind `serve.ServeFleet`, an
autoscaler, a shared cache fabric, elastic mesh recovery — and a
cross-replica incident was reconstructed by hand from per-process
JSONL. The tower is the missing aggregation point (the global-timeline
argument DaggerFFT makes for task-scheduled distributed FFTs,
arXiv 2601.12209):

* **Sources.** Each replica, the cache fabric, the autoscaler, the
  fleet itself (and, in the mesh drills, the recovery orchestrator)
  registers a *named telemetry source*: a callable returning a
  JSON-ready dict with optional ``counters`` (flat name → number) and
  ``stages`` (name → ``{"count", "total_s"}``) blocks.
  `fleet_telemetry` merges them into ONE artifact block — per-source
  breakdowns plus fleet ``totals`` that are exactly the per-source
  sums (re-derived and asserted by
  `validate_fleet_telemetry_artifact`).
* **Windowed signals.** Registered signal callables (queue share,
  queued depth, p99, shed rate, cache hit ratio...) are sampled once
  per supervisor tick into sliding windows. The brownout ladder and
  the `serve.FleetAutoscaler` consume THE SAME per-tick sample instead
  of each recomputing the signal — one clock, one value, bit-identical
  decisions.
* **SLO burn-rate alerts.** Declarative `SLO` specs are evaluated
  every tick with the classic multi-window rule: an alert OPENS when
  the breach fraction over both the fast and the slow window reaches
  the burn threshold (a blip cannot page), and CLOSES when the fast
  window clears (recovery is seen quickly). Open/close events land in
  the flight recorder (`obs.recorder`), on the trace, and in the
  ``alerts`` artifact block (`validate_alerts_artifact`).
* **Per-source Perfetto tracks.** Fleet threads name their trace
  tracks (`trace.name_track`), so the existing Chrome exporter renders
  one labelled row per source and ``scripts/trace_report.py
  --by-source`` groups the self-time attribution the same way.

See docs/observability.md ("Control tower") for the operator guide.
"""

from __future__ import annotations

import collections
import threading
import time

from . import metrics as _metrics
from . import recorder as _recorder
from . import trace as _trace

__all__ = [
    "SLO",
    "ControlTower",
    "validate_alerts_artifact",
    "validate_fleet_telemetry_artifact",
]

_WINDOW_SAMPLES = 4096  # per-signal sample ring
_MAX_ALERT_EVENTS = 256


class SLO:
    """One declarative objective over a registered tower signal.

    :param name: alert name (e.g. ``"queue_share"``)
    :param signal: the tower signal it watches (e.g.
        ``"fleet.queue_share"``)
    :param threshold: the objective boundary
    :param direction: ``"above"`` — a sample BREACHES when it exceeds
        ``threshold`` (latency, shed rate, queue share);
        ``"below"`` — a sample breaches when it falls under it (cache
        hit ratio, MFU floor)
    :param fast_s / slow_s: the two burn-rate windows in seconds
    :param burn: breach fraction (0..1] a window must reach to count
        as burning
    """

    __slots__ = ("name", "signal", "threshold", "direction", "fast_s",
                 "slow_s", "burn")

    def __init__(self, name, signal, threshold, direction="above",
                 fast_s=1.0, slow_s=5.0, burn=0.5):
        if direction not in ("above", "below"):
            raise ValueError(
                f"direction must be 'above' or 'below', got {direction!r}"
            )
        if not 0.0 < burn <= 1.0:
            raise ValueError(f"burn must be in (0, 1], got {burn!r}")
        if not 0.0 < fast_s <= slow_s:
            raise ValueError(
                f"need 0 < fast_s <= slow_s (got {fast_s}, {slow_s})"
            )
        self.name = str(name)
        self.signal = str(signal)
        self.threshold = float(threshold)
        self.direction = direction
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.burn = float(burn)

    def breached(self, value):
        if self.direction == "above":
            return value > self.threshold
        return value < self.threshold

    def spec(self):
        return {
            "name": self.name,
            "signal": self.signal,
            "threshold": self.threshold,
            "direction": self.direction,
            "fast_s": self.fast_s,
            "slow_s": self.slow_s,
            "burn": self.burn,
        }


class ControlTower:
    """The fleet-wide aggregation point: sources, signals, alerts.

    :param clock: injectable monotonic clock (share the fleet's so
        windows align with supervision ticks)
    :param slos: initial iterable of `SLO` specs
    """

    def __init__(self, *, clock=time.monotonic, slos=()):
        self._clock = clock
        self._lock = threading.Lock()
        self._sources = {}   # name -> (kind, callable)
        self._signals = {}   # name -> callable
        self._windows = {}   # signal -> deque[(t, value)]
        self._latest = {}    # signal -> last sampled value
        self.slos = [s if isinstance(s, SLO) else SLO(**s) for s in slos]
        self._alerts = {}    # slo name -> open alert dict
        self._alert_events = []
        self._counts = {
            "samples": 0, "alerts_opened": 0, "alerts_closed": 0,
            "source_errors": 0,
        }

    # -- sources -------------------------------------------------------------

    def register_source(self, name, fn, kind="replica"):
        """Register one named telemetry source: ``fn()`` must return a
        JSON-ready dict (optional ``counters``/``stages`` blocks feed
        the fleet totals). Re-registering a name replaces it."""
        with self._lock:
            self._sources[str(name)] = (str(kind), fn)

    def unregister_source(self, name):
        with self._lock:
            self._sources.pop(str(name), None)

    @property
    def sources(self):
        with self._lock:
            return {n: kind for n, (kind, _fn) in self._sources.items()}

    # -- signals -------------------------------------------------------------

    def register_signal(self, name, fn):
        """Register one windowed signal: ``fn()`` returns the current
        float value; the tower samples it on every `tick`."""
        with self._lock:
            self._signals[str(name)] = fn
            self._windows.setdefault(
                str(name), collections.deque(maxlen=_WINDOW_SAMPLES)
            )

    def signal(self, name, default=0.0):
        """The most recently sampled value of one signal."""
        with self._lock:
            return self._latest.get(name, default)

    def sample(self, now=None):
        """Sample every registered signal once into its window; returns
        ``{signal: value}`` — THE per-tick sample the brownout ladder
        and the autoscaler consume (one clock read, one value, shared
        by every consumer)."""
        now = self._clock() if now is None else now
        with self._lock:
            fns = list(self._signals.items())
        out = {}
        for name, fn in fns:
            try:
                v = float(fn())
            except Exception:  # noqa: BLE001 - a signal must not kill ticks
                self._counts["source_errors"] += 1
                continue
            out[name] = v
        with self._lock:
            for name, v in out.items():
                self._windows[name].append((now, v))
                self._latest[name] = v
            self._counts["samples"] += 1
        return out

    def window(self, name, seconds, now=None):
        """``[(t, value), ...]`` samples of one signal from the last
        ``seconds``."""
        now = self._clock() if now is None else now
        cutoff = now - float(seconds)
        with self._lock:
            ring = self._windows.get(name, ())
            return [(t, v) for (t, v) in ring if t >= cutoff]

    def window_mean(self, name, seconds, now=None):
        w = self.window(name, seconds, now)
        return sum(v for _t, v in w) / len(w) if w else None

    # -- SLO burn-rate evaluation --------------------------------------------

    def add_slo(self, slo):
        self.slos.append(slo if isinstance(slo, SLO) else SLO(**slo))

    def set_slos(self, slos):
        self.slos = [s if isinstance(s, SLO) else SLO(**s) for s in slos]

    def _burn(self, slo, seconds, now):
        """Breach fraction of one window, or None with no samples."""
        w = self.window(slo.signal, seconds, now)
        if not w:
            return None
        return sum(1 for _t, v in w if slo.breached(v)) / len(w)

    def evaluate(self, now=None):
        """One multi-window burn-rate pass over every SLO: opens an
        alert when BOTH windows burn at/above the threshold, closes it
        when the fast window clears. Returns the list of open alerts."""
        now = self._clock() if now is None else now
        for slo in self.slos:
            fast = self._burn(slo, slo.fast_s, now)
            slow = self._burn(slo, slo.slow_s, now)
            open_alert = self._alerts.get(slo.name)
            if open_alert is None:
                if (
                    fast is not None and slow is not None
                    and fast >= slo.burn and slow >= slo.burn
                ):
                    self._open_alert(slo, now, fast, slow)
            elif fast is not None and fast < slo.burn:
                self._close_alert(slo, now, fast, slow)
        return self.open_alerts()

    def tick(self, now=None):
        """Sample + evaluate: the supervisor-tick entry point. Returns
        the per-tick signal sample (see `sample`)."""
        now = self._clock() if now is None else now
        out = self.sample(now)
        self.evaluate(now)
        return out

    def _open_alert(self, slo, now, fast, slow):
        alert = {
            "slo": slo.name,
            "signal": slo.signal,
            "threshold": slo.threshold,
            "direction": slo.direction,
            "opened_t": round(now, 6),
            "value": self._latest.get(slo.signal),
            "fast_burn": round(fast, 4),
            "slow_burn": round(slow, 4),
        }
        with self._lock:
            self._alerts[slo.name] = alert
            self._counts["alerts_opened"] += 1
            if len(self._alert_events) < _MAX_ALERT_EVENTS:
                self._alert_events.append(
                    {"t": round(now, 6), "slo": slo.name,
                     "action": "open", "fast_burn": round(fast, 4),
                     "slow_burn": round(slow, 4)}
                )
        _metrics.count("tower.alerts_opened")
        _trace.instant(f"alert.{slo.name}.open", cat="alert",
                       signal=slo.signal, fast_burn=round(fast, 4),
                       slow_burn=round(slow, 4))
        _recorder.record(
            "alert", f"alert.{slo.name}.open",
            f"{slo.signal} fast={fast:.2f} slow={slow:.2f} "
            f"vs burn={slo.burn:.2f}",
        )

    def _close_alert(self, slo, now, fast, slow):
        with self._lock:
            opened = self._alerts.pop(slo.name, None)
            self._counts["alerts_closed"] += 1
            if len(self._alert_events) < _MAX_ALERT_EVENTS:
                self._alert_events.append(
                    {"t": round(now, 6), "slo": slo.name,
                     "action": "close",
                     "fast_burn": round(fast, 4) if fast is not None
                     else None,
                     "open_s": round(now - opened["opened_t"], 6)
                     if opened else None}
                )
        _metrics.count("tower.alerts_closed")
        _trace.instant(f"alert.{slo.name}.close", cat="alert",
                       signal=slo.signal)
        _recorder.record(
            "alert", f"alert.{slo.name}.close",
            f"{slo.signal} fast cleared"
            + (f" ({fast:.2f} < {slo.burn:.2f})" if fast is not None
               else ""),
        )

    def open_alerts(self):
        with self._lock:
            return list(self._alerts.values())

    # -- export --------------------------------------------------------------

    def heartbeat_fields(self):
        """The fleet fields `obs.heartbeat.Heartbeat` stamps when a
        tower is active: replica count, open alerts, queue depth and
        the brownout rung (all from already-sampled state — no source
        calls on the heartbeat path)."""
        with self._lock:
            replicas = sum(
                1 for kind, _fn in self._sources.values()
                if kind == "replica"
            )
            open_alerts = len(self._alerts)
            depth = self._latest.get("fleet.queued_depth")
            rung = self._latest.get("fleet.brownout_level")
        return {
            "fleet_replicas": replicas,
            "fleet_open_alerts": open_alerts,
            "fleet_queue_depth": None if depth is None else int(depth),
            "fleet_brownout_level": None if rung is None else int(rung),
        }

    def fleet_telemetry(self):
        """The ``fleet_telemetry`` artifact block: every source's
        export keyed by name, plus fleet ``totals`` summing the
        per-source ``counters`` and ``stages`` — by construction the
        per-replica breakdowns sum to the fleet totals, and
        `validate_fleet_telemetry_artifact` re-derives the sums to
        prove it."""
        with self._lock:
            sources = list(self._sources.items())
            latest = {
                k: round(v, 6) for k, v in self._latest.items()
            }
        blocks = {}
        for name, (kind, fn) in sources:
            try:
                stats = fn()
            except Exception as exc:  # noqa: BLE001 - keep exporting
                with self._lock:
                    self._counts["source_errors"] += 1
                blocks[name] = {"kind": kind, "error": str(exc)}
                continue
            blocks[name] = {"kind": kind, **(stats or {})}
        with self._lock:
            # counts snapshot AFTER the source calls so this export's
            # own source errors are visible in this export
            counts = dict(self._counts)
        return {
            "n_sources": len(blocks),
            "sources": blocks,
            "totals": _totals(blocks),
            "signals": latest,
            **counts,
        }

    def alerts_block(self):
        """The ``alerts`` artifact block (see
        `validate_alerts_artifact`)."""
        with self._lock:
            return {
                "slos": [s.spec() for s in self.slos],
                "open": list(self._alerts.values()),
                "events": list(self._alert_events),
                "opened": self._counts["alerts_opened"],
                "closed": self._counts["alerts_closed"],
            }


def _totals(blocks):
    """Fleet totals over source blocks: per-name counter sums and
    per-stage ``{count, total_s}`` sums."""
    counters = {}
    stages = {}
    for block in blocks.values():
        for k, v in (block.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for k, st in (block.get("stages") or {}).items():
            if not isinstance(st, dict):
                continue
            agg = stages.setdefault(k, {"count": 0, "total_s": 0.0})
            agg["count"] += int(st.get("count", 0))
            agg["total_s"] += float(st.get("total_s", 0.0))
    for agg in stages.values():
        agg["total_s"] = round(agg["total_s"], 6)
    return {"counters": counters, "stages": stages}


# ---------------------------------------------------------------------------
# Artifact validators (the obs.manifest pattern: a list of problem
# strings, empty when the block holds).
# ---------------------------------------------------------------------------

_SLO_SPEC_FIELDS = ("name", "signal", "threshold", "direction",
                    "fast_s", "slow_s", "burn")


def validate_fleet_telemetry_artifact(record):
    """Problems with a record's ``fleet_telemetry`` block: sources
    present, each carrying a ``kind``, and the stamped ``totals``
    EQUAL to the re-derived per-source sums (a totals block that
    drifts from its breakdowns is a lie, not an aggregate)."""
    problems = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    ft = record.get("fleet_telemetry")
    if not isinstance(ft, dict):
        return ["missing fleet_telemetry block"]
    sources = ft.get("sources")
    if not isinstance(sources, dict) or not sources:
        problems.append("fleet_telemetry has no sources")
        return problems
    for name, block in sources.items():
        if not isinstance(block, dict) or "kind" not in block:
            problems.append(f"source {name!r} missing kind")
    totals = ft.get("totals")
    if not isinstance(totals, dict):
        problems.append("fleet_telemetry missing totals")
        return problems
    derived = _totals(sources)
    for k, v in derived["counters"].items():
        got = (totals.get("counters") or {}).get(k)
        if got is None or abs(float(got) - float(v)) > 1e-6:
            problems.append(
                f"totals.counters[{k!r}] = {got!r} != per-source sum {v}"
            )
    for k, agg in derived["stages"].items():
        got = (totals.get("stages") or {}).get(k)
        if not isinstance(got, dict):
            problems.append(f"totals.stages missing {k!r}")
            continue
        if int(got.get("count", -1)) != agg["count"]:
            problems.append(
                f"totals.stages[{k!r}].count = {got.get('count')!r} "
                f"!= per-source sum {agg['count']}"
            )
        if abs(float(got.get("total_s", -1.0)) - agg["total_s"]) > 1e-5:
            problems.append(
                f"totals.stages[{k!r}].total_s = "
                f"{got.get('total_s')!r} != per-source sum "
                f"{agg['total_s']}"
            )
    return problems


def validate_alerts_artifact(record):
    """Problems with a record's ``alerts`` block: SLO specs complete,
    event trail well-formed (open/close only), and the open/closed
    ledger consistent (open alerts == opened - closed)."""
    problems = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    al = record.get("alerts")
    if not isinstance(al, dict):
        return ["missing alerts block"]
    slos = al.get("slos")
    if not isinstance(slos, list):
        problems.append("alerts.slos is not a list")
        slos = []
    for i, spec in enumerate(slos):
        if not isinstance(spec, dict):
            problems.append(f"alerts.slos[{i}] is not a dict")
            continue
        for field in _SLO_SPEC_FIELDS:
            if field not in spec:
                problems.append(f"alerts.slos[{i}] missing {field!r}")
        if spec.get("direction") not in ("above", "below"):
            problems.append(
                f"alerts.slos[{i}] direction "
                f"{spec.get('direction')!r} not above/below"
            )
    events = al.get("events")
    if not isinstance(events, list):
        problems.append("alerts.events is not a list")
        events = []
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "slo" not in e or "t" not in e:
            problems.append(f"alerts.events[{i}] missing slo/t")
            continue
        if e.get("action") not in ("open", "close"):
            problems.append(
                f"alerts.events[{i}] action {e.get('action')!r} "
                "not open/close"
            )
    opened = al.get("opened")
    closed = al.get("closed")
    open_list = al.get("open")
    if not isinstance(open_list, list):
        problems.append("alerts.open is not a list")
        open_list = []
    if not isinstance(opened, int) or not isinstance(closed, int):
        problems.append("alerts.opened/closed not ints")
    else:
        if closed > opened:
            problems.append(
                f"alerts closed {closed} > opened {opened}"
            )
        if len(open_list) != opened - closed:
            problems.append(
                f"{len(open_list)} open alert(s) != opened {opened} - "
                f"closed {closed}"
            )
    return problems
