"""Critical-path tracing: hierarchical spans, request journeys, HBM
watermarks, Chrome trace-event export.

``obs.metrics`` answers "how much time did stage X cost *in total*";
this module answers "where did THIS run (or THIS serve request) spend
its time, and what was on the critical path" — the question the
streamed 64k/128k plans, the serving SLO harness and the chaos drills
raise. The model is Dask's task-stream timeline and the XLA profiler's
Perfetto traces: structured spans with parent/child context, exported
to the Chrome trace-event JSON format any Perfetto UI loads.

Design constraints, in the ``metrics.py`` discipline:

1. **Zero cost off.** Disabled (the default), ``trace.span(...)`` is
   one attribute check and the return of a shared no-op context
   manager — no allocation, no clock read, no contextvar touch. Every
   ``metrics.stage(...)`` site doubles as a trace site through the
   bridge in ``metrics._Stage``, so the engine's hot loops carry ONE
   set of instrumentation for both systems.
2. **One vocabulary.** Spans opened by the metrics bridge carry the
   stage names documented in docs/observability.md, so host spans line
   up with the ``jax.profiler.TraceAnnotation`` device tracks when
   both traces are loaded side by side.
3. **Hierarchy via contextvars.** The current span is a context
   variable: nested ``with`` blocks build the run → bench leg → pass →
   column group → stage tree automatically, async-task-safe. Worker
   threads inherit the spawning context explicitly via ``current()`` /
   ``adopt(ctx)`` (contextvars do not flow into ``threading.Thread``).
4. **Peak-memory attribution.** At every span close the tracer samples
   per-device HBM (``device.memory_stats()`` where the runtime exposes
   it; the ``set_hbm_gauge`` fallback otherwise) and stamps the
   watermark into the span — and into the
   ``metrics.gauge_max("hbm.peak_bytes")`` peak gauge.

Request journeys (``serve.SubgridService``) are recorded as
*explicit-time* spans (`add_span`): the service knows a request's
admission / queue-exit / compute-done / completion timestamps only at
completion, and emits the journey segments retroactively onto a
per-request synthetic track so Perfetto shows one row per request and
``report.py`` can decompose p99 outliers into queue vs compute vs
transfer.

Enable via ``SWIFTLY_TRACE=1`` (``SWIFTLY_TRACE_PATH`` names the
Chrome JSON written at interpreter exit) or programmatically with
``trace.enable(path)``; ``bench.py --trace PATH`` and the demo
scripts' ``--trace PATH`` wire it per run. See docs/observability.md.
"""

from __future__ import annotations

import atexit
import contextvars
import itertools
import json
import os
import threading
import time

__all__ = [
    "Tracer",
    "adopt",
    "current",
    "disable",
    "enable",
    "enabled",
    "export",
    "get_tracer",
    "instant",
    "name_track",
    "reset",
    "save",
    "set_hbm_gauge",
    "span",
]

# Synthetic-track base: journey spans get tid = base + request id so
# every serve request renders as its own Perfetto row (real thread ids
# stay far below this).
JOURNEY_TID_BASE = 1 << 20

_SPAN_IDS = itertools.count(1)
_CURRENT = contextvars.ContextVar("swiftly_trace_span", default=0)


class _NullSpan:
    """The shared disabled-path context manager (no state, no work).

    Attribute writes and ``set(...)`` calls are swallowed so call sites
    may annotate spans unconditionally without branching on enablement.
    """

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __setattr__(self, name, value):
        pass

    def set(self, **args):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """One enabled span: perf_counter bracket + contextvar parenting."""

    __slots__ = ("_tr", "id", "parent", "name", "cat", "args", "tid",
                 "_t0", "_token")

    def __init__(self, tracer, name, cat, args):
        self._tr = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **args):
        """Attach args discovered inside the block (bytes, counts...)."""
        self.args.update(args)
        return self

    def __enter__(self):
        self.id = next(_SPAN_IDS)
        self.parent = _CURRENT.get()
        self._token = _CURRENT.set(self.id)
        self.tid = threading.get_native_id()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        try:
            _CURRENT.reset(self._token)
        except ValueError:  # pragma: no cover - exited in a peer context
            _CURRENT.set(self.parent)
        self._tr._finish(self, self._t0, t1)
        return False


class Tracer:
    """Span recorder + Chrome trace-event exporter; no-op unless enabled.

    One process-wide instance (``get_tracer()``) serves the engine;
    independent instances are constructible for tests.
    """

    def __init__(self, enabled=False, path=None):
        self._lock = threading.Lock()
        self.enabled = False
        self.path = None
        self._spans = []   # finished spans, completion order
        self._events = []  # instant events
        self._track_names = {}  # tid -> label ("M" metadata + --by-source)
        self._t0 = time.perf_counter()
        self._t_epoch = time.time()
        self._hbm_sampler = None
        self._hbm_gauge = None
        self._atexit_registered = False
        if enabled:
            self.enable(path)

    # -- lifecycle ---------------------------------------------------------

    def enable(self, path=None):
        """Turn recording on; ``path`` names the Chrome JSON written by
        ``save()`` (and at interpreter exit, so ``SWIFTLY_TRACE=1``
        runs that never call save still leave a loadable timeline).

        The HBM sampler is resolved here (not per span) so the
        enabled-path cost stays one callable check; runtimes without
        ``device.memory_stats()`` (CPU) fall back to whatever the
        instrumentation last pushed through ``set_hbm_gauge``.
        """
        with self._lock:
            self.enabled = True
            self._t0 = time.perf_counter()
            self._t_epoch = time.time()
            if path:
                self.path = str(path)
                if not self._atexit_registered:
                    self._atexit_registered = True
                    atexit.register(self._atexit_save)
            if self._hbm_sampler is None:
                self._hbm_sampler = _resolve_hbm_sampler()
        return self

    def disable(self):
        """Stop recording (spans are kept for export until reset())."""
        with self._lock:
            self.enabled = False

    def reset(self):
        """Drop all recorded spans/events and rebase the clock."""
        with self._lock:
            self._spans = []
            self._events = []
            self._track_names = {}
            self._t0 = time.perf_counter()
            self._t_epoch = time.time()
            self._hbm_gauge = None

    def _atexit_save(self):  # pragma: no cover - interpreter shutdown
        try:
            if self.path and (self._spans or self._events):
                self.save(self.path)
        except Exception:
            pass

    # -- recording ---------------------------------------------------------

    def span(self, name, cat="host", **args):
        """Context manager opening one span as a child of the current
        context; disabled this returns the shared no-op immediately."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def name_track(self, tid, label):
        """Label one Perfetto track (thread row): fleet sources name
        their own tid (``replica-3``, ``fleet-supervisor``) so the
        exported timeline reads per-source and ``trace_report.py
        --by-source`` can group attribution the same way."""
        if not self.enabled:
            return
        with self._lock:
            self._track_names[int(tid)] = str(label)

    def track_names(self):
        """``{tid: label}`` of explicitly named tracks."""
        with self._lock:
            return dict(self._track_names)

    def instant(self, name, cat="event", **args):
        """One timestamped point event (fault injections, degradation
        steps, shed/quarantine decisions...)."""
        if not self.enabled:
            return
        rec = {
            "name": name,
            "cat": cat,
            "ts": time.perf_counter() - self._t0,
            "tid": threading.get_native_id(),
            "args": args,
        }
        with self._lock:
            self._events.append(rec)

    def add_span(self, name, t0, t1, cat="host", tid=None, parent=0,
                 **args):
        """Record a span with EXPLICIT perf_counter endpoints (for
        retroactive emission — e.g. a serve request's queue segment,
        known only at completion). Returns the span id (0 disabled)."""
        if not self.enabled:
            return 0
        sid = next(_SPAN_IDS)
        rec = {
            "id": sid,
            "parent": parent,
            "name": name,
            "cat": cat,
            "tid": threading.get_native_id() if tid is None else int(tid),
            "ts": t0 - self._t0,
            "dur": max(0.0, t1 - t0),
            "args": args,
        }
        with self._lock:
            self._spans.append(rec)
        return sid

    def _finish(self, span, t0, t1):
        rec = {
            "id": span.id,
            "parent": span.parent,
            "name": span.name,
            "cat": span.cat,
            "tid": span.tid,
            "ts": t0 - self._t0,
            "dur": t1 - t0,
            "args": span.args,
        }
        hbm = self._sample_hbm()
        if hbm is not None:
            rec["args"]["hbm_peak_bytes"] = hbm
        with self._lock:
            self._spans.append(rec)

    # -- HBM watermarks -----------------------------------------------------

    def set_hbm_gauge(self, nbytes):
        """Fallback watermark for runtimes without memory_stats: the
        instrumentation pushes its best projection (plan bytes, RSS...)
        and subsequent span closes stamp it."""
        self._hbm_gauge = int(nbytes)

    def _sample_hbm(self):
        sampler = self._hbm_sampler
        if sampler is not None:
            try:
                v = sampler()
            except Exception:  # pragma: no cover - runtime hiccup
                v = None
            if v:
                self._push_hbm_peak(v)
                return v
        return self._hbm_gauge

    @staticmethod
    def _push_hbm_peak(v):
        # local import: metrics imports this module (the stage bridge),
        # so the reverse edge must stay function-scoped
        from . import metrics as _metrics

        _metrics.gauge_max("hbm.peak_bytes", int(v))

    # -- export ------------------------------------------------------------

    def counts(self):
        """(n_spans, n_events) recorded so far."""
        with self._lock:
            return len(self._spans), len(self._events)

    def export(self):
        """The recorded timeline as a Chrome trace-event JSON dict.

        Every span is a complete ``"ph": "X"`` event whose args carry
        ``span_id``/``parent_id`` (the explicit tree — nesting-by-time
        reconstruction is not needed), instants are ``"ph": "i"``
        thread-scoped events, and synthetic tracks (request journeys)
        get ``"M"`` thread-name metadata so Perfetto labels the rows.
        """
        pid = os.getpid()
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            t_epoch = self._t_epoch
            named_tids = dict(self._track_names)
        out = []
        for s in spans:
            args = dict(s["args"])
            args["span_id"] = s["id"]
            args["parent_id"] = s["parent"]
            out.append(
                {
                    "name": s["name"],
                    "cat": s["cat"],
                    "ph": "X",
                    "ts": round(s["ts"] * 1e6, 3),
                    "dur": round(s["dur"] * 1e6, 3),
                    "pid": pid,
                    "tid": s["tid"],
                    "args": args,
                }
            )
            if s["tid"] >= JOURNEY_TID_BASE and s["tid"] not in named_tids:
                named_tids[s["tid"]] = (
                    f"req {s['tid'] - JOURNEY_TID_BASE}"
                )
        for e in events:
            out.append(
                {
                    "name": e["name"],
                    "cat": e["cat"],
                    "ph": "i",
                    "s": "t",
                    "ts": round(e["ts"] * 1e6, 3),
                    "pid": pid,
                    "tid": e["tid"],
                    "args": dict(e["args"]),
                }
            )
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": label},
            }
            for tid, label in sorted(named_tids.items())
        ]
        return {
            "traceEvents": meta + out,
            "displayTimeUnit": "ms",
            "otherData": {
                "schema": "swiftly-tpu-trace/1",
                "t_epoch": t_epoch,
                "n_spans": len(spans),
                "n_events": len(events),
            },
        }

    def save(self, path=None, atomic=False):
        """Write the Chrome trace JSON; returns the path written.

        ``atomic=True`` publishes via a tmp sibling + rename (the
        `write_stream_state` discipline) so a concurrent reader — the
        process-fleet parent merging worker timelines while the worker
        is still serving — sees the previous complete trace or the new
        one, never a torn file."""
        path = str(path or self.path)
        if not path:
            raise ValueError("no trace path given and none configured")
        if atomic:
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(self.export(), fh)
            os.replace(tmp, path)
        else:
            with open(path, "w") as fh:
                json.dump(self.export(), fh)
        return path


def _resolve_hbm_sampler():
    """A zero-arg callable returning device-0 peak HBM bytes, or None
    when the runtime exposes no memory_stats (CPU, some tunnels)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None
        key = (
            "peak_bytes_in_use"
            if "peak_bytes_in_use" in stats
            else "bytes_in_use" if "bytes_in_use" in stats else None
        )
        if key is None:
            return None

        def sample():
            s = dev.memory_stats() or {}
            return int(s.get(key, 0))

        return sample
    except Exception:
        return None


# ---------------------------------------------------------------------------
# The process-wide tracer + module-level conveniences (the engine's
# call-site API: `from ..obs import trace` ... `trace.span(...)`).
# ---------------------------------------------------------------------------

_TRACER = Tracer(
    enabled=os.environ.get("SWIFTLY_TRACE", "0") not in ("", "0"),
    path=os.environ.get("SWIFTLY_TRACE_PATH") or None,
)


def get_tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return _TRACER.enabled


def path():
    return _TRACER.path


def enable(path=None):
    return _TRACER.enable(path)


def disable():
    _TRACER.disable()


def reset():
    _TRACER.reset()


def span(name, cat="host", **args):
    if not _TRACER.enabled:  # keep the disabled path one check deep
        return _NULL_SPAN
    return _Span(_TRACER, name, cat, args)


def instant(name, cat="event", **args):
    _TRACER.instant(name, cat=cat, **args)


def name_track(tid, label):
    _TRACER.name_track(tid, label)


def add_span(name, t0, t1, cat="host", tid=None, parent=0, **args):
    return _TRACER.add_span(name, t0, t1, cat=cat, tid=tid,
                            parent=parent, **args)


def set_hbm_gauge(nbytes):
    _TRACER.set_hbm_gauge(nbytes)


def current() -> int:
    """The current span id — capture before handing work to a thread."""
    return _CURRENT.get()


def adopt(ctx: int):
    """Adopt ``ctx`` (a ``current()`` capture) as this thread's parent
    span — contextvars do not flow into ``threading.Thread`` targets."""
    _CURRENT.set(int(ctx))


def export():
    return _TRACER.export()


def save(path=None, atomic=False):
    return _TRACER.save(path, atomic=atomic)
