"""Run provenance: the manifest stamped into every BENCH artifact.

Round-5 review found BENCH artifacts whose baseline was an unauditable
hand-typed constant and same-day artifacts disagreeing with no way to
tell which code/config produced which number (VERDICT.md "What's
missing" #3). The manifest makes every artifact self-describing: which
device, which git revision, which env knobs, which config — and, most
importantly, where its ``numpy_baseline_s`` came from
(``baseline_source``):

* ``"measured"``  — the numpy reference ran on this machine this run;
* ``"operator"``  — supplied via BENCH_NUMPY_BASELINE_S (e.g. from a
  prior full run of the same config) — auditable via the env capture;
* ``"estimated"`` — sample-extrapolated from timed sub-ops
  (``bench._numpy_baseline_from_parts``), bracket recorded alongside.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import subprocess
import sys
import time

__all__ = [
    "BASELINE_SOURCES",
    "CACHE_ARTIFACT_FIELDS",
    "DELTA_ARTIFACT_FIELDS",
    "FLEET_ARTIFACT_FIELDS",
    "MANIFEST_SCHEMA",
    "MESH_ARTIFACT_FIELDS",
    "PLAN_ARTIFACT_FIELDS",
    "PROCFLEET_ARTIFACT_FIELDS",
    "RESILIENCE_ARTIFACT_FIELDS",
    "SERVE_ARTIFACT_FIELDS",
    "config_hash",
    "run_manifest",
    "validate_artifact",
    "validate_delta_artifact",
    "validate_fleet_artifact",
    "validate_mesh_artifact",
    "validate_plan_artifact",
    "validate_procfleet_artifact",
    "validate_resilience_artifact",
    "validate_serve_artifact",
]

MANIFEST_SCHEMA = "swiftly-tpu-run-manifest/1"

BASELINE_SOURCES = ("measured", "operator", "estimated")

# Env prefixes that change what the engine executes (captured verbatim);
# anything else in the environment is noise for reproduction purposes.
_ENV_PREFIXES = ("SWIFTLY_", "BENCH_", "JAX_", "XLA_")


def _git_revision(path):
    """(sha, dirty) of the repo containing `path`, or (None, None)."""
    try:
        cwd = os.path.dirname(os.path.abspath(path))
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, capture_output=True,
            text=True, timeout=10,
        )
        if sha.returncode != 0:
            return None, None
        status = subprocess.run(
            ["git", "status", "--porcelain"], cwd=cwd,
            capture_output=True, text=True, timeout=10,
        )
        dirty = bool(status.stdout.strip()) if status.returncode == 0 else None
        return sha.stdout.strip(), dirty
    except Exception:  # pragma: no cover - no git binary
        return None, None


def config_hash(params) -> str:
    """Deterministic short hash of a config/parameter mapping."""
    blob = json.dumps(params, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _device_info():
    try:
        import jax

        devs = jax.devices()
        return {
            "platform": devs[0].platform,
            "kind": str(getattr(devs[0], "device_kind", "")),
            "count": len(devs),
        }
    except Exception:  # pragma: no cover - jax not importable/initialised
        return {"platform": None, "kind": None, "count": 0}


def run_manifest(baseline_source=None, params=None, extra=None) -> dict:
    """The full provenance record for one run/artifact.

    :param baseline_source: one of ``BASELINE_SOURCES`` (or None when
        the artifact carries no baseline comparison at all)
    :param params: the config parameter mapping the run executed
        (hashed into ``config_hash`` and recorded verbatim)
    :param extra: caller fields merged in at top level (must not
        collide with schema fields)
    """
    if baseline_source is not None and baseline_source not in BASELINE_SOURCES:
        raise ValueError(
            f"baseline_source must be one of {BASELINE_SOURCES}, "
            f"got {baseline_source!r}"
        )
    sha, dirty = _git_revision(__file__)
    env = {
        k: v
        for k, v in sorted(os.environ.items())
        if k.startswith(_ENV_PREFIXES)
    }
    try:
        import jax

        jax_version = jax.__version__
    except Exception:  # pragma: no cover
        jax_version = None
    import numpy as np

    # the tracing state is recorded explicitly (not just via the env
    # capture): a programmatic trace.enable(path) leaves no env trail,
    # but the artifact must still name the timeline it belongs to
    from . import trace as _trace_mod

    manifest = {
        "schema": MANIFEST_SCHEMA,
        "timestamp_utc": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "hostname": platform.node(),
        "platform": platform.platform(),
        "python": sys.version.split()[0],
        "jax": jax_version,
        "numpy": np.__version__,
        "device": _device_info(),
        "git_sha": sha,
        "git_dirty": dirty,
        "argv": list(sys.argv),
        "env": env,
        "baseline_source": baseline_source,
        "trace": {
            "enabled": bool(_trace_mod.enabled()),
            "path": _trace_mod.path(),
        },
    }
    if params is not None:
        manifest["config_params"] = dict(params)
        manifest["config_hash"] = config_hash(params)
    if extra:
        overlap = set(extra) & set(manifest)
        if overlap:
            raise ValueError(f"extra fields collide with schema: {overlap}")
        manifest.update(extra)
    return manifest


# Fields every stamped manifest must carry (schema check for the
# bench --smoke leg and the obs tests).
_REQUIRED_MANIFEST_FIELDS = (
    "schema", "timestamp_utc", "device", "git_sha", "env",
    "baseline_source",
)


def validate_artifact(record, require_baseline=True):
    """Problems with a BENCH-style artifact record, as a list of strings.

    An empty list means the record passes: it carries a complete
    manifest, a valid ``baseline_source``, and (for measured legs) the
    headline metric fields. Used by ``bench.py --smoke`` and the tier-1
    schema test — schema drift fails fast instead of surfacing as an
    unauditable artifact months later.
    """
    problems = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    manifest = record.get("manifest")
    if not isinstance(manifest, dict):
        problems.append("missing manifest")
        manifest = {}
    for field in _REQUIRED_MANIFEST_FIELDS:
        if field not in manifest:
            problems.append(f"manifest missing field {field!r}")
    if manifest.get("schema") not in (None, MANIFEST_SCHEMA):
        problems.append(
            f"manifest schema {manifest.get('schema')!r} != "
            f"{MANIFEST_SCHEMA!r}"
        )
    if require_baseline:
        src = record.get("baseline_source", manifest.get("baseline_source"))
        if src not in BASELINE_SOURCES:
            problems.append(
                f"baseline_source {src!r} not in {BASELINE_SOURCES}"
            )
    for field in ("metric", "value", "unit"):
        if field not in record:
            problems.append(f"missing metric field {field!r}")
    return problems


# The latency-SLO block every `bench.py --serve` artifact must carry
# (`SubgridService.stats()` flattened into the record) — the serving
# workload's schema contract, guarded by the --serve --smoke leg the
# same way validate_artifact guards the batch legs.
SERVE_ARTIFACT_FIELDS = (
    "p50_ms",
    "p99_ms",
    "shed_rate",
    "coalesce_hit_rate",
    "throughput_rps",
    "n_requests",
    "n_served",
)


def validate_serve_artifact(record):
    """Problems with a serve-mode BENCH artifact, as a list of strings.

    Serving legs carry no numpy baseline (there is no reference serving
    implementation to race) but must carry the full manifest plus the
    SLO metric block, with rates in [0, 1] and a coherent latency
    ordering — schema drift in the serving telemetry fails in seconds
    on CPU, not in a production latency regression nobody can read.

    One of the serving-family validators (`validate_serve_artifact`,
    `validate_fleet_artifact`, `validate_vis_artifact`): all three
    share the manifest + latency-ordering + bit-identity checks and
    differ in the workload block they enforce.
    """
    problems = validate_artifact(record, require_baseline=False)
    for field in SERVE_ARTIFACT_FIELDS:
        if field not in record:
            problems.append(f"missing serve field {field!r}")
    for rate in ("shed_rate", "coalesce_hit_rate"):
        v = record.get(rate)
        if v is not None and not (0.0 <= v <= 1.0):
            problems.append(f"{rate} {v!r} outside [0, 1]")
    p50, p99 = record.get("p50_ms"), record.get("p99_ms")
    if (
        isinstance(p50, (int, float))
        and isinstance(p99, (int, float))
        and p99 < p50
    ):
        problems.append(f"p99_ms {p99} < p50_ms {p50}")
    if record.get("n_served") and not record.get("throughput_rps"):
        problems.append("served requests but no throughput_rps")
    bit = record.get("bit_identical")
    if not isinstance(bit, dict) or not (
        {"checked", "mismatches"} <= set(bit)
    ):
        problems.append(
            "missing bit_identical {checked, mismatches} block"
        )
    journey = record.get("journey")
    if isinstance(journey, dict):
        # the request-journey decomposition must partition the served
        # wall: segment shares sum to 1 (each segment is a contiguous
        # timestamp diff of the same per-request interval)
        shares = [
            journey[seg]["share"]
            for seg in ("queue", "compute", "transfer")
            if isinstance(journey.get(seg), dict)
            and "share" in journey[seg]
        ]
        if len(shares) != 3:
            problems.append(
                "journey block missing queue/compute/transfer segments"
            )
        elif not 0.99 <= sum(shares) <= 1.01:
            problems.append(
                f"journey segment shares sum to {sum(shares)}, not 1"
            )
    return problems


# The visibility block every `bench.py --vis` artifact must carry
# (`VisibilityService.stats()` plus the accuracy/adjoint/grid audits)
# — the visibility-serving schema contract, guarded by the --vis
# --smoke leg like the serve/fleet families above.
VIS_ARTIFACT_FIELDS = (
    "p50_ms",
    "p99_ms",
    "shed_rate",
    "coalesce_hit_rate",
    "throughput_ksamples_s",
    "n_requests",
    "n_samples",
    "n_served_samples",
    "degrid_rms",
    "kernel",
    "adjoint",
    "grid",
)


def validate_vis_artifact(record):
    """Problems with a vis-mode BENCH artifact, as a list of strings.

    Visibility legs are audited against the direct-DFT oracle instead
    of a numpy baseline race, so beyond the manifest + SLO checks of
    the serve family this validator enforces the ACCURACY contract:
    ``degrid_rms`` within the stamped kernel's ``tolerance``
    (`vis.kernel.DEGRID_TOLERANCE`), the adjoint dot-product identity
    within its own tolerance, and a gridding block showing the batch
    round-tripped into the backward ingest — a vis artifact that
    serves fast but wrong must fail validation, not ship.
    """
    problems = validate_artifact(record, require_baseline=False)
    vis = record.get("vis")
    if not isinstance(vis, dict):
        problems.append("missing vis block")
        return problems
    for field in VIS_ARTIFACT_FIELDS:
        if field not in vis:
            problems.append(f"missing vis field {field!r}")
    for rate in ("shed_rate", "coalesce_hit_rate"):
        v = vis.get(rate)
        if v is not None and not (0.0 <= v <= 1.0):
            problems.append(f"vis {rate} {v!r} outside [0, 1]")
    p50, p99 = vis.get("p50_ms"), vis.get("p99_ms")
    if (
        isinstance(p50, (int, float))
        and isinstance(p99, (int, float))
        and p99 < p50
    ):
        problems.append(f"vis p99_ms {p99} < p50_ms {p50}")
    if vis.get("n_served_samples") and not vis.get(
        "throughput_ksamples_s"
    ):
        problems.append("served samples but no throughput_ksamples_s")
    kernel = vis.get("kernel")
    if not isinstance(kernel, dict) or not (
        {"support", "oversample", "band", "tolerance"} <= set(kernel)
    ):
        problems.append(
            "missing kernel {support, oversample, band, tolerance} "
            "block"
        )
        kernel = {}
    rms = vis.get("degrid_rms")
    tol = kernel.get("tolerance")
    if (
        isinstance(rms, (int, float))
        and isinstance(tol, (int, float))
        and rms > tol
    ):
        problems.append(
            f"degrid_rms {rms} exceeds the kernel tolerance {tol}"
        )
    adjoint = vis.get("adjoint")
    if not isinstance(adjoint, dict) or not (
        {"rel_err", "tolerance"} <= set(adjoint)
    ):
        problems.append("missing adjoint {rel_err, tolerance} block")
    elif adjoint["rel_err"] > adjoint["tolerance"]:
        problems.append(
            f"adjoint rel_err {adjoint['rel_err']} exceeds "
            f"{adjoint['tolerance']}"
        )
    grid = vis.get("grid")
    if not isinstance(grid, dict) or not (
        {"n_gridded", "ingested"} <= set(grid)
    ):
        problems.append("missing grid {n_gridded, ingested} block")
    elif grid.get("n_gridded") and not grid.get("ingested"):
        problems.append(
            "gridded samples never ingested into the backward "
            "(add_subgrid_group round-trip missing)"
        )
    bit = record.get("bit_identical")
    if not isinstance(bit, dict) or not (
        {"checked", "mismatches"} <= set(bit)
    ):
        problems.append(
            "missing bit_identical {checked, mismatches} block"
        )
    journey = vis.get("journey")
    if isinstance(journey, dict):
        shares = [
            journey[seg]["share"]
            for seg in ("queue", "compute", "transfer")
            if isinstance(journey.get(seg), dict)
            and "share" in journey[seg]
        ]
        if len(shares) != 3:
            problems.append(
                "vis journey block missing queue/compute/transfer "
                "segments"
            )
        elif not 0.99 <= sum(shares) <= 1.01:
            problems.append(
                f"vis journey segment shares sum to {sum(shares)}, "
                "not 1"
            )
    return problems


# The fleet block every `bench.py --fleet` artifact must carry — the
# self-healing serve drill's schema contract: the kill/restore cycle
# (replica deaths, failovers, restores), the full breaker cycle, the
# p99 before/during/after windows, and zero-loss + bit-identity.
FLEET_ARTIFACT_FIELDS = (
    "p50_ms",
    "p99_ms",
    "throughput_rps",
    "n_requests",
    "n_served",
)

_FLEET_BLOCK_FIELDS = (
    "n_replicas",
    "failovers",
    "replica_deaths",
    "restores",
    "zero_lost",
    "p99_before_ms",
    "p99_during_ms",
    "p99_after_ms",
    "breaker_cycle",
    "per_replica",
    "brownout",
    "health_transitions",
)


def validate_fleet_artifact(record):
    """Problems with a fleet-mode BENCH artifact, as a list of strings.

    Fleet legs carry no numpy baseline (nothing is raced) but must
    carry the full manifest, the fleet-wide latency/QPS block, and a
    coherent ``fleet`` drill block: at least one replica killed and
    restored, its breaker showing the full open → half-open → closed
    cycle, a per-replica QPS table covering the whole fleet,
    ``zero_lost`` True and a clean bit-identity audit — a failover
    drill that dropped or corrupted a request is a correctness bug,
    not an availability result. Serving-family sibling of
    `validate_serve_artifact` / `validate_vis_artifact`.
    """
    problems = validate_artifact(record, require_baseline=False)
    for field in FLEET_ARTIFACT_FIELDS:
        if field not in record:
            problems.append(f"missing fleet field {field!r}")
    p50, p99 = record.get("p50_ms"), record.get("p99_ms")
    if (
        isinstance(p50, (int, float))
        and isinstance(p99, (int, float))
        and p99 < p50
    ):
        problems.append(f"p99_ms {p99} < p50_ms {p50}")
    bit = record.get("bit_identical")
    if not isinstance(bit, dict) or not (
        {"checked", "mismatches"} <= set(bit)
    ):
        problems.append(
            "missing bit_identical {checked, mismatches} block"
        )
    elif bit["mismatches"]:
        problems.append(
            f"bit-identity audit failed: {bit['mismatches']} "
            f"mismatch(es) in {bit['checked']} checked"
        )
    fleet = record.get("fleet")
    if not isinstance(fleet, dict):
        problems.append("missing fleet block")
        return problems
    for field in _FLEET_BLOCK_FIELDS:
        if field not in fleet:
            problems.append(f"fleet block missing {field!r}")
    n = fleet.get("n_replicas")
    if isinstance(n, int) and n < 2:
        problems.append(
            f"n_replicas {n} < 2 (a one-replica fleet cannot fail over)"
        )
    if isinstance(fleet.get("replica_deaths"), int):
        if fleet["replica_deaths"] < 1:
            problems.append("fleet drill killed no replica")
    if isinstance(fleet.get("restores"), int) and fleet["restores"] < 1:
        problems.append("fleet drill restored no replica")
    if fleet.get("zero_lost") is not True:
        problems.append(
            f"zero_lost is {fleet.get('zero_lost')!r}: the drill must "
            "complete every admitted request"
        )
    cycle = fleet.get("breaker_cycle")
    if isinstance(cycle, list):
        missing = {"open", "half_open", "closed"} - set(cycle)
        if missing:
            problems.append(
                f"breaker cycle {cycle} missing state(s) "
                f"{sorted(missing)} — the victim's breaker must open, "
                "half-open and close in the artifact"
            )
    per = fleet.get("per_replica")
    if isinstance(per, list):
        if isinstance(n, int) and len(per) != n:
            problems.append(
                f"per_replica has {len(per)} row(s) for {n} replicas"
            )
        for row in per:
            if not isinstance(row, dict) or not (
                {"id", "served", "qps"} <= set(row)
            ):
                problems.append(
                    "per_replica rows need {id, served, qps}"
                )
                break
    for field in ("p99_before_ms", "p99_during_ms", "p99_after_ms"):
        v = fleet.get(field)
        if v is not None and (
            not isinstance(v, (int, float)) or v < 0
        ):
            problems.append(f"{field} {v!r} is not a latency")
    problems.extend(_validate_cache_block(record, fleet))
    return problems


# The shared-cache-fabric block a fabric-backed `bench.py --fleet`
# artifact carries (`cache.SharedStreamTier.stats` plus the QPS
# equivalence audit) — the fabric's schema contract: exactly ONE
# resident stream copy, a coherent hit/miss ledger, and per-view rows.
CACHE_ARTIFACT_FIELDS = (
    "resident_stream_copies",
    "stream_version",
    "views",
    "l1_hits",
    "l2_hits",
    "misses",
    "hit_ratio",
    "dedup_hits",
    "per_view",
)


def _validate_cache_block(record, fleet):
    """Problems with a fleet artifact's ``cache`` (fabric) block. The
    block is optional — pre-fabric fleet artifacts validate as before —
    but when present it must show one resident stream copy and a
    coherent hit ledger, and the fleet block must agree."""
    cache = record.get("cache")
    if cache is None:
        return []
    problems = []
    if not isinstance(cache, dict):
        return ["cache block is not a dict"]
    for field in CACHE_ARTIFACT_FIELDS:
        if field not in cache:
            problems.append(f"cache block missing {field!r}")
    copies = cache.get("resident_stream_copies")
    if copies is not None and copies != 1:
        problems.append(
            f"resident_stream_copies is {copies!r}: the fabric's whole "
            "contract is ONE resident stream across the fleet"
        )
    fleet_copies = fleet.get("stream_copies")
    if fleet_copies is not None and copies == 1 and fleet_copies != 1:
        problems.append(
            f"fleet.stream_copies {fleet_copies!r} disagrees with the "
            "cache block's one resident copy"
        )
    ratio = cache.get("hit_ratio")
    if ratio is not None and (
        not isinstance(ratio, (int, float)) or not 0.0 <= ratio <= 1.0
    ):
        problems.append(f"hit_ratio {ratio!r} is not in [0, 1]")
    for field in ("l1_hits", "l2_hits", "misses", "dedup_hits"):
        v = cache.get(field)
        if v is not None and (not isinstance(v, int) or v < 0):
            problems.append(f"cache {field} {v!r} is not a count")
    served = (
        cache.get("l1_hits", 0) + cache.get("l2_hits", 0)
        + cache.get("misses", 0)
    )
    if isinstance(ratio, (int, float)) and served == 0 and ratio:
        problems.append(
            f"hit_ratio {ratio} with an empty hit/miss ledger"
        )
    per_view = cache.get("per_view")
    if isinstance(per_view, list):
        views = cache.get("views")
        if isinstance(views, int) and len(per_view) != views:
            problems.append(
                f"per_view has {len(per_view)} row(s) for "
                f"{views} view(s)"
            )
        for row in per_view:
            if not isinstance(row, dict) or not (
                {"replica", "l1_hits", "l2_hits"} <= set(row)
            ):
                problems.append(
                    "per_view rows need {replica, l1_hits, l2_hits}"
                )
                break
    return problems


PROCFLEET_ARTIFACT_FIELDS = (
    "p50_ms",
    "p99_ms",
    "throughput_rps",
    "n_requests",
    "n_served",
)

_PROCFLEET_BLOCK_FIELDS = (
    "n_workers",
    "worker_deaths",
    "restarts",
    "failovers",
    "lost_requests",
    "failover_ms",
    "breaker_cycle",
    "per_worker",
    "health_transitions",
    "orphans",
    "mid_l2_kill",
    "wire",
    "telemetry",
    "clock_offsets",
    "trace_merge",
    "black_box",
)


def validate_procfleet_artifact(record):
    """Problems with a process-fleet BENCH artifact (``--procfleet``),
    as a list of strings.

    The process drill's contract is the thread fleet's, survived for
    real: at least one worker ``SIGKILL``ed and restarted, its breaker
    showing the full open → half-open → closed cycle, ``lost_requests``
    exactly 0, ``failover_ms`` a real measurement, a clean bit-identity
    audit, a ``mid_l2_kill`` phase that landed its kill inside an L2
    read and still served the row bit-identically, and a ``wire`` block
    whose heartbeats actually flowed (a drill whose leases never beat
    proved nothing).

    The distributed observability plane extends the contract: a
    ``fleet_telemetry`` block whose cross-process totals sum exactly
    (`obs.tower.validate_fleet_telemetry_artifact`), a ``telemetry``
    block with frames flowing and coverage in [0, 1], HELLO-estimated
    ``clock_offsets`` with their RTT uncertainty, a ``trace_merge``
    summary proving one timeline across ≥2 processes, and a
    ``black_box`` block showing an exhumed worker's own events folded
    into the parent's post-mortem. Per-worker heartbeat payloads
    (``last_stats``) are schema-checked: a worker shipping garbage
    stats trips the validator, not a downstream dashboard.
    """
    problems = validate_artifact(record, require_baseline=False)
    for field in PROCFLEET_ARTIFACT_FIELDS:
        if field not in record:
            problems.append(f"missing procfleet field {field!r}")
    p50, p99 = record.get("p50_ms"), record.get("p99_ms")
    if (
        isinstance(p50, (int, float))
        and isinstance(p99, (int, float))
        and p99 < p50
    ):
        problems.append(f"p99_ms {p99} < p50_ms {p50}")
    bit = record.get("bit_identical")
    if not isinstance(bit, dict) or not (
        {"checked", "mismatches"} <= set(bit)
    ):
        problems.append(
            "missing bit_identical {checked, mismatches} block"
        )
    elif bit["mismatches"]:
        problems.append(
            f"bit-identity audit failed: {bit['mismatches']} "
            f"mismatch(es) in {bit['checked']} checked"
        )
    pf = record.get("procfleet")
    if not isinstance(pf, dict):
        problems.append("missing procfleet block")
        return problems
    for field in _PROCFLEET_BLOCK_FIELDS:
        if field not in pf:
            problems.append(f"procfleet block missing {field!r}")
    n = pf.get("n_workers")
    if isinstance(n, int) and n < 2:
        problems.append(
            f"n_workers {n} < 2 (a one-worker fleet cannot fail over)"
        )
    if isinstance(pf.get("worker_deaths"), int) and pf["worker_deaths"] < 1:
        problems.append("procfleet drill killed no worker")
    if isinstance(pf.get("restarts"), int) and pf["restarts"] < 1:
        problems.append("procfleet drill restarted no worker")
    if pf.get("lost_requests") != 0:
        problems.append(
            f"lost_requests is {pf.get('lost_requests')!r}: the drill "
            "must complete every admitted request"
        )
    fo = pf.get("failover_ms")
    if not isinstance(fo, (int, float)) or fo < 0:
        problems.append(
            f"failover_ms {fo!r} is not a measured failover latency"
        )
    cycle = pf.get("breaker_cycle")
    if isinstance(cycle, list):
        missing = {"open", "half_open", "closed"} - set(cycle)
        if missing:
            problems.append(
                f"breaker cycle {cycle} missing state(s) "
                f"{sorted(missing)} — the victim's breaker must open, "
                "half-open and close in the artifact"
            )
    per = pf.get("per_worker")
    if isinstance(per, list):
        if isinstance(n, int) and len(per) != n:
            problems.append(
                f"per_worker has {len(per)} row(s) for {n} workers"
            )
        for row in per:
            if not isinstance(row, dict) or not (
                {"id", "served", "qps"} <= set(row)
            ):
                problems.append("per_worker rows need {id, served, qps}")
                break
        for row in per:
            if not isinstance(row, dict):
                continue
            stats = row.get("last_stats")
            if stats is None:
                continue  # a worker that never beat has no payload
            if not isinstance(stats, dict):
                problems.append(
                    f"per_worker[{row.get('id')!r}].last_stats is "
                    f"{type(stats).__name__}, expected a heartbeat dict"
                )
                continue
            for counter in ("beats", "served", "pending"):
                v = stats.get(counter)
                if not isinstance(v, int) or v < 0:
                    problems.append(
                        f"per_worker[{row.get('id')!r}].last_stats."
                        f"{counter} = {v!r} is not a counter"
                    )
    orphans = pf.get("orphans")
    if orphans is not None:
        if not isinstance(orphans, dict) or not (
            {"orphans_reaped", "stale_sockets_swept"} <= set(orphans)
        ):
            problems.append(
                "orphans block needs {orphans_reaped, stale_sockets_swept}"
            )
    l2 = pf.get("mid_l2_kill")
    if not isinstance(l2, dict) or not (
        {"killed_mid_read", "row_bit_identical"} <= set(l2)
    ):
        problems.append(
            "missing mid_l2_kill {killed_mid_read, row_bit_identical} "
            "block"
        )
    else:
        if l2.get("killed_mid_read") is not True:
            problems.append(
                "mid_l2_kill phase never landed its kill inside an L2 "
                "read"
            )
        if l2.get("row_bit_identical") is not True:
            problems.append(
                "mid_l2_kill phase observed a torn or stale row "
                "cross-process"
            )
    wire = pf.get("wire")
    if wire is not None:
        if not isinstance(wire, dict) or not isinstance(
            wire.get("heartbeats"), int
        ):
            problems.append("wire block needs a heartbeats count")
        elif wire["heartbeats"] < 1:
            problems.append(
                "wire block shows no heartbeats — leases never beat "
                "on the wire"
            )
    # -- distributed observability plane --------------------------------
    if "fleet_telemetry" in record:
        from .tower import validate_fleet_telemetry_artifact

        problems.extend(validate_fleet_telemetry_artifact(record))
    else:
        problems.append(
            "missing fleet_telemetry block — the fleet ran without "
            "its cross-process telemetry plane"
        )
    tel = pf.get("telemetry")
    if tel is not None:
        if not isinstance(tel, dict):
            problems.append("procfleet telemetry block is not a dict")
        else:
            frames = tel.get("frames")
            if not isinstance(frames, int) or frames < 1:
                problems.append(
                    f"telemetry.frames {frames!r}: no TELEMETRY frame "
                    "ever crossed the wire"
                )
            zombies = tel.get("zombie_frames")
            if not isinstance(zombies, int) or zombies < 0:
                problems.append(
                    f"telemetry.zombie_frames {zombies!r} is not a count"
                )
            cov = tel.get("coverage")
            if not isinstance(cov, (int, float)) or not 0.0 <= cov <= 1.0:
                problems.append(
                    f"telemetry.coverage {cov!r} is not in [0, 1]"
                )
    offs = pf.get("clock_offsets")
    if offs is not None:
        if not isinstance(offs, dict) or not offs:
            problems.append(
                "clock_offsets is empty — no HELLO exchange estimated "
                "a worker clock"
            )
        else:
            for rid, off in offs.items():
                if not isinstance(off, dict) or not isinstance(
                    off.get("offset_s"), (int, float)
                ):
                    problems.append(
                        f"clock_offsets[{rid!r}] has no offset_s number"
                    )
                    continue
                rtt = off.get("rtt_s")
                if not isinstance(rtt, (int, float)) or rtt < 0:
                    problems.append(
                        f"clock_offsets[{rid!r}].rtt_s {rtt!r} is not "
                        "a non-negative uncertainty"
                    )
    tm = pf.get("trace_merge")
    if tm is not None:
        if not isinstance(tm, dict):
            problems.append("trace_merge block is not a dict")
        else:
            nproc = tm.get("n_processes")
            if not isinstance(nproc, int) or nproc < 2:
                problems.append(
                    f"trace_merge.n_processes {nproc!r} < 2 — one "
                    "process is not a merged timeline"
                )
            pids = tm.get("pids")
            if not isinstance(pids, list) or (
                isinstance(nproc, int) and len(pids) != nproc
            ):
                problems.append(
                    f"trace_merge.pids {pids!r} does not list "
                    f"{nproc!r} process(es)"
                )
            xreq = tm.get("cross_process_requests")
            if not isinstance(xreq, int) or xreq < 1:
                problems.append(
                    f"trace_merge.cross_process_requests {xreq!r}: no "
                    "request span crossed a process boundary"
                )
    bb = pf.get("black_box")
    if bb is not None:
        if not isinstance(bb, dict):
            problems.append("black_box block is not a dict")
        else:
            exhumed = bb.get("exhumed")
            if not isinstance(exhumed, list) or not exhumed:
                problems.append(
                    "black_box.exhumed is empty — no dead worker's "
                    "ring was recovered"
                )
            else:
                for i, box in enumerate(exhumed):
                    if not isinstance(box, dict) or not (
                        {"rid", "generation", "n_events"} <= set(box)
                    ):
                        problems.append(
                            f"black_box.exhumed[{i}] needs "
                            "{rid, generation, n_events}"
                        )
            if bb.get("victim_events_in_post_mortem") is not True:
                problems.append(
                    "black_box: the victim's own events never reached "
                    "the parent's post-mortem"
                )
    return problems


# The compiled-plan block streamed/roundtrip bench artifacts stamp
# (`swiftly_tpu.plan.Plan.artifact_block`) — the plan compiler's schema
# contract: which inputs were priced (hash), the chosen pass grid /
# spill policy / serve shapes, and predicted vs measured wall so a
# mispriced model (future bad plans) is visible in the artifact itself.
PLAN_ARTIFACT_FIELDS = (
    "inputs_hash",
    "mode",
    "backward",
    "spill",
    "serve",
    "mesh",
    "predicted",
    "coeffs_source",
)

_PLAN_BACKWARD_FIELDS = (
    "n_passes", "n_facet_passes", "n_row_slabs", "fold_group",
    "feed_group", "n_feeds", "resident_bytes",
)

_PLAN_SPILL_MODES = ("none", "ram", "disk", "replay")


def validate_plan_artifact(record):
    """Problems with an artifact's ``plan_compiled`` block, as strings.

    The block must carry the pricing-inputs hash, a coherent backward
    pass grid (``n_passes == n_facet_passes * n_row_slabs``), a known
    spill mode, ascending serve bucket shapes, numeric predicted
    wall/HBM peak, and a coefficient pedigree — so a plan nobody can
    reprice (or a grid that disagrees with itself) fails in seconds on
    CPU instead of silently producing bad plans later.
    """
    problems = []
    block = record.get("plan_compiled")
    if not isinstance(block, dict):
        return ["missing plan_compiled block"]
    for field in PLAN_ARTIFACT_FIELDS:
        if field not in block:
            problems.append(f"plan_compiled missing {field!r}")
    if not block.get("inputs_hash"):
        problems.append("plan_compiled inputs_hash is empty")
    bwd = block.get("backward")
    if isinstance(bwd, dict):
        for field in _PLAN_BACKWARD_FIELDS:
            if field not in bwd:
                problems.append(f"plan backward block missing {field!r}")
        n, nf, nr = (
            bwd.get("n_passes"), bwd.get("n_facet_passes"),
            bwd.get("n_row_slabs"),
        )
        if (
            all(isinstance(v, int) for v in (n, nf, nr))
            and n != nf * nr
        ):
            problems.append(
                f"plan pass grid incoherent: {n} passes != "
                f"{nf} facet passes x {nr} row slabs"
            )
        # feed-once/fold-many schedule coherence: q in [1, n_passes]
        # and n_feeds == ceil(n_passes / q) — a schedule that disagrees
        # with its own grid would mis-size every feed's residency
        q, nfe = bwd.get("feed_group"), bwd.get("n_feeds")
        if all(isinstance(v, int) for v in (n, q, nfe)):
            if not (1 <= q <= max(1, n)):
                problems.append(
                    f"plan feed_group {q} outside [1, {n}] passes"
                )
            elif nfe != -(-n // q):
                problems.append(
                    f"plan feed schedule incoherent: {nfe} feeds != "
                    f"ceil({n} passes / {q} per feed)"
                )
    elif "backward" in block:
        problems.append("plan backward block is not a dict")
    spill = block.get("spill")
    if isinstance(spill, dict):
        if spill.get("mode") not in _PLAN_SPILL_MODES:
            problems.append(
                f"plan spill mode {spill.get('mode')!r} not in "
                f"{_PLAN_SPILL_MODES}"
            )
    serve = block.get("serve")
    if isinstance(serve, dict):
        buckets = serve.get("bucket_sizes")
        if not isinstance(buckets, list) or not buckets or any(
            b2 <= b1 for b1, b2 in zip(buckets, buckets[1:])
        ):
            problems.append(
                f"plan serve bucket_sizes {buckets!r} is not an "
                "ascending non-empty list"
            )
    pred = block.get("predicted")
    if isinstance(pred, dict):
        for field in ("wall_s", "hbm_peak_bytes"):
            v = pred.get(field)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(
                    f"plan predicted.{field} {v!r} is not a "
                    "non-negative number"
                )
    elif "predicted" in block:
        problems.append("plan predicted block is not a dict")
    if "measured_wall_s" in block and not isinstance(
        block["measured_wall_s"], (int, float)
    ):
        problems.append(
            f"plan measured_wall_s {block['measured_wall_s']!r} is "
            "not a number"
        )
    if block.get("coeffs_source") not in (
        None, "default", "measured", "ledger"
    ):
        problems.append(
            f"plan coeffs_source {block.get('coeffs_source')!r} not "
            "default|measured|ledger"
        )
    mesh = block.get("mesh")
    if isinstance(mesh, dict):
        if mesh.get("status") not in _PLAN_MESH_STATUSES:
            problems.append(
                f"plan mesh status {mesh.get('status')!r} not in "
                f"{_PLAN_MESH_STATUSES}"
            )
        shards = mesh.get("facet_shards")
        if isinstance(shards, int) and shards < 1:
            problems.append(f"plan mesh facet_shards {shards} < 1")
    elif "mesh" in block:
        problems.append("plan mesh block is not a dict")
    return problems


# "stub": the compiler planned a layout no executor consumed (incl. the
# trivial single-device layout); "bound": the mesh-streamed engine
# executed it (swiftly_tpu.mesh flips the status at construction).
_PLAN_MESH_STATUSES = ("stub", "bound")


# The mesh block every `bench.py --mesh` artifact must carry — the
# mesh-streamed drill's schema contract: the layout that ran (shards,
# padding), the cross-device traffic, scaling vs the single-chip
# engine, and the reduction-order match audit.
MESH_ARTIFACT_FIELDS = (
    "n_devices",
    "facet_shards",
    "padded_facets",
    "collective_bytes",
    "single_chip_wall_s",
    "mesh_wall_s",
    "scaling_efficiency",
    "match",
    "hlo",
)

# The mesh block of a `bench.py --mesh --chaos` artifact: the chaos
# drill races nothing (no single-chip leg, no HLO audit — those are the
# scaling leg's contract); its match audit is the BIT-identity of the
# recovered run vs the undisturbed mesh run, and it must carry the
# recovery block below.
MESH_CHAOS_ARTIFACT_FIELDS = (
    "n_devices",
    "facet_shards",
    "padded_facets",
    "collective_bytes",
    "match",
    "recovery",
)

# The `mesh.recovery` block schema — the elastic-recovery drill's
# contract: what was lost, what the survivors re-planned to (priced by
# the plan compiler, not guessed), whether the checkpoint migrated
# across layouts, how long the ladder took (`recovery_wall_s`, and
# `recovery_overhead` = disturbed/undisturbed wall ratio — the
# bench_compare sentinel), and whether the resumed result stayed
# bit-identical.
MESH_RECOVERY_FIELDS = (
    "events",
    "shards_before",
    "shards_after",
    "replanned",
    "migrated",
    "subgrids_migrated",
    "watchdog",
    "recovery_wall_s",
    "recovery_overhead",
    "bit_identical",
)


def _mesh_recovery_problems(recovery):
    """Schema problems with one `mesh.recovery` block."""
    if not isinstance(recovery, dict):
        return ["mesh recovery block is not a dict"]
    problems = []
    for field in MESH_RECOVERY_FIELDS:
        if field not in recovery:
            problems.append(f"mesh recovery block missing {field!r}")
    events = recovery.get("events")
    if isinstance(events, int) and events < 1:
        problems.append(
            "mesh recovery drill recovered from no shard loss"
        )
    before = recovery.get("shards_before")
    after = recovery.get("shards_after")
    if (
        isinstance(before, int) and isinstance(after, int)
        and not (1 <= after < before)
    ):
        problems.append(
            f"recovery shards {before} -> {after} did not shrink to a "
            "surviving layout"
        )
    replanned = recovery.get("replanned")
    if isinstance(replanned, dict):
        if (
            isinstance(after, int)
            and replanned.get("facet_shards") not in (None, after)
        ):
            problems.append(
                f"re-planned layout shards "
                f"{replanned.get('facet_shards')} != surviving "
                f"shard count {after}"
            )
    elif "replanned" in recovery:
        problems.append(
            "recovery replanned block is not a layout dict — the "
            "survivor layout must come from the plan compiler, not "
            "be guessed"
        )
    if recovery.get("migrated") is not True:
        problems.append(
            "recovery did not migrate a checkpoint across layouts"
        )
    if not isinstance(recovery.get("watchdog"), dict):
        problems.append("recovery watchdog block is not a dict")
    for field in ("recovery_wall_s", "recovery_overhead"):
        v = recovery.get(field)
        if v is not None and (
            not isinstance(v, (int, float)) or v <= 0
        ):
            problems.append(f"recovery {field} {v!r} is not positive")
    if recovery.get("bit_identical") is not True:
        problems.append(
            f"recovery bit_identical is "
            f"{recovery.get('bit_identical')!r}; the recovered stream "
            "must equal the undisturbed run exactly"
        )
    return problems


def validate_mesh_artifact(record):
    """Problems with a mesh-mode BENCH artifact, as a list of strings.

    Mesh legs carry no numpy baseline (the single-chip streamed engine
    is the reference, recorded in the block itself) but must carry the
    full manifest plus a coherent ``mesh`` block: a real multi-shard
    layout (>= 2 facet shards — a one-shard "mesh" proves nothing), the
    padded facet count a multiple of the shard count, non-negative
    collective bytes, a positive scaling_efficiency, a match audit
    whose max |diff| sits inside the stamped reduction-order tolerance,
    an HLO audit showing >= 1 facet-axis all-reduce in the lowered
    streamed stage, and ``plan_compiled.mesh.status == "bound"`` — a
    mesh drill whose plan nothing consumed, or whose results drifted
    past tolerance, is a correctness bug, not a scaling result.

    A ``mesh.recovery`` block switches the schema to the elastic
    recovery drill's (``bench.py --mesh --chaos``): the scaling-leg
    fields (single-chip wall, scaling_efficiency, hlo) are not
    required, but the recovery block must be coherent — >= 1 recovery
    event, shards genuinely shrunk, a re-planned survivor layout whose
    shard count matches, a checkpoint migration, positive recovery
    wall/overhead, and ``bit_identical`` True (the recovered stream
    must equal the undisturbed run EXACTLY; a drifted recovery is a
    correctness bug, not a resilience result).
    """
    problems = validate_artifact(record, require_baseline=False)
    mesh = record.get("mesh")
    if not isinstance(mesh, dict):
        problems.append("missing mesh block")
        return problems
    recovery = mesh.get("recovery")
    required = (
        MESH_ARTIFACT_FIELDS if recovery is None
        else MESH_CHAOS_ARTIFACT_FIELDS
    )
    for field in required:
        if field not in mesh:
            problems.append(f"mesh block missing {field!r}")
    if recovery is not None:
        problems.extend(_mesh_recovery_problems(recovery))
    shards = mesh.get("facet_shards")
    if isinstance(shards, int) and shards < 2:
        problems.append(
            f"facet_shards {shards} < 2 (a one-shard mesh leg "
            "exercises no collective)"
        )
    padded = mesh.get("padded_facets")
    if (
        isinstance(shards, int) and shards >= 1
        and isinstance(padded, int) and padded % shards
    ):
        problems.append(
            f"padded_facets {padded} is not a multiple of "
            f"facet_shards {shards}"
        )
    cb = mesh.get("collective_bytes")
    if cb is not None and (not isinstance(cb, (int, float)) or cb < 0):
        problems.append(f"collective_bytes {cb!r} is not a byte count")
    se = mesh.get("scaling_efficiency")
    if se is not None and (not isinstance(se, (int, float)) or se <= 0):
        problems.append(
            f"scaling_efficiency {se!r} is not a positive number"
        )
    match = mesh.get("match")
    if not isinstance(match, dict) or not (
        {"max_abs_diff", "tolerance", "within_tolerance"} <= set(match)
    ):
        problems.append(
            "missing match {max_abs_diff, tolerance, within_tolerance} "
            "block"
        )
    else:
        if match.get("within_tolerance") is not True:
            problems.append(
                f"mesh result outside the reduction-order tolerance: "
                f"{match}"
            )
        mad, tol = match.get("max_abs_diff"), match.get("tolerance")
        if (
            isinstance(mad, (int, float))
            and isinstance(tol, (int, float))
            and mad > tol
        ):
            problems.append(
                f"match max_abs_diff {mad} > tolerance {tol} but "
                "within_tolerance claims otherwise"
            )
    hlo = mesh.get("hlo")
    if isinstance(hlo, dict):
        # the HLO audit proves the EXECUTED collective matches the
        # schedule: psum lowers to a facet-axis all-reduce, ring to the
        # 2(n-1) collective-permute pipeline (and must NOT silently
        # fall back to all-reduce)
        if mesh.get("collective") == "ring":
            if not hlo.get("collective_permute"):
                problems.append(
                    "ring collective requested but lowered streamed "
                    "stage shows no collective-permute pipeline"
                )
        elif not hlo.get("all_reduce"):
            problems.append(
                "lowered streamed stage shows no facet-axis all-reduce"
            )
    elif "hlo" in mesh:
        problems.append("mesh hlo block is not a dict")
    pc = record.get("plan_compiled")
    if isinstance(pc, dict):
        status = (pc.get("mesh") or {}).get("status")
        if status != "bound":
            problems.append(
                f"plan_compiled.mesh.status {status!r} != 'bound' — "
                "the engine must consume the compiled layout"
            )
    else:
        problems.append("mesh artifact missing plan_compiled block")
    return problems


# The resilience block every `bench.py --chaos` artifact must carry —
# the chaos drill's schema contract (what was injected, what survived,
# how the run degraded, and whether the killed-and-resumed output is
# bit-identical to the undisturbed run).
RESILIENCE_ARTIFACT_FIELDS = (
    "faults_injected",
    "faults_injected_total",
    "faults_survived",
    "retries",
    "degradations",
    "resume_count",
    "bit_identical",
)


def validate_resilience_artifact(record):
    """Problems with a chaos-mode BENCH artifact, as a list of strings.

    Chaos legs carry no numpy baseline (nothing is being raced) but must
    carry the full manifest plus a coherent ``resilience`` block: at
    least one fault injected, every fault survived or resumed past, a
    resume count >= 1 (the drill kills mid-run by contract), the
    degradation trail as a list, and ``bit_identical`` True — a chaos
    drill whose output drifted is a correctness bug, not a resilience
    result.
    """
    problems = validate_artifact(record, require_baseline=False)
    res = record.get("resilience")
    if not isinstance(res, dict):
        problems.append("missing resilience block")
        return problems
    for field in RESILIENCE_ARTIFACT_FIELDS:
        if field not in res:
            problems.append(f"resilience block missing {field!r}")
    injected = res.get("faults_injected")
    if injected is not None and not isinstance(injected, dict):
        problems.append(
            f"faults_injected is {type(injected).__name__}, expected "
            "a site -> count dict"
        )
    elif isinstance(injected, dict):
        total = res.get("faults_injected_total")
        if isinstance(total, int) and total != sum(injected.values()):
            problems.append(
                f"faults_injected_total {total} != sum of by-site "
                f"counts {sum(injected.values())}"
            )
    if isinstance(res.get("faults_injected_total"), int):
        if res["faults_injected_total"] < 1:
            problems.append("chaos drill injected no faults")
    if not isinstance(res.get("degradations"), list):
        problems.append("degradations is not a list")
    rc = res.get("resume_count")
    if isinstance(rc, int) and rc < 1:
        problems.append("resume_count < 1 (the drill must kill+resume)")
    if res.get("bit_identical") is not True:
        problems.append(
            f"bit_identical is {res.get('bit_identical')!r}, the "
            "resumed run must match the undisturbed run exactly"
        )
    return problems


# The delta block every `bench.py --delta` artifact must carry — the
# incremental-update drill's schema contract (which facets moved, how
# many cached columns were patched, the patch-vs-full speedup, and the
# audit that the patched stream matches a fresh full recompute).
DELTA_ARTIFACT_FIELDS = (
    "changed_facets",
    "patched_columns",
    "speedup_vs_full",
    "max_abs_diff",
    "plan",
)


def validate_delta_artifact(record):
    """Problems with a delta-mode BENCH artifact, as a list of strings.

    Delta legs carry no numpy baseline (the full re-record of the same
    engine is the reference, timed in the block itself) but must carry
    the full manifest plus a coherent ``delta`` block: at least one
    changed facet, at least one patched column, a positive
    speedup_vs_full, a match audit whose max |diff| sits inside the
    stamped f32 sum-reorder tolerance, and a ``plan`` block whose mode
    names the path actually taken (``"patch"`` or ``"full"``) — a delta
    drill whose patched stream drifted past tolerance is a correctness
    bug, not a speedup result.
    """
    problems = validate_artifact(record, require_baseline=False)
    delta = record.get("delta")
    if not isinstance(delta, dict):
        problems.append("missing delta block")
        return problems
    for field in DELTA_ARTIFACT_FIELDS:
        if field not in delta:
            problems.append(f"delta block missing {field!r}")
    changed = delta.get("changed_facets")
    if isinstance(changed, list) and not changed:
        problems.append("delta drill changed no facets")
    elif changed is not None and not isinstance(changed, list):
        problems.append(
            f"changed_facets is {type(changed).__name__}, expected a "
            "facet-index list"
        )
    pc = delta.get("patched_columns")
    if isinstance(pc, int) and pc < 1 and not delta.get("exact_mode"):
        # SWIFTLY_DELTA_EXACT=1 legs replay instead of patching —
        # zero patched columns is the contract there, not a failure
        problems.append("delta drill patched no cached columns")
    sp = delta.get("speedup_vs_full")
    if sp is not None and (not isinstance(sp, (int, float)) or sp <= 0):
        problems.append(
            f"speedup_vs_full {sp!r} is not a positive number"
        )
    match = delta.get("match")
    if not isinstance(match, dict) or not (
        {"max_abs_diff", "tolerance", "within_tolerance"} <= set(match)
    ):
        problems.append(
            "missing match {max_abs_diff, tolerance, within_tolerance} "
            "block"
        )
    else:
        if match.get("within_tolerance") is not True:
            problems.append(
                f"patched stream outside the f32 sum-reorder "
                f"tolerance: {match}"
            )
        mad, tol = match.get("max_abs_diff"), match.get("tolerance")
        if (
            isinstance(mad, (int, float))
            and isinstance(tol, (int, float))
            and mad > tol
        ):
            problems.append(
                f"match max_abs_diff {mad} > tolerance {tol} but "
                "within_tolerance claims otherwise"
            )
    plan = delta.get("plan")
    if isinstance(plan, dict):
        mode = plan.get("mode")
        if mode not in ("patch", "full"):
            problems.append(
                f"delta plan mode {mode!r} not in ('patch', 'full')"
            )
    elif plan is not None:
        problems.append("delta plan block is not a dict")
    return problems
