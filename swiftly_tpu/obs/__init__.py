"""Structured run telemetry: metrics, provenance, progress.

The reference pipeline reads its visibility off Dask's performance
reports and worker transfer logs (reference scripts/utils.py:166-231);
this package is the TPU port's equivalent substrate, designed so every
perf artifact this repo emits is *measured, attributed and auditable*:

* ``obs.metrics`` — a near-zero-overhead metrics registry (counters,
  gauges, stage timers with min/mean/max/p99). Disabled (the default)
  every instrumentation site costs one attribute check; enabled, each
  stage pairs a host wall-clock timer with a
  ``jax.profiler.TraceAnnotation`` of the SAME name, so Perfetto traces
  and host metrics index by one stage vocabulary. Optional JSONL event
  log + dict export with per-stage analytic FLOPs/MFU.
* ``obs.manifest`` — the run-provenance record (device kind, SWIFTLY_*
  env knobs, git SHA, config hash, ``baseline_source``) stamped into
  every BENCH artifact, plus the artifact schema validator the
  ``bench.py --smoke`` leg runs.
* ``obs.trace`` — the hierarchical span tracer (run → bench leg →
  pass → column group → stage; serve request journeys on per-request
  tracks; HBM watermarks at span boundaries), exporting Chrome
  trace-event JSON loadable in Perfetto. Same one-attribute-check
  discipline when disabled; every ``metrics.stage`` site doubles as a
  trace site through the bridge.
* ``obs.report`` — trace analysis: span trees, critical-path/self-time
  attribution (``scripts/trace_report.py``), journey decomposition,
  and the ``trace`` artifact-block schema check.
* ``obs.heartbeat`` — progress reporting for hour-scale runs
  (units/s, ETA) and incremental partial-artifact flushing so a killed
  run still leaves its finished legs on disk.
* ``obs.recorder`` — the always-on flight recorder: a bounded
  lock-light ring of fleet events (faults, ladder steps, breaker/lease
  flips, autoscale decisions, cache rolls) kept even with tracing OFF,
  dumped as a post-mortem bundle on `WorkerKilled`/`ShardLostError`/
  forced drain/SLO breach. ``SWIFTLY_RECORDER=1`` /
  ``SWIFTLY_RECORDER_SECONDS``.
* ``obs.tower`` — the fleet control tower: named telemetry sources
  merged into one ``fleet_telemetry`` block (per-replica breakdowns +
  fleet totals), windowed signals shared by the brownout ladder and
  autoscaler, and declarative SLOs evaluated with multi-window
  burn-rate rules into an ``alerts`` block.

Enable via ``SWIFTLY_METRICS=1`` (JSONL path in
``SWIFTLY_METRICS_JSONL``) / ``SWIFTLY_TRACE=1`` (Chrome JSON in
``SWIFTLY_TRACE_PATH``) or programmatically with
``metrics.enable(...)`` / ``trace.enable(path)``. See
docs/observability.md.
"""

from . import ledger, metrics, recorder, report, tower, trace
from .heartbeat import Heartbeat, PartialArtifactWriter
from .ledger import validate_plan_accuracy_artifact
from .manifest import (
    run_manifest,
    validate_artifact,
    validate_delta_artifact,
    validate_fleet_artifact,
    validate_mesh_artifact,
    validate_plan_artifact,
    validate_procfleet_artifact,
    validate_resilience_artifact,
    validate_serve_artifact,
    validate_vis_artifact,
)
from .report import (
    by_process,
    merge_traces,
    summarize_trace,
    validate_trace_artifact,
)
from .tower import (
    SLO,
    ControlTower,
    validate_alerts_artifact,
    validate_fleet_telemetry_artifact,
)

__all__ = [
    "ControlTower",
    "Heartbeat",
    "PartialArtifactWriter",
    "SLO",
    "by_process",
    "ledger",
    "merge_traces",
    "metrics",
    "recorder",
    "report",
    "run_manifest",
    "summarize_trace",
    "tower",
    "trace",
    "validate_alerts_artifact",
    "validate_artifact",
    "validate_delta_artifact",
    "validate_fleet_artifact",
    "validate_fleet_telemetry_artifact",
    "validate_mesh_artifact",
    "validate_plan_accuracy_artifact",
    "validate_plan_artifact",
    "validate_procfleet_artifact",
    "validate_resilience_artifact",
    "validate_serve_artifact",
    "validate_trace_artifact",
    "validate_vis_artifact",
]
