"""Structured run telemetry: metrics, provenance, progress.

The reference pipeline reads its visibility off Dask's performance
reports and worker transfer logs (reference scripts/utils.py:166-231);
this package is the TPU port's equivalent substrate, designed so every
perf artifact this repo emits is *measured, attributed and auditable*:

* ``obs.metrics`` — a near-zero-overhead metrics registry (counters,
  gauges, stage timers with min/mean/max/p99). Disabled (the default)
  every instrumentation site costs one attribute check; enabled, each
  stage pairs a host wall-clock timer with a
  ``jax.profiler.TraceAnnotation`` of the SAME name, so Perfetto traces
  and host metrics index by one stage vocabulary. Optional JSONL event
  log + dict export with per-stage analytic FLOPs/MFU.
* ``obs.manifest`` — the run-provenance record (device kind, SWIFTLY_*
  env knobs, git SHA, config hash, ``baseline_source``) stamped into
  every BENCH artifact, plus the artifact schema validator the
  ``bench.py --smoke`` leg runs.
* ``obs.heartbeat`` — progress reporting for hour-scale runs
  (units/s, ETA) and incremental partial-artifact flushing so a killed
  run still leaves its finished legs on disk.

Enable via ``SWIFTLY_METRICS=1`` (JSONL path in
``SWIFTLY_METRICS_JSONL``) or programmatically with
``metrics.enable(...)``. See docs/observability.md.
"""

from . import metrics
from .heartbeat import Heartbeat, PartialArtifactWriter
from .manifest import (
    run_manifest,
    validate_artifact,
    validate_resilience_artifact,
    validate_serve_artifact,
)

__all__ = [
    "Heartbeat",
    "PartialArtifactWriter",
    "metrics",
    "run_manifest",
    "validate_artifact",
    "validate_resilience_artifact",
    "validate_serve_artifact",
]
