"""Always-on flight recorder: the last N seconds of fleet events,
kept even when full tracing is OFF.

The chaos and fleet drills showed the gap: when a replica dies or an
SLO burns, the *interesting* events (the fault injection, the ladder
steps, the breaker flips, the autoscale decisions) happened seconds
before the trigger — and unless a full trace was running, they are
gone. The recorder is the black box for that window:

* **Bounded and lock-light.** A fixed-size ``collections.deque``
  (``maxlen`` evicts oldest) of pre-formatted event tuples
  ``(t, kind, name, detail)``. ``deque.append`` is atomic in CPython,
  so the hot recording path takes NO lock: one enabled check, one
  ``perf_counter`` read, one tuple, one append — well under the
  5 us/event budget asserted by tests/test_trace.py, and cheap enough
  to leave ON for every drill (and production serve run).
* **Zero cost off.** Disabled (the library default), every hook is one
  attribute check — the ``obs.metrics`` discipline. Drills enable it
  by default (``SWIFTLY_RECORDER=0`` opts out); ``SWIFTLY_RECORDER=1``
  turns it on for any run.
* **Post-mortem bundles.** On a trigger (`WorkerKilled`,
  `ShardLostError`, a forced drain, an SLO breach) `post_mortem`
  snapshots the last ``SWIFTLY_RECORDER_SECONDS`` (default 60) of
  events into a JSON-ready bundle — trigger, per-kind counts, the
  event tail — and `dump` writes it as JSONL plus a rendered ``.txt``
  summary, the artifact every drill now stamps.

Event kinds recorded by the built-in hooks: ``stage`` (via the
``metrics.stage`` bridge), ``fault`` (injections), ``degrade`` (ladder
steps), ``breaker`` / ``lease`` (transitions), ``autoscale`` and
``fleet`` (scale/drain/brownout decisions), ``cache`` (version rolls),
``mesh`` (recovery phases), ``alert`` (SLO open/close). See
docs/observability.md ("Control tower").
"""

from __future__ import annotations

import collections
import json
import os
import time

__all__ = [
    "FlightRecorder",
    "disable",
    "dump",
    "enable",
    "enabled",
    "events",
    "get_recorder",
    "post_mortem",
    "record",
    "reset",
]

_DEFAULT_EVENTS = 32768   # ring capacity (tuples — a few MB at worst)
_DEFAULT_SECONDS = 60.0   # post-mortem lookback window


class FlightRecorder:
    """The bounded event ring; a no-op unless enabled.

    One process-wide instance (``get_recorder()``) serves the engine;
    independent instances are constructible for tests.

    :param capacity: ring size in events (oldest evicted beyond it)
    :param seconds: post-mortem lookback window in seconds
    """

    def __init__(self, enabled=False, capacity=_DEFAULT_EVENTS,
                 seconds=_DEFAULT_SECONDS):
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self.seconds = float(seconds)
        self._ring = collections.deque(maxlen=self.capacity)
        self._t0 = time.perf_counter()
        self._t_epoch = time.time()
        self.dumps = 0

    # -- lifecycle ---------------------------------------------------------

    def enable(self, seconds=None):
        if seconds is not None:
            self.seconds = float(seconds)
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False

    def reset(self):
        self._ring.clear()
        self._t0 = time.perf_counter()
        self._t_epoch = time.time()
        self.dumps = 0

    # -- recording ---------------------------------------------------------

    def record(self, kind, name, detail=None):
        """Append one pre-formatted event. The hot path: enabled check,
        clock read, tuple, atomic append — no lock, no string work
        beyond what the caller already paid."""
        if not self.enabled:
            return
        self._ring.append(
            (time.perf_counter() - self._t0, kind, name, detail)
        )

    # -- export ------------------------------------------------------------

    def events(self, seconds=None):
        """JSON-ready events from the last ``seconds`` (default: the
        configured window), oldest first."""
        window = self.seconds if seconds is None else float(seconds)
        cutoff = (time.perf_counter() - self._t0) - window
        return [
            {"t": round(t, 6), "kind": kind, "name": name,
             "detail": detail}
            for (t, kind, name, detail) in list(self._ring)
            if t >= cutoff
        ]

    def events_since(self, t_watermark):
        """JSON-ready events recorded after ``t_watermark`` (a relative
        ``t`` from a previous event, or ``-1.0`` for everything), plus
        the new watermark: ``(events, watermark)``. The incremental
        export the process-fleet black-box flusher drains the ring with
        — each flush ships only what the last one did not."""
        out = []
        last = t_watermark
        for (t, kind, name, detail) in list(self._ring):
            if t > t_watermark:
                out.append({"t": round(t, 6), "kind": kind,
                            "name": name, "detail": detail})
                last = t  # raw clock value: rounding must not re-emit
        return out, last

    def post_mortem(self, trigger, reason=None, seconds=None):
        """The JSON-ready bundle for one trigger: the recorded window,
        per-kind counts, and the non-stage event tail (the readable
        story — stage events dominate by volume, decisions by value)."""
        evs = self.events(seconds)
        by_kind = {}
        for e in evs:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
        tail = [e for e in evs if e["kind"] != "stage"][-64:]
        return {
            "trigger": str(trigger),
            "reason": None if reason is None else str(reason),
            "t_epoch": self._t_epoch,
            "window_s": self.seconds if seconds is None else seconds,
            "n_events": len(evs),
            "by_kind": by_kind,
            "events": tail,
        }

    def dump(self, path, trigger, reason=None, seconds=None):
        """Write the post-mortem bundle: ``path`` gets one JSONL line
        per event (header line first), ``path + ".txt"`` the rendered
        summary. Returns the bundle dict (what drills stamp into their
        artifact)."""
        bundle = self.post_mortem(trigger, reason=reason,
                                  seconds=seconds)
        evs = self.events(seconds)
        with open(path, "w") as fh:
            header = {k: v for k, v in bundle.items() if k != "events"}
            fh.write(json.dumps({"kind": "post_mortem", **header}) + "\n")
            for e in evs:
                fh.write(json.dumps(e) + "\n")
        with open(str(path) + ".txt", "w") as fh:
            fh.write(render_post_mortem(bundle))
        self.dumps += 1
        return bundle


class _RecorderStage:
    """The recorder-only stage timer: what ``metrics.stage`` returns
    when the registry and tracer are both off but the recorder is on.
    One clock read each side of the block plus one ring append — the
    <5 us/event contract tests/test_trace.py asserts."""

    __slots__ = ("name", "flops", "bytes_moved", "_t0")

    def __init__(self, name):
        self.name = name
        self.flops = 0
        self.bytes_moved = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        _RECORDER.record("stage", self.name, round(t1 - self._t0, 6))
        return False


def render_post_mortem(bundle):
    """A human-readable rendering of one post-mortem bundle."""
    lines = [
        f"post-mortem: {bundle['trigger']}"
        + (f" ({bundle['reason']})" if bundle.get("reason") else ""),
        f"  window {bundle['window_s']}s, "
        f"{bundle['n_events']} recorded event(s)",
        "  by kind: "
        + (
            ", ".join(
                f"{k}={n}" for k, n in sorted(bundle["by_kind"].items())
            )
            or "none"
        ),
        "  last events:",
    ]
    for e in bundle["events"]:
        detail = f"  {e['detail']}" if e.get("detail") else ""
        lines.append(
            f"    t={e['t']:>10.4f}  {e['kind']:<10} {e['name']}{detail}"
        )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The process-wide recorder + module-level conveniences (the engine's
# hook API: `from ..obs import recorder` ... `recorder.record(...)`).
# ---------------------------------------------------------------------------

_RECORDER = FlightRecorder(
    enabled=os.environ.get("SWIFTLY_RECORDER", "0") not in ("", "0"),
    seconds=float(os.environ.get("SWIFTLY_RECORDER_SECONDS")
                  or _DEFAULT_SECONDS),
)


def get_recorder() -> FlightRecorder:
    return _RECORDER


def enabled() -> bool:
    return _RECORDER.enabled


def enable(seconds=None):
    return _RECORDER.enable(seconds)


def disable():
    _RECORDER.disable()


def reset():
    _RECORDER.reset()


def record(kind, name, detail=None):
    # keep the disabled path shallow: one attribute check in record()
    _RECORDER.record(kind, name, detail)


def events(seconds=None):
    return _RECORDER.events(seconds)


def post_mortem(trigger, reason=None, seconds=None):
    return _RECORDER.post_mortem(trigger, reason=reason,
                                 seconds=seconds)


def dump(path, trigger, reason=None, seconds=None):
    return _RECORDER.dump(path, trigger, reason=reason,
                          seconds=seconds)
