"""Plan-accuracy ledger: per-stage predicted-vs-measured reconciliation.

The plan compiler prices every stage of a run (`plan.predicted.stages`)
and the metrics registry times the matching runtime stages — but until
this module the only reconciliation between the two was ONE whole-leg
``predicted_vs_measured`` ratio. The re-anchor warning stands: every
perf gain since PR 5 is plan-priced and CPU-interpret-validated only,
so the first real TPU session must be able to answer, stage by stage,
"where was the model wrong, and by how much?" from artifacts alone.

Three pieces close that loop:

* **The stage-name mapping** — `PLAN_STAGE_TIMERS` names, for every
  plan-priced stage, the runtime timer(s) whose measured wall is its
  counterpart; `EXEMPT_STAGE_TIMERS` lists every runtime timer that is
  deliberately OUTSIDE the priced model, each with its reason. The
  contract is total: a timer in neither table is drift
  (`unmapped_stage_names`, guarded by tests/test_plan_ledger.py — a
  new ``_metrics.stage`` site cannot silently fall out of the ledger).
* **The ``plan_accuracy`` artifact block** — `plan_accuracy_block`
  joins a stamped ``plan_compiled`` block against the leg's
  ``telemetry`` export: per-stage predicted/measured walls and their
  ratio (predicted / measured — **> 1 means the plan over-predicted**,
  the run beat the price; < 1 means the plan was optimistic), the
  coverage fraction of predicted stage wall that has a measured
  counterpart, and the uncovered stages BY NAME — no silent gaps.
  Every block appends to a persisted calibration history
  (JSONL, `append_history`) keyed by inputs-hash, geometry, platform
  and git SHA, so drift ACROSS runs is first-class; `plan.autotune`
  refits per-stage coefficients from that history with
  ``source="ledger"`` provenance (`refit_from_ledger`).
* **The drift alarm** — `register_plan_accuracy_source` wires the
  latest block into a `obs.tower.ControlTower` as a ``plan_accuracy``
  source plus a ``plan.mispricing_drift`` signal with a burn-rate SLO;
  `record_mispricing` lands ``plan.mispriced`` flight-recorder events
  (and a post-mortem dump) when a CALIBRATED stage misprices beyond
  threshold. Default-coefficient blocks are reported, never alarmed —
  a CPU smoke racing TPU-anchored defaults is a category error.

See docs/planning.md (Calibration) and docs/observability.md.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import math
import os
import time

__all__ = [
    "CALIBRATED_SOURCES",
    "EXEMPT_STAGE_TIMERS",
    "PLAN_ACCURACY_SCHEMA",
    "PLAN_STAGE_TIMERS",
    "append_history",
    "history_path",
    "load_calibration_history",
    "mapped_timer_names",
    "mispriced_stages",
    "mispricing_drift",
    "plan_accuracy_block",
    "record_mispricing",
    "register_plan_accuracy_source",
    "round_sig",
    "stage_accuracy",
    "unmapped_stage_names",
    "validate_plan_accuracy_artifact",
]

logger = logging.getLogger(__name__)

PLAN_ACCURACY_SCHEMA = "swiftly-tpu-plan-accuracy/1"

# Coefficient pedigrees that make a prediction a CONTRACT rather than a
# ranking anchor: "measured" (plan.autotune.refit over raw telemetry)
# and "ledger" (refit_from_ledger over accumulated plan_accuracy
# history). Only calibrated blocks can alarm.
CALIBRATED_SOURCES = ("measured", "ledger")

# Every plan-priced stage name -> the runtime timer(s) whose measured
# wall is its counterpart. A priced stage may fan out to several timers
# (the executor picks a body per geometry: the grouped column pass
# records ``fwd.column_pass``, the facet-slab streaming path records
# ``fwd.slab_step`` — both are the SAME priced work); the join sums
# whichever of them fired. Keys must cover everything
# `plan.model.price_forward` / `price_backward` / the compiler's
# ``mesh.psum`` pricing can emit — tests/test_plan_ledger.py compiles
# plans and asserts it.
PLAN_STAGE_TIMERS = {
    "fwd.sampled_facet_pass": ("fwd.sampled_facet_pass", "fwd.facet_pass"),
    "fwd.column_pass": ("fwd.column_pass", "fwd.slab_step"),
    "fwd.column_pass.pallas": ("fwd.column_pass.pallas", "fwd.slab_step"),
    "bwd.column_pass": ("bwd.column_pass",),
    "bwd.column_pass.pallas": ("bwd.column_pass.pallas",),
    "bwd.sampled_fold": ("bwd.sampled_fold",),
    "spill.write": ("spill.write",),
    "bwd.feed_group": ("bwd.feed_group",),
    "fwd.replay": ("fwd.replay",),
    "mesh.psum": ("mesh.psum",),
    "mesh.ring_step": ("mesh.ring_step",),
    # visibility serving (plan.vis.price_vis): every stage records
    # under its priced name (the row fetch's hit/miss tier split is
    # blended into one priced wall at the expected hit rate)
    "vis.degrid": ("vis.degrid",),
    "vis.grid": ("vis.grid",),
    "vis.row_fetch": ("vis.row_fetch",),
}

# Runtime timers deliberately OUTSIDE the priced model, each with its
# reason — the other half of the total-mapping contract. Anything the
# engine times that is in neither table is drift and fails the guard.
EXEMPT_STAGE_TIMERS = {
    "fwd.h2d": "facet upload inside the column pass's overlap window; "
               "priced into the stage's effective rate, not separately",
    "fwd.d2h": "subgrid drain hidden behind compute by the double "
               "buffer; part of the column stage's effective rate",
    "fwd.drain": "end-of-stream flush of in-flight buffers (bounded "
                 "tail, not steady-state work)",
    "fwd.facet_upload": "one-time facet-stack upload (setup, amortized "
                        "over the whole run)",
    "fwd.slab_prefetch": "async slab h2d the slab compute hides; the "
                         "exposed part surfaces in fwd.slab_step",
    "fwd.slab_upload": "synchronous slab upload fallback (setup path)",
    "fwd.group_finish": "column-group boundary bookkeeping",
    "spill.read": "cache read the feed prefetch hides; the exposed "
                  "feed wall is priced as bwd.feed_group",
    "spill.h2d": "cache h2d dispatch inside the feed's overlap window; "
                 "priced as bwd.feed_group traffic",
    "bwd.drain": "backward end-of-stream flush (bounded tail)",
    "bwd.ct_fold": "sub-stage of the priced backward column pass; "
                   "mapping it too would double-count the wall",
    "bwd.fft_fold": "sub-stage of the priced adjoint fold (fft "
                    "residency variant); same double-count hazard",
    "bwd.finish": "final per-facet finish, paid once per pass outside "
                  "the steady-state price",
    "bwd.facet_pass": "legacy full-residency backward body (not the "
                      "sampled path the plan prices)",
    "bwd.d2h": "result download after the fold (bounded tail)",
}


def mapped_timer_names():
    """Every runtime timer name some plan-priced stage claims."""
    names = set()
    for timers in PLAN_STAGE_TIMERS.values():
        names.update(timers)
    return names


def unmapped_stage_names(names):
    """The runtime timer names in ``names`` that are neither mapped to
    a plan-priced stage nor on the documented exemption list — i.e.
    ledger drift. The stage-contract guard asserts this is empty over
    every ``_metrics.stage``/``observe`` site in ``parallel/`` and
    ``mesh/``."""
    known = mapped_timer_names() | set(EXEMPT_STAGE_TIMERS)
    return sorted(set(names) - known)


def round_sig(value, sig=4):
    """Round to ``sig`` significant figures (NOT decimal places).

    ``round(x, 4)`` zeroed sub-0.1 ms walls — a smoke leg's 3.2e-5 s
    stage became 0.0 and every downstream ratio silently vanished.
    Sig-fig rounding keeps small walls comparable at any scale."""
    v = float(value)
    if v == 0.0 or not math.isfinite(v):
        return v
    return round(v, int(sig) - 1 - int(math.floor(math.log10(abs(v)))))


# ---------------------------------------------------------------------------
# The join
# ---------------------------------------------------------------------------


def stage_accuracy(plan_block, telemetry):
    """Join one plan's predicted stage walls against measured timers.

    :param plan_block: a stamped ``plan_compiled`` artifact block
    :param telemetry: the leg's ``metrics.export()`` block
    :return: ``(stages, uncovered, totals)`` — per-plan-stage entries
        (predicted/measured walls, ``ratio = predicted / measured``,
        the timers joined, the analytic flops/bytes the refit divides),
        the priced stages with NO measured counterpart, and the wall
        totals the coverage fraction is computed from
    """
    predicted = ((plan_block or {}).get("predicted") or {}).get(
        "stages"
    ) or {}
    measured = (telemetry or {}).get("stages") or {}
    stages = {}
    uncovered = []
    total_pred = covered_pred = total_meas = 0.0
    for name, cost in predicted.items():
        cost = cost if isinstance(cost, dict) else {}
        pred_wall = float(cost.get("wall_s") or 0.0)
        timers = PLAN_STAGE_TIMERS.get(name)
        entry = {
            "predicted_wall_s": round_sig(pred_wall),
            "timers": list(timers) if timers else [],
        }
        if timers is None:
            entry["unmapped"] = True
        for key in ("flops", "bytes", "dispatches"):
            if cost.get(key):
                entry[key] = cost[key]
        meas_wall = 0.0
        count = 0
        fired = []
        for timer in timers or ():
            m = measured.get(timer)
            if isinstance(m, dict) and (m.get("total_s") or 0) > 0:
                meas_wall += float(m["total_s"])
                count += int(m.get("count") or 0)
                fired.append(timer)
        total_pred += pred_wall
        if meas_wall > 0:
            entry["measured_wall_s"] = round_sig(meas_wall)
            entry["measured_timers"] = fired
            entry["count"] = count
            covered_pred += pred_wall
            total_meas += meas_wall
            if pred_wall > 0:
                entry["ratio"] = round_sig(pred_wall / meas_wall)
        else:
            uncovered.append(name)
        stages[name] = entry
    totals = {
        "predicted_stage_wall_s": round_sig(total_pred),
        "measured_stage_wall_s": round_sig(total_meas),
        "coverage": round(
            covered_pred / total_pred if total_pred > 0 else 0.0, 4
        ),
    }
    return stages, uncovered, totals


def plan_accuracy_block(plan_block, telemetry, manifest=None):
    """The validated ``plan_accuracy`` artifact block one run stamps.

    Keyed for the calibration history: inputs-hash + config (geometry
    identity), platform + git SHA (provenance), coefficient pedigree.
    ``stages[*].ratio`` is predicted / measured — > 1 is an
    OVER-prediction (the run beat the price), < 1 an optimistic plan.
    """
    plan_block = plan_block or {}
    manifest = manifest or {}
    stages, uncovered, totals = stage_accuracy(plan_block, telemetry)
    return {
        "schema": PLAN_ACCURACY_SCHEMA,
        "t_epoch": round(time.time(), 3),
        "inputs_hash": plan_block.get("inputs_hash"),
        "config": plan_block.get("config"),
        "mode": plan_block.get("mode"),
        "coeffs_source": plan_block.get("coeffs_source") or "default",
        "platform": (manifest.get("device") or {}).get("platform"),
        "git_sha": manifest.get("git_sha"),
        "stages": stages,
        "uncovered": uncovered,
        **totals,
    }


def validate_plan_accuracy_artifact(record):
    """Problems with an artifact's ``plan_accuracy`` block, as strings.

    Accepts the full BENCH record (reads ``record["plan_accuracy"]``)
    or a bare block. The no-silent-gaps rule is schema: every priced
    stage without a measured wall MUST be listed in ``uncovered``,
    coverage must be a [0, 1] fraction, and a measured stage with a
    positive prediction must carry its ratio.
    """
    block = record
    if isinstance(record, dict) and "plan_accuracy" in record:
        block = record.get("plan_accuracy")
    if not isinstance(block, dict):
        return ["missing plan_accuracy block"]
    problems = []
    if block.get("schema") != PLAN_ACCURACY_SCHEMA:
        problems.append(
            f"plan_accuracy schema {block.get('schema')!r} != "
            f"{PLAN_ACCURACY_SCHEMA!r}"
        )
    for field in ("inputs_hash", "mode", "coeffs_source"):
        if not block.get(field):
            problems.append(f"plan_accuracy missing {field!r}")
    if block.get("coeffs_source") not in (
        None, "default", *CALIBRATED_SOURCES
    ):
        problems.append(
            f"plan_accuracy coeffs_source {block.get('coeffs_source')!r}"
            " not default|measured|ledger"
        )
    coverage = block.get("coverage")
    if not isinstance(coverage, (int, float)) or not (
        0.0 <= coverage <= 1.0
    ):
        problems.append(
            f"plan_accuracy coverage {coverage!r} is not a [0, 1] "
            "fraction"
        )
    stages = block.get("stages")
    uncovered = block.get("uncovered")
    if not isinstance(uncovered, list):
        problems.append("plan_accuracy uncovered is not a list")
        uncovered = []
    if not isinstance(stages, dict) or not stages:
        problems.append("plan_accuracy stages is not a non-empty dict")
        return problems
    for name, entry in stages.items():
        if not isinstance(entry, dict):
            problems.append(f"plan_accuracy stage {name} is not a dict")
            continue
        pred = entry.get("predicted_wall_s")
        if not isinstance(pred, (int, float)) or pred < 0:
            problems.append(
                f"plan_accuracy stage {name} predicted_wall_s {pred!r} "
                "is not a non-negative number"
            )
        meas = entry.get("measured_wall_s")
        if meas is None:
            if name not in uncovered:
                problems.append(
                    f"plan_accuracy stage {name} has no measured wall "
                    "but is not listed uncovered (silent gap)"
                )
            continue
        if not isinstance(meas, (int, float)) or meas <= 0:
            problems.append(
                f"plan_accuracy stage {name} measured_wall_s {meas!r} "
                "is not a positive number"
            )
        elif (
            isinstance(pred, (int, float)) and pred > 0
            and not isinstance(entry.get("ratio"), (int, float))
        ):
            problems.append(
                f"plan_accuracy stage {name} has both walls but no "
                "ratio"
            )
        if name in uncovered:
            problems.append(
                f"plan_accuracy stage {name} is measured AND listed "
                "uncovered"
            )
    for name in uncovered:
        if name not in stages:
            problems.append(
                f"plan_accuracy uncovered stage {name} not in stages"
            )
    return problems


# ---------------------------------------------------------------------------
# Calibration history (JSONL)
# ---------------------------------------------------------------------------

DEFAULT_HISTORY_PATH = "BENCH_calibration.jsonl"


def history_path(default=DEFAULT_HISTORY_PATH):
    """Where the calibration history accumulates:
    ``SWIFTLY_CALIBRATION_HISTORY`` (``0`` disables → None), else
    ``BENCH_calibration.jsonl`` next to the other artifacts."""
    env = os.environ.get("SWIFTLY_CALIBRATION_HISTORY")
    if env == "0":
        return None
    return env or default


def append_history(block, path=None):
    """Append one ``plan_accuracy`` block to the JSONL history; returns
    the path written (None when history is disabled)."""
    path = history_path() if path is None else path
    if not path:
        return None
    with open(path, "a") as fh:
        fh.write(json.dumps(block, sort_keys=True) + "\n")
    return path


def load_calibration_history(patterns=None):
    """Every ``plan_accuracy`` block from JSONL history file(s).

    :param patterns: path/glob strings (or one string); default the
        `history_path` file
    """
    if patterns is None:
        patterns = [history_path() or DEFAULT_HISTORY_PATH]
    if isinstance(patterns, (str, bytes)):
        patterns = [patterns]
    blocks = []
    for pattern in patterns:
        for path in sorted(_glob.glob(str(pattern))):
            try:
                text = open(path).read()
            except OSError as exc:
                logger.warning("ledger: cannot read %s: %s", path, exc)
                continue
            for line in text.splitlines():
                if not line.strip():
                    continue
                try:
                    data = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("ledger: bad JSONL line in %s", path)
                    continue
                if (
                    isinstance(data, dict)
                    and data.get("schema") == PLAN_ACCURACY_SCHEMA
                ):
                    blocks.append(data)
    return blocks


# ---------------------------------------------------------------------------
# Drift signal, tower source, flight-recorder hook
# ---------------------------------------------------------------------------


def mispriced_stages(block, threshold=2.0):
    """``[(stage, ratio), ...]`` whose predicted/measured ratio leaves
    ``[1/threshold, threshold]`` — regardless of pedigree (callers gate
    on `CALIBRATED_SOURCES` where only contracts may alarm)."""
    out = []
    for name, entry in ((block or {}).get("stages") or {}).items():
        ratio = entry.get("ratio") if isinstance(entry, dict) else None
        if (
            isinstance(ratio, (int, float)) and ratio > 0
            and not (1.0 / threshold <= ratio <= threshold)
        ):
            out.append((name, ratio))
    return out


def mispricing_drift(block):
    """The worst per-stage mispricing factor, symmetric in direction:
    ``max over stages of max(ratio, 1/ratio)`` — 1.0 is a perfect
    price, 2.0 means some stage is off 2x either way. 1.0 with no
    joined stages (nothing to misprice yet)."""
    worst = 1.0
    for name, entry in ((block or {}).get("stages") or {}).items():
        ratio = entry.get("ratio") if isinstance(entry, dict) else None
        if isinstance(ratio, (int, float)) and ratio > 0:
            worst = max(worst, ratio, 1.0 / ratio)
    return worst


def register_plan_accuracy_source(tower, provider, threshold=2.0,
                                  fast_s=1.0, slow_s=5.0, burn=0.5):
    """Wire the ledger into a control tower.

    Registers a ``plan_accuracy`` source (coverage, pedigree, drift and
    the stage counters the fleet totals sum), a
    ``plan.mispricing_drift`` signal (the `mispricing_drift` factor of
    the CURRENT block — pinned to 1.0 for uncalibrated blocks, which
    must never alarm), and a ``plan_mispricing`` burn-rate SLO at
    ``threshold``.

    :param tower: an `obs.tower.ControlTower`
    :param provider: callable returning the latest ``plan_accuracy``
        block (or None before the first run)
    """
    from .tower import SLO

    def _block():
        try:
            return provider() or {}
        except Exception:  # noqa: BLE001 - a source must not kill ticks
            return {}

    def source():
        block = _block()
        stages = block.get("stages") or {}
        uncovered = block.get("uncovered") or []
        bad = mispriced_stages(block, threshold)
        return {
            "coeffs_source": block.get("coeffs_source"),
            "calibrated": (
                block.get("coeffs_source") in CALIBRATED_SOURCES
            ),
            "coverage": block.get("coverage"),
            "mispricing_drift": round(mispricing_drift(block), 4),
            "mispriced": [name for name, _r in bad],
            "counters": {
                "plan.stages_priced": len(stages),
                "plan.stages_covered": len(stages) - len(uncovered),
                "plan.stages_mispriced": len(bad),
            },
        }

    def signal():
        block = _block()
        if block.get("coeffs_source") not in CALIBRATED_SOURCES:
            return 1.0
        return mispricing_drift(block)

    tower.register_source("plan_accuracy", source, kind="plan")
    tower.register_signal("plan.mispricing_drift", signal)
    tower.add_slo(SLO(
        name="plan_mispricing", signal="plan.mispricing_drift",
        threshold=float(threshold), direction="above",
        fast_s=fast_s, slow_s=slow_s, burn=burn,
    ))


def record_mispricing(block, threshold=2.0, dump_path=None):
    """Flight-recorder trail for a mispriced CALIBRATED block.

    One ``plan.mispriced`` event per offending stage, plus a
    post-mortem bundle dump when ``dump_path`` is given. Uncalibrated
    blocks return ``[]`` untouched — a default-coefficient miss is a
    ranking anchor being wrong, not a broken contract.

    :return: the `mispriced_stages` list that was recorded
    """
    block = block or {}
    if block.get("coeffs_source") not in CALIBRATED_SOURCES:
        return []
    bad = mispriced_stages(block, threshold)
    if not bad:
        return []
    from . import recorder as _recorder

    for name, ratio in bad:
        _recorder.record(
            "plan", "plan.mispriced",
            f"{name} predicted/measured x{ratio:.3g} outside "
            f"[1/{threshold:g}, {threshold:g}] "
            f"({block.get('config')}, {block.get('coeffs_source')} "
            "coeffs)",
        )
    if dump_path:
        _recorder.dump(
            dump_path, trigger="PlanMispriced",
            reason=(
                f"{len(bad)} calibrated stage(s) mispriced beyond "
                f"x{threshold:g}: "
                + ", ".join(name for name, _r in bad)
            ),
        )
    return bad
