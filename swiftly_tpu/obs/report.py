"""Trace analysis: span trees, critical-path attribution, journey
decomposition, and the trace artifact schema check.

Consumes the Chrome trace-event JSON written by ``obs.trace`` (every
span an ``"X"`` event whose args carry ``span_id``/``parent_id``) and
answers the operator questions the raw timeline only shows visually:

* **critical path** — for a root span (a bench leg, a serve run), the
  dominant child chain and the top-k spans by aggregated *self time*
  (wall minus children). Self times partition the root's wall exactly,
  so the printed attribution always sums back to the leg wall — the
  invariant ``bench.py --smoke --trace`` asserts within 5%.
* **journey decomposition** — ``serve.journey.*`` segment totals
  (queue wait vs compute vs transfer share), the p99-outlier
  decomposition of the serving SLO harness.
* **HBM watermarks** — the max ``hbm_peak_bytes`` any span carried.

``summarize_trace`` builds the JSON block bench artifacts stamp as
``record["trace"]``; ``validate_trace_artifact`` is its schema guard
(the ``validate_serve_artifact`` twin); ``validate_trace_events`` is
the structural Chrome-format check (Perfetto-loadable or not) that
``scripts/trace_report.py`` and the tier-1 tests run.
"""

from __future__ import annotations

import json

__all__ = [
    "TRACE_ARTIFACT_FIELDS",
    "build_tree",
    "by_process",
    "by_source",
    "critical_path",
    "journey_stats",
    "load_trace",
    "merge_traces",
    "self_times",
    "summarize_trace",
    "validate_trace_artifact",
    "validate_trace_events",
]

TRACE_SCHEMA = "swiftly-tpu-trace/1"

# Per-process span-id namespace stride used by `merge_traces`: each
# non-base process's span ids are lifted into their own block so the
# merged timeline has ONE consistent id space (per-process tracers all
# start their id counters at 1).
MERGE_SPAN_NS = 1 << 24


def load_trace(path):
    """The Chrome trace dict at ``path`` (accepts the bare event list
    some tools emit, normalising to the object form)."""
    with open(path) as fh:
        data = json.load(fh)
    if isinstance(data, list):
        data = {"traceEvents": data}
    return data


def validate_trace_events(trace):
    """Structural problems with a Chrome trace dict (empty = loads in
    Perfetto): event list present, required per-phase fields, complete
    events with non-negative microsecond durations."""
    problems = []
    if not isinstance(trace, dict):
        return [f"trace is {type(trace).__name__}, expected dict"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["missing traceEvents list"]
    if not events:
        problems.append("traceEvents is empty")
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i} is {type(e).__name__}")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "I", "M", "b", "e", "B", "E", "C"):
            problems.append(f"event {i} has unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        for field in ("name", "pid", "tid", "ts"):
            if field not in e:
                problems.append(f"event {i} ({ph}) missing {field!r}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i} (X) has bad dur {dur!r}")
    return problems


def build_tree(trace):
    """Span records from a trace dict: ``{id: {name, cat, ts_s, dur_s,
    parent, children, args}}``. Spans whose parent never closed (or a
    cross-process import) are treated as roots."""
    spans = {}
    for e in trace.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid is None:
            continue
        spans[sid] = {
            "id": sid,
            "name": e.get("name", "?"),
            "cat": e.get("cat", ""),
            "tid": e.get("tid"),
            "ts_s": float(e.get("ts", 0.0)) / 1e6,
            "dur_s": float(e.get("dur", 0.0)) / 1e6,
            "parent": args.get("parent_id", 0) or 0,
            "children": [],
            "args": args,
        }
    for s in spans.values():
        if s["parent"] not in spans:
            s["parent"] = 0
    for s in spans.values():
        if s["parent"]:
            spans[s["parent"]]["children"].append(s["id"])
    return spans


def _subtree_ids(spans, root_id):
    out, stack = [], [root_id]
    while stack:
        sid = stack.pop()
        out.append(sid)
        stack.extend(spans[sid]["children"])
    return out


def self_times(spans):
    """Per-span self time (wall minus direct children's wall, clamped
    at 0 against clock jitter). Self times of a subtree sum to the
    root's wall exactly when no child overhangs its parent."""
    out = {}
    for sid, s in spans.items():
        child_wall = sum(spans[c]["dur_s"] for c in s["children"])
        out[sid] = max(0.0, s["dur_s"] - child_wall)
    return out


def _roots(spans, root_id=None):
    if root_id is not None:
        return [root_id] if root_id in spans else []
    return [sid for sid, s in spans.items() if not s["parent"]]


def critical_path(spans, root_id=None):
    """The dominant chain: from the longest root, repeatedly descend
    into the longest child. Returns ``[{name, dur_s, self_s}, ...]``
    root-first (sequential siblings are ALL on the critical path of a
    single-threaded trace — the chain names where the time is, the
    self-time table says how much each level keeps for itself)."""
    roots = _roots(spans, root_id)
    if not roots:
        return []
    selfs = self_times(spans)
    sid = max(roots, key=lambda r: spans[r]["dur_s"])
    chain = []
    while True:
        s = spans[sid]
        chain.append(
            {
                "name": s["name"],
                "dur_s": round(s["dur_s"], 6),
                "self_s": round(selfs[sid], 6),
            }
        )
        if not s["children"]:
            return chain
        sid = max(s["children"], key=lambda c: spans[c]["dur_s"])


def aggregate(spans, root_id=None):
    """Per-name aggregation over the (sub)tree: count, total wall,
    self wall, max HBM watermark. Sorted by self time, descending."""
    selfs = self_times(spans)
    if root_id is not None and root_id in spans:
        ids = _subtree_ids(spans, root_id)
    else:
        ids = list(spans)
    by_name = {}
    for sid in ids:
        s = spans[sid]
        a = by_name.setdefault(
            s["name"],
            {"name": s["name"], "count": 0, "total_s": 0.0,
             "self_s": 0.0, "hbm_peak_bytes": None},
        )
        a["count"] += 1
        a["total_s"] += s["dur_s"]
        a["self_s"] += selfs[sid]
        hbm = s["args"].get("hbm_peak_bytes")
        if hbm is not None:
            a["hbm_peak_bytes"] = max(a["hbm_peak_bytes"] or 0, int(hbm))
    out = sorted(by_name.values(), key=lambda a: -a["self_s"])
    for a in out:
        a["total_s"] = round(a["total_s"], 6)
        a["self_s"] = round(a["self_s"], 6)
    return out


def journey_stats(spans):
    """Serve request-journey decomposition from the ``serve.journey.*``
    segment spans: per-segment totals and the share of end-to-end
    request wall each claims (queue-wait share is the p99 postmortem
    headline). None when the trace holds no journeys."""
    segs = {}
    total = 0.0
    n = 0
    for s in spans.values():
        if s["name"] == "serve.journey":
            total += s["dur_s"]
            n += 1
        elif s["name"].startswith("serve.journey."):
            seg = s["name"].rsplit(".", 1)[1]
            segs[seg] = segs.get(seg, 0.0) + s["dur_s"]
    if not n:
        return None
    out = {"n_requests": n, "total_s": round(total, 6)}
    for seg, t in sorted(segs.items()):
        out[f"{seg}_s"] = round(t, 6)
        out[f"{seg}_share"] = round(t / total, 4) if total else 0.0
    return out


def summarize_trace(trace, root_id=None, top_k=5):
    """The JSON block bench artifacts stamp as ``record["trace"]``:
    span counts, the root wall, top-k self-time attribution, the
    critical-path chain, journey decomposition and the HBM peak."""
    spans = build_tree(trace)
    roots = _roots(spans, root_id)
    selfs = self_times(spans)
    if root_id is None and roots:
        root_id = max(roots, key=lambda r: spans[r]["dur_s"])
    wall = spans[root_id]["dur_s"] if root_id in spans else 0.0
    sub = set(_subtree_ids(spans, root_id)) if root_id in spans else set()
    attributed = sum(selfs[sid] for sid in sub)
    hbm = [
        int(s["args"]["hbm_peak_bytes"])
        for s in spans.values()
        if s["args"].get("hbm_peak_bytes") is not None
    ]
    out = {
        "schema": TRACE_SCHEMA,
        "span_count": len(spans),
        "event_count": sum(
            1 for e in trace.get("traceEvents", ())
            if e.get("ph") in ("i", "I")
        ),
        "root": spans[root_id]["name"] if root_id in spans else None,
        "wall_s": round(wall, 6),
        "attributed_s": round(attributed, 6),
        "critical_path": critical_path(spans, root_id),
        "top": aggregate(spans, root_id)[:top_k],
        "hbm_peak_bytes": max(hbm) if hbm else None,
    }
    journeys = journey_stats(spans)
    if journeys:
        out["journeys"] = journeys
    return out


def by_source(trace, top_k=5):
    """Per-source attribution: spans and instants grouped by Perfetto
    track (tid), each labelled with its ``"M"`` thread-name metadata —
    the fleet tracks `trace.name_track` registered (``replica-N``,
    ``fleet-supervisor``) plus the synthetic journey rows. Returns
    rows sorted by self time, busiest source first."""
    labels = {}
    for e in trace.get("traceEvents", ()):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            labels[e.get("tid")] = (e.get("args") or {}).get("name")
    spans = build_tree(trace)
    selfs = self_times(spans)
    groups = {}

    def group(tid):
        return groups.setdefault(tid, {
            "label": labels.get(tid) or f"tid {tid}",
            "spans": 0, "events": 0, "wall_s": 0.0, "self_s": 0.0,
            "stages": {},
        })

    for sid, s in spans.items():
        g = group(s["tid"])
        g["spans"] += 1
        g["wall_s"] += s["dur_s"]
        g["self_s"] += selfs[sid]
        st = g["stages"].setdefault(
            s["name"], {"count": 0, "self_s": 0.0}
        )
        st["count"] += 1
        st["self_s"] += selfs[sid]
    for e in trace.get("traceEvents", ()):
        if e.get("ph") in ("i", "I"):
            group(e.get("tid"))["events"] += 1
    rows = []
    for tid, g in sorted(
        groups.items(), key=lambda kv: -kv[1]["self_s"]
    ):
        top = sorted(
            g["stages"].items(), key=lambda kv: -kv[1]["self_s"]
        )[:top_k]
        rows.append({
            "tid": tid,
            "label": g["label"],
            "spans": g["spans"],
            "events": g["events"],
            "wall_s": round(g["wall_s"], 6),
            "self_s": round(g["self_s"], 6),
            "top": [
                {"name": n, "count": v["count"],
                 "self_s": round(v["self_s"], 6)}
                for n, v in top
            ],
        })
    return rows


def merge_traces(traces, offsets=None, labels=None):
    """ONE Perfetto timeline from per-process Chrome traces.

    ``traces[0]`` is the time base (the process-fleet router); every
    other trace's events are shifted onto its clock using the traces'
    ``otherData.t_epoch`` anchors corrected by ``offsets`` — the
    per-process wall-clock offsets the fleet estimated from the HELLO
    exchange (``{pid: {"offset_s": ..., "rtt_s": ...}}``, or a bare
    float per pid). A worker whose wall clock runs ``offset_s`` ahead
    of the router's has that much subtracted, so a request's
    router→worker→router journey lines up on one axis within the
    recorded RTT uncertainty.

    Span ids are namespaced per process (``MERGE_SPAN_NS`` stride, base
    trace unshifted) so `build_tree` sees one consistent id space, and
    worker spans carrying the fleet's cross-process trace context
    (``args.xparent`` + ``args.xpid``) are re-parented onto the
    originating process's span — the merged tree walks the hop.

    Returns a Chrome trace dict whose ``otherData`` records the base
    epoch, the merged pids, and the clock offsets applied.
    """
    traces = [t for t in traces if isinstance(t, dict)]
    if not traces:
        raise ValueError("merge_traces needs at least one trace")
    offsets = offsets or {}

    def _offset_s(pid):
        off = offsets.get(pid, offsets.get(str(pid), 0.0))
        if isinstance(off, dict):
            return float(off.get("offset_s", 0.0) or 0.0)
        return float(off or 0.0)

    def _pids(trace):
        return {
            e.get("pid") for e in trace.get("traceEvents", ())
            if isinstance(e, dict) and e.get("pid") is not None
        }

    base_epoch = float(
        (traces[0].get("otherData") or {}).get("t_epoch") or 0.0
    )
    # process index per trace: the base keeps index 0 (ids unshifted)
    pid_index = {}
    for i, trace in enumerate(traces):
        for pid in sorted(_pids(trace), key=str):
            pid_index.setdefault(pid, i)

    def _ns(pid, sid):
        if not sid:
            return 0
        return pid_index.get(pid, 0) * MERGE_SPAN_NS + int(sid)

    merged = []
    pids = []
    n_events = 0
    for i, trace in enumerate(traces):
        epoch = float(
            (trace.get("otherData") or {}).get("t_epoch") or base_epoch
        )
        trace_pids = _pids(trace)
        pids.extend(p for p in sorted(trace_pids, key=str)
                    if p not in pids)
        for e in trace.get("traceEvents", ()):
            if not isinstance(e, dict):
                continue
            e = dict(e)
            pid = e.get("pid")
            shift_us = (
                (epoch - _offset_s(pid) - base_epoch) * 1e6
                if i else 0.0
            )
            if "ts" in e:
                e["ts"] = round(float(e["ts"]) + shift_us, 3)
            if e.get("ph") == "X":
                args = dict(e.get("args") or {})
                sid = args.get("span_id")
                if sid is not None:
                    args["span_id"] = _ns(pid, sid)
                    xparent = args.get("xparent")
                    xpid = args.get("xpid")
                    if xparent and xpid in pid_index:
                        # the cross-process hop: adopt the originating
                        # process's span as the parent in the merged tree
                        args["parent_id"] = _ns(xpid, xparent)
                    else:
                        args["parent_id"] = _ns(
                            pid, args.get("parent_id", 0))
                e["args"] = args
            if e.get("ph") in ("i", "I"):
                n_events += 1
            merged.append(e)
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {
                "name": (labels or {}).get(
                    pid, (labels or {}).get(str(pid), f"pid {pid}")),
            },
        }
        for pid in pids
    ]
    clock = {}
    for pid in pids:
        off = offsets.get(pid, offsets.get(str(pid)))
        if off is None:
            continue
        if isinstance(off, dict):
            clock[str(pid)] = {
                "offset_s": float(off.get("offset_s", 0.0) or 0.0),
                "rtt_s": float(off.get("rtt_s", 0.0) or 0.0),
            }
        else:
            clock[str(pid)] = {"offset_s": float(off), "rtt_s": 0.0}
    return {
        "traceEvents": meta + merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": TRACE_SCHEMA,
            "t_epoch": base_epoch,
            "n_processes": len(pids),
            "pids": pids,
            "clock_offsets": clock,
            "n_spans": sum(
                1 for e in merged if e.get("ph") == "X"
            ),
            "n_events": n_events,
        },
    }


def by_process(trace, top_k=5):
    """Per-process attribution: spans and instants grouped by pid, each
    labelled with its ``"M"`` process-name metadata — the merged
    process-fleet timeline's router/worker rows. Returns rows sorted by
    self time, busiest process first (the `by_source` twin, one level
    up the hierarchy)."""
    labels = {}
    span_pid = {}
    for e in trace.get("traceEvents", ()):
        if not isinstance(e, dict):
            continue
        if e.get("ph") == "M" and e.get("name") == "process_name":
            labels[e.get("pid")] = (e.get("args") or {}).get("name")
        elif e.get("ph") == "X":
            sid = (e.get("args") or {}).get("span_id")
            if sid is not None:
                span_pid[sid] = e.get("pid")
    spans = build_tree(trace)
    selfs = self_times(spans)
    groups = {}

    def group(pid):
        return groups.setdefault(pid, {
            "label": labels.get(pid) or f"pid {pid}",
            "spans": 0, "events": 0, "wall_s": 0.0, "self_s": 0.0,
            "stages": {},
        })

    for sid, s in spans.items():
        g = group(span_pid.get(sid))
        g["spans"] += 1
        g["wall_s"] += s["dur_s"]
        g["self_s"] += selfs[sid]
        st = g["stages"].setdefault(
            s["name"], {"count": 0, "self_s": 0.0}
        )
        st["count"] += 1
        st["self_s"] += selfs[sid]
    for e in trace.get("traceEvents", ()):
        if isinstance(e, dict) and e.get("ph") in ("i", "I"):
            group(e.get("pid"))["events"] += 1
    rows = []
    for pid, g in sorted(
        groups.items(), key=lambda kv: -kv[1]["self_s"]
    ):
        top = sorted(
            g["stages"].items(), key=lambda kv: -kv[1]["self_s"]
        )[:top_k]
        rows.append({
            "pid": pid,
            "label": g["label"],
            "spans": g["spans"],
            "events": g["events"],
            "wall_s": round(g["wall_s"], 6),
            "self_s": round(g["self_s"], 6),
            "top": [
                {"name": n, "count": v["count"],
                 "self_s": round(v["self_s"], 6)}
                for n, v in top
            ],
        })
    return rows


# The block every ``--trace`` BENCH artifact must carry — the timeline's
# schema contract, guarded the same way validate_serve_artifact guards
# the SLO block.
TRACE_ARTIFACT_FIELDS = (
    "schema",
    "span_count",
    "wall_s",
    "attributed_s",
    "critical_path",
    "top",
)


def validate_trace_artifact(record):
    """Problems with a traced BENCH artifact, as a list of strings.

    The record must carry a ``trace`` block with recorded spans, a
    positive root wall, a non-empty critical path, and self-time
    attribution that sums back to the root wall within 5% — an
    attribution that doesn't cover the leg is a broken span tree, not
    a timeline.
    """
    problems = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    tr = record.get("trace")
    if not isinstance(tr, dict):
        return ["missing trace block"]
    for field in TRACE_ARTIFACT_FIELDS:
        if field not in tr:
            problems.append(f"trace block missing {field!r}")
    if tr.get("schema") not in (None, TRACE_SCHEMA):
        problems.append(
            f"trace schema {tr.get('schema')!r} != {TRACE_SCHEMA!r}"
        )
    sc = tr.get("span_count")
    if isinstance(sc, int) and sc < 1:
        problems.append("trace recorded no spans")
    wall = tr.get("wall_s")
    if isinstance(wall, (int, float)) and wall <= 0:
        problems.append(f"trace wall_s {wall!r} not positive")
    cp = tr.get("critical_path")
    if isinstance(cp, list):
        if not cp:
            problems.append("critical_path is empty")
        for k, entry in enumerate(cp):
            if not isinstance(entry, dict) or not (
                {"name", "dur_s", "self_s"} <= set(entry)
            ):
                problems.append(
                    f"critical_path[{k}] missing name/dur_s/self_s"
                )
    elif cp is not None:
        problems.append("critical_path is not a list")
    att = tr.get("attributed_s")
    if (
        isinstance(wall, (int, float))
        and isinstance(att, (int, float))
        and wall > 0
        and not (0.95 * wall <= att <= 1.05 * wall)
    ):
        problems.append(
            f"attributed self time {att} does not cover the root wall "
            f"{wall} within 5%"
        )
    return problems
