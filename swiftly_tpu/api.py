"""Streaming forward/backward API.

`SwiftlyForward` streams subgrids out of a set of facets; `SwiftlyBackward`
streams subgrids in and accumulates facets. Both bound their working set:

* prepared facets (`BF_Fs`) are computed once and reused for every subgrid;
* per-column intermediates are cached/accumulated in an LRU keyed by the
  subgrid column offset `off0` — forward recomputes on miss, backward folds
  the evicted column into the per-facet accumulators;
* a flight queue caps the number of in-flight device computations
  (JAX dispatch is asynchronous; the queue blocks on the oldest result,
  which is the TPU equivalent of the reference's Dask
  `TaskQueue`/`distributed.wait` backpressure, api.py:466-522).

Subgrids may be produced/consumed in any order — every accumulation is a
sum of linear contributions (the shuffle-order test relies on this).

API parity: reference SwiftlyForward/SwiftlyBackward
(/root/reference/src/ska_sdp_exec_swiftly/api.py:217-463), re-designed for
single-program batched execution over stacked facets.
"""

from __future__ import annotations

import logging
from collections import deque

import numpy as np

from .models.config import FacetConfig, SubgridConfig, SwiftlyConfig
from .obs import metrics as _metrics
from .models.covers import (
    make_full_facet_cover,
    make_full_subgrid_cover,
    make_sparse_facet_cover,
    sparse_fov_cover_offsets,
)
from .ops.oracle import make_facet_from_sources, make_subgrid_from_sources
from .parallel import batched, sharded
from .parallel.mesh import mesh_size as _mesh_size, pad_to_shards

log = logging.getLogger("swiftly-tpu")

__all__ = [
    "FacetConfig",
    "SubgridConfig",
    "SwiftlyConfig",
    "SwiftlyForward",
    "SwiftlyBackward",
    "FlightQueue",
    "LRUCache",
    "backward_all",
    "check_facet",
    "check_residual",
    "check_subgrid",
    "last_dispatch_path",
    "make_facet",
    "make_real_facet",
    "make_full_facet_cover",
    "make_full_subgrid_cover",
    "make_sparse_facet_cover",
    "make_subgrid",
    "sparse_fov_cover_offsets",
]


# ---------------------------------------------------------------------------
# Oracle helpers (host-side)
# ---------------------------------------------------------------------------


def make_facet(image_size, facet_config, sources):
    """Build a facet's data from a source list (test/demo input)."""
    return make_facet_from_sources(
        sources,
        image_size,
        facet_config.size,
        [facet_config.off0, facet_config.off1],
        [facet_config.mask0, facet_config.mask1],
    )


def make_real_facet(image_size, facet_config, sources, dtype=None):
    """`make_facet` as a sparse-built real plane (f32 by default).

    == make_facet(...).real, built without the dense complex
    intermediate — the input path for large-N streamed drivers (one 64k
    facet is 8 GB complex but 2 GB as its real plane, and point-source
    facets are zeros plus a handful of mask-scaled pixels)."""
    from .ops.oracle import make_real_facet_plane_from_sources

    kwargs = {} if dtype is None else {"dtype": dtype}
    return make_real_facet_plane_from_sources(
        sources,
        image_size,
        facet_config.size,
        [facet_config.off0, facet_config.off1],
        [facet_config.mask0, facet_config.mask1],
        **kwargs,
    )


def make_sparse_facet(image_size, facet_config, sources, dtype=None):
    """`make_facet` as a `SparseRealFacet` descriptor (coords + values).

    The input path for streamed executors at 64k+ scale: the facet
    plane is synthesised ON DEVICE from these few pixels, so facet-slab
    streaming re-uploads kilobytes per column group instead of the
    multi-GB dense stack. `densify()` == `make_facet(...).real`."""
    from .ops.oracle import make_sparse_real_facet_from_sources

    kwargs = {} if dtype is None else {"dtype": dtype}
    return make_sparse_real_facet_from_sources(
        sources,
        image_size,
        facet_config.size,
        [facet_config.off0, facet_config.off1],
        [facet_config.mask0, facet_config.mask1],
        **kwargs,
    )


def make_subgrid(image_size, sg_config, sources):
    """Build a subgrid's data by direct DFT (test/demo input)."""
    return make_subgrid_from_sources(
        sources,
        image_size,
        sg_config.size,
        [sg_config.off0, sg_config.off1],
        [sg_config.mask0, sg_config.mask1],
    )


def check_facet(image_size, facet_config, approx_facet, sources):
    """RMS error of a computed facet vs the analytic source model."""
    facet = make_facet(image_size, facet_config, sources)
    return float(np.sqrt(np.mean(np.abs(facet - np.asarray(approx_facet)) ** 2)))


def check_subgrid(image_size, sg_config, approx_subgrid, sources):
    """RMS error of a computed subgrid vs the direct-DFT source model."""
    approx_subgrid = np.asarray(approx_subgrid)
    subgrid = make_subgrid_from_sources(
        sources,
        image_size,
        approx_subgrid.shape[0],
        [sg_config.off0, sg_config.off1],
        [sg_config.mask0, sg_config.mask1],
    )
    return float(np.sqrt(np.mean(np.abs(subgrid - approx_subgrid) ** 2)))


def check_residual(residual):
    """RMS of a residual array."""
    return float(np.sqrt(np.mean(np.abs(np.asarray(residual)) ** 2)))


# ---------------------------------------------------------------------------
# Working-set control
# ---------------------------------------------------------------------------


class LRUCache:
    """Small LRU: bounds the number of live column buffers.

    `set` returns the evicted (key, value) once capacity is exceeded —
    eviction is what triggers the backward fold step. Parity: reference
    LRUCache (api.py:525-590).

    Hit/miss counters (``<name>.hit`` / ``<name>.miss``, recorded only
    while metrics are enabled) make column-cache effectiveness visible
    in serve/bench telemetry — a serving workload whose column locality
    the scheduler fails to exploit shows up as a rising ``lru.miss``.
    """

    def __init__(self, capacity: int, name: str = "lru"):
        self.capacity = capacity
        self._store = {}  # insertion-ordered; order == recency
        self._hit_name = f"{name}.hit"
        self._miss_name = f"{name}.miss"

    def get(self, key):
        """Return the cached value and refresh its recency, or None."""
        if key not in self._store:
            if _metrics.enabled():
                _metrics.count(self._miss_name)
            return None
        if _metrics.enabled():
            _metrics.count(self._hit_name)
        value = self._store.pop(key)
        self._store[key] = value
        return value

    def keys(self):
        """Cached keys, oldest first (recency order) — the serving
        scheduler's column-locality signal."""
        return list(self._store)

    def set(self, key, value):
        """Insert/refresh; returns (evicted_key, evicted_value) or
        (None, None)."""
        self._store.pop(key, None)
        self._store[key] = value
        if len(self._store) <= self.capacity:
            return None, None
        oldest = next(iter(self._store))
        return oldest, self._store.pop(oldest)

    def pop_all(self):
        """Drain the cache oldest-first, yielding (key, value)."""
        while self._store:
            oldest = next(iter(self._store))
            yield oldest, self._store.pop(oldest)

    def __len__(self):
        return len(self._store)


class FlightQueue:
    """Bounds in-flight asynchronous device work, counted in LOGICAL
    TASKS (subgrids), not bytes.

    JAX dispatches computations asynchronously; unbounded dispatch can
    enqueue arbitrarily much device work and host memory. `admit` blocks on
    the oldest in-flight result once `depth` computations are outstanding —
    the streaming analogue of the reference's TaskQueue (api.py:466-522),
    whose unit is also a task. Batched/fused paths admit one slot per
    subgrid even when many subgrids share one program's output array, so
    `queue_size` keeps its meaning across execution paths; byte-level
    control is the sharding layout plus the streamed executors'
    HBM-budgeted group sizing (`col_group_for_budget`). Note the
    tunnel-runtime caveat: where `block_until_ready` returns early, the
    streamed paths use checksum-pull backpressure instead of this queue.
    """

    def __init__(self, depth: int):
        import os

        self.depth = depth
        # deque: the queue drains oldest-first on every admit past the
        # bound, and list.pop(0) is O(n) per pop — O(n^2) across a long
        # serving session's stream of admissions
        self._inflight = deque()
        # On runtimes whose block_until_ready returns before the dispatch
        # queue has drained (the tunnel-attached TPU this repo benches
        # on), blocking is not backpressure. With SWIFTLY_QUEUE_CHECKSUM=1
        # `_ready` instead PULLS one element of each item to the host — a
        # genuine device round trip that cannot complete before the
        # producing computation has, so the queue-depth bound is real on
        # such runtimes too (the streamed executors' built-in checksum
        # pipelines use the same trick unconditionally).
        self._checksum = os.environ.get("SWIFTLY_QUEUE_CHECKSUM") == "1"

    def _ready(self, item):
        # Accumulators are donated to their successor computation; a
        # queued buffer may therefore already be deleted by the time we
        # would block on it — its successor in the queue covers it.
        deleted = getattr(item, "is_deleted", None)
        if deleted is not None and deleted():
            return
        if self._checksum and hasattr(item, "ndim"):
            np.asarray(item[(0,) * item.ndim])
            return
        if hasattr(item, "block_until_ready"):
            item.block_until_ready()

    def admit(self, arrays):
        """Register newly dispatched arrays, blocking if the queue is full."""
        if not isinstance(arrays, (list, tuple)):
            arrays = [arrays]
        self._inflight.extend(arrays)
        while len(self._inflight) > self.depth:
            self._ready(self._inflight.popleft())

    def drain(self):
        """Block until all in-flight work completes."""
        while self._inflight:
            self._ready(self._inflight.popleft())


# ---------------------------------------------------------------------------
# Facet stacking
# ---------------------------------------------------------------------------


class _FacetStack:
    """Stacked facet metadata: offsets and realised masks as arrays.

    When running on a mesh the stack is zero-padded to a multiple of the
    mesh size; padded entries have zero masks and contribute exact zeros
    to every (linear) accumulation.
    """

    def __init__(self, facet_configs, pad_to: int = 1):
        if not facet_configs:
            raise ValueError("At least one facet is required")
        sizes = {cfg.size for cfg in facet_configs}
        if len(sizes) != 1:
            raise ValueError("All facets must share one size")
        self.size = sizes.pop()
        self.configs = list(facet_configs)
        self.n_real = len(facet_configs)
        self.n_total = pad_to_shards(self.n_real, pad_to)
        n_pad = self.n_total - self.n_real

        def mask_row(mask):
            return np.ones(self.size) if mask is None else np.asarray(mask)

        zero_mask = np.zeros(self.size)
        self.offs0 = np.array([c.off0 for c in facet_configs] + [0] * n_pad)
        self.offs1 = np.array([c.off1 for c in facet_configs] + [0] * n_pad)
        self.masks0 = np.stack(
            [mask_row(c.mask0) for c in facet_configs] + [zero_mask] * n_pad
        )
        self.masks1 = np.stack(
            [mask_row(c.mask1) for c in facet_configs] + [zero_mask] * n_pad
        )

    def pad_data(self, stacked):
        """Zero-pad stacked per-facet data [n_real, ...] to [n_total, ...]."""
        if self.n_total == self.n_real:
            return stacked
        pad = np.zeros((self.n_total - self.n_real,) + stacked.shape[1:],
                       dtype=stacked.dtype)
        return np.concatenate([stacked, pad])

    def __len__(self):
        return self.n_total




def _place(core, mesh, arr, shard_facets: bool):
    """Device-place an array: facet-sharded over the mesh or replicated.

    With no mesh, returns the array unchanged (the batched kernels place
    it on the default device)."""
    if mesh is None:
        return arr
    import jax
    from .parallel.mesh import place_facet_sharded, replicated_sharding

    if np.iscomplexobj(arr):
        arr = core._prep(np.asarray(arr))
    if shard_facets:
        # multihost-safe: each process supplies only its facet shard
        return place_facet_sharded(arr, mesh)
    return jax.device_put(arr, replicated_sharding(mesh))


def _use_shard_map(config):
    return getattr(config, "spmd_mode", "shard_map") == "shard_map"


# Which execution path served the latest column-batched forward request.
# Silent degradation is the failure mode here: `get_subgrid_tasks` falls
# back to the per-subgrid loop on host backends, and a serving/bench run
# that quietly took the slow path produces numbers nobody can interpret.
# The fallback therefore warns ONCE per reason and the executed path is
# recorded (gauge `fwd.dispatch_path` + `last_dispatch_path()`) so run
# manifests can stamp how their requests were actually served.
_LAST_DISPATCH_PATH = None
_FALLBACK_WARNED = set()


def last_dispatch_path():
    """The path the most recent batched-forward call executed:
    ``"batched-column"``, ``"sharded-column"``, or the host
    ``"per-subgrid-loop"`` fallback (None before any call)."""
    return _LAST_DISPATCH_PATH


def _record_dispatch_path(path, fallback_reason=None):
    global _LAST_DISPATCH_PATH
    _LAST_DISPATCH_PATH = path
    if _metrics.enabled():
        _metrics.gauge("fwd.dispatch_path", path)
        _metrics.count(f"fwd.path.{path}")
    if fallback_reason and fallback_reason not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(fallback_reason)
        log.warning(
            "get_subgrid_tasks falling back to the per-subgrid loop "
            "(%s): column batching unavailable — O(subgrids) dispatches "
            "instead of O(columns)", fallback_reason,
        )
        _metrics.event(
            "fwd.path_fallback", path=path, reason=fallback_reason
        )


def _subgrid_masks(sg_config):
    size = sg_config.size
    m0 = np.ones(size) if sg_config.mask0 is None else np.asarray(sg_config.mask0)
    m1 = np.ones(size) if sg_config.mask1 is None else np.asarray(sg_config.mask1)
    return m0, m1


def _group_columns(subgrid_configs, key=lambda sg: sg, require_one_size=False):
    """Group items by subgrid column offset (off0), preserving order.

    :param key: maps an item to its SubgridConfig
    :param require_one_size: raise on mixed subgrid sizes (callers whose
        output is stacked cannot handle them); otherwise mixed sizes just
        make the grouping non-rectangular
    :return: (groups, rectangular) — groups is {off0: [item, ...]};
        rectangular is True when all subgrids share one size and all
        columns have equal length (the shape the fused whole-cover
        programs require).
    """
    groups = {}
    for item in subgrid_configs:  # may be any iterable, incl. a generator
        groups.setdefault(key(item).off0, []).append(item)
    if not groups:
        raise ValueError("At least one subgrid is required")
    sizes = {key(item).size for col in groups.values() for item in col}
    if require_one_size and len(sizes) != 1:
        raise ValueError(
            f"All subgrids must share one size for stacked output "
            f"(got sizes {sorted(sizes)})"
        )
    rectangular = (
        len(sizes) == 1 and len({len(v) for v in groups.values()}) == 1
    )
    return groups, rectangular


def _pad_ragged_columns(groups, size, make_pad=None):
    """Pad ragged columns ({off0: [(index, SubgridConfig), ...]}) to equal
    length with zero-mask entries (index None) appended at the end.

    Exact by construction: a zero mask zeroes a padded entry's output
    (forward), and zero data contributes zeros to every linear
    accumulation (backward). `make_pad(off0, first_config)` customises
    the padded item; the default appends (None, zero-mask config).
    """
    max_S = max(len(col) for col in groups.values())
    zero_mask = np.zeros(size)
    for off0, col in groups.items():
        first = col[0][1] if make_pad is None else None
        while len(col) < max_S:
            if make_pad is not None:
                col.append(make_pad(off0, col[0]))
            else:
                col.append(
                    (
                        None,
                        SubgridConfig(
                            off0, first.off1, size, zero_mask, zero_mask
                        ),
                    )
                )
    return max_S


# ---------------------------------------------------------------------------
# Forward: facets -> subgrids
# ---------------------------------------------------------------------------


class SwiftlyForward:
    """Stream subgrids out of a facet set.

    :param swiftly_config: SwiftlyConfig
    :param facet_tasks: list of (FacetConfig, facet_data) pairs
    :param lru_forward: number of column intermediates kept resident
    :param queue_size: in-flight computation cap
    """

    def __init__(self, swiftly_config, facet_tasks, lru_forward=1,
                 queue_size=20):
        self.config = swiftly_config
        self.core = swiftly_config.core
        self.mesh = getattr(swiftly_config, "mesh", None)
        self.stack = _FacetStack(
            [cfg for cfg, _ in facet_tasks], pad_to=_mesh_size(self.mesh)
        )
        self._facet_data = [data for _, data in facet_tasks]
        self._BF_Fs = None
        self._offs0 = _place(self.core, self.mesh, self.stack.offs0, True)
        self._offs1 = _place(self.core, self.mesh, self.stack.offs1, True)
        self.lru = LRUCache(lru_forward)
        self.queue = FlightQueue(queue_size)

    def adopt_facet_tasks(self, facet_tasks):
        """Swap in a new facet stack: drops the prepared facet planes
        and the column LRU, and rebuilds the stack descriptors, so
        every later subgrid computes from the new data. The serve
        path's update hook (`serve.SubgridService.post_facet_update`)
        calls this so its compute fallback — feed misses, evicted rows,
        stale feeds — never serves a superseded stack. Callables are
        materialised and sparse descriptors densified, matching the
        constructor's expectations."""
        data = []
        for _, d in facet_tasks:
            d = d() if callable(d) else d
            if hasattr(d, "densify"):
                d = d.densify()
            data.append(d)
        self.stack = _FacetStack(
            [cfg for cfg, _ in facet_tasks], pad_to=_mesh_size(self.mesh)
        )
        self._facet_data = data
        self._BF_Fs = None
        self._offs0 = _place(self.core, self.mesh, self.stack.offs0, True)
        self._offs1 = _place(self.core, self.mesh, self.stack.offs1, True)
        self.lru = LRUCache(self.lru.capacity)
        return self

    def _get_BF_Fs(self):
        if self._BF_Fs is None:
            with _metrics.stage("fwd.prepare_facets") as st:
                facets = self.stack.pad_data(
                    np.stack(
                        [
                            np.asarray(d, dtype=complex)
                            for d in self._facet_data
                        ]
                    )
                )
                st.bytes_moved = int(facets.nbytes)  # h2d upload volume
                facets = _place(self.core, self.mesh, facets, True)
                self._BF_Fs = batched.prepare_facets_batch(
                    self.core, facets, self._offs0
                )
        return self._BF_Fs

    def _get_columns(self, off0):
        cols = self.lru.get(off0)
        if cols is None:
            cols = batched.extract_columns_batch(
                self.core, self._get_BF_Fs(), off0, self._offs1
            )
            self.lru.set(off0, cols)
        return cols

    def get_subgrid_task(self, subgrid_config):
        """Compute one subgrid (asynchronous device array)."""
        cols = self._get_columns(subgrid_config.off0)
        if self.mesh is not None and _use_shard_map(self.config):
            subgrid = sharded.subgrid_from_columns_sharded(
                self.core,
                self.mesh,
                cols,
                self._offs0,
                self._offs1,
                subgrid_config.off0,
                subgrid_config.off1,
                subgrid_config.size,
                _subgrid_masks(subgrid_config),
            )
        else:
            subgrid = batched.subgrid_from_columns_batch(
                self.core,
                cols,
                self._offs0,
                self._offs1,
                subgrid_config.off0,
                subgrid_config.off1,
                subgrid_config.size,
                _subgrid_masks(subgrid_config),
            )
        self.queue.admit([subgrid])
        return subgrid

    def get_subgrid_tasks(self, subgrid_configs):
        """Compute many subgrids, one program per column.

        Groups the requests by column offset (off0) and computes each
        column's subgrids in a single batched program — same results as
        mapping `get_subgrid_task`, with far fewer dispatches. On a mesh
        the column program runs under shard_map with a single psum per
        column (or via GSPMD inference in "gspmd" mode). Returns the
        subgrids in input order.
        """
        if self.core.backend in ("numpy", "native"):
            _record_dispatch_path(
                "per-subgrid-loop",
                fallback_reason=f"backend={self.core.backend!r}",
            )
            return [self.get_subgrid_task(sg) for sg in subgrid_configs]
        _record_dispatch_path(
            "sharded-column"
            if self.mesh is not None and _use_shard_map(self.config)
            else "batched-column"
        )
        groups = {}  # (off0, size) -> list of input indices
        for i, sg in enumerate(subgrid_configs):
            groups.setdefault((sg.off0, sg.size), []).append(i)
        results = [None] * len(subgrid_configs)
        for (off0, size), idxs in groups.items():
            cols = self._get_columns(off0)
            sg_offs = [
                (subgrid_configs[i].off0, subgrid_configs[i].off1)
                for i in idxs
            ]
            masks = [_subgrid_masks(subgrid_configs[i]) for i in idxs]
            if self.mesh is not None and _use_shard_map(self.config):
                stacked = sharded.subgrids_from_columns_sharded(
                    self.core, self.mesh, cols, self._offs0, self._offs1,
                    sg_offs, size, masks,
                )
            else:
                stacked = batched.subgrids_from_columns_batch(
                    self.core, cols, self._offs0, self._offs1, sg_offs,
                    size, masks,
                )
            # One queue slot per subgrid, not per program: queue_size
            # keeps bounding in-flight *subgrids* regardless of batching.
            self.queue.admit([stacked] * len(idxs))
            for k, i in enumerate(idxs):
                results[i] = stacked[k]
        return results

    def all_subgrids(self, subgrid_configs):
        """Every requested subgrid as ONE fused program.

        Returns a stacked device array [n, xA, xA(, 2)] in request order —
        a single XLA dispatch (scan over columns) and thus a single host
        sync for the entire forward transform; the latency-optimal path
        for remote-attached TPUs. On a mesh the fused program runs under
        shard_map with one psum per scanned column ("gspmd" mode lets XLA
        infer the same collectives). Irregular (ragged-column) covers
        stay on the fused path via exact zero-mask padding; only host
        backends fall back to per-column streaming. All subgrids must
        share one size (the output is stacked); raises ValueError
        otherwise.
        """
        subgrid_configs = list(subgrid_configs)
        groups, rectangular = _group_columns(
            enumerate(subgrid_configs),
            key=lambda item: item[1],
            require_one_size=True,
        )
        if self.core.backend in ("numpy", "native"):
            tasks = self.get_subgrid_tasks(subgrid_configs)
            return np.stack([np.asarray(t) for t in tasks])
        import jax.numpy as jnp

        size = subgrid_configs[0].size
        if not rectangular:
            # Ragged (sparse/irregular) cover: pad short columns with
            # zero-mask entries — exact (padded rows are computed then
            # discarded; their masks are all zero) and cheap, and it
            # keeps the whole cover a single fused dispatch.
            _pad_ragged_columns(groups, size)
        col_offs0 = list(groups)
        max_S = len(groups[col_offs0[0]])
        sg_offs1, masks0, masks1, rows = [], [], [], {}
        for c, off0 in enumerate(col_offs0):
            col = groups[off0]
            for s, (i, _) in enumerate(col):
                if i is not None:
                    rows[i] = c * max_S + s
            sg_offs1.append([sg.off1 for _, sg in col])
            ms = [_subgrid_masks(sg) for _, sg in col]
            masks0.append([m[0] for m in ms])
            masks1.append([m[1] for m in ms])
        fused_flops = 0
        if _metrics.enabled():
            from .utils.flops import forward_batched_flops

            fused_flops = forward_batched_flops(
                self.core,
                n_facets=self.stack.n_real,
                facet_size=self.stack.size,
                n_columns=len(col_offs0),
                subgrids_per_column=max_S,
                subgrid_size=size,
            )
            _metrics.count("fwd.subgrids", len(subgrid_configs))
        with _metrics.stage("fwd.fused_forward", flops=fused_flops):
            if self.mesh is not None and _use_shard_map(self.config):
                stacked = sharded.forward_all_sharded(
                    self.core, self.mesh, self._get_BF_Fs(), self._offs0,
                    self._offs1, col_offs0, sg_offs1, size, masks0, masks1,
                )
            else:
                stacked = batched.forward_all_batch(
                    self.core, self._get_BF_Fs(), self._offs0, self._offs1,
                    col_offs0, sg_offs1, size, masks0, masks1,
                )
        flat = stacked.reshape(
            (len(col_offs0) * max_S,) + stacked.shape[2:]
        )
        n = len(subgrid_configs)
        order = [rows[i] for i in range(n)]
        if order != list(range(n)):
            flat = jnp.take(flat, jnp.asarray(order), axis=0)
        elif flat.shape[0] != n:  # identity order but tail padding rows
            flat = flat[:n]
        # One queue slot per subgrid (not per program), like
        # get_subgrid_tasks: queue_size keeps bounding in-flight subgrids.
        self.queue.admit([flat] * len(subgrid_configs))
        return flat


# ---------------------------------------------------------------------------
# Backward: subgrids -> facets
# ---------------------------------------------------------------------------


class SwiftlyBackward:
    """Stream subgrids in; accumulate and finish facets.

    :param swiftly_config: SwiftlyConfig
    :param facets_config_list: FacetConfigs describing the output facets
    :param lru_backward: number of column accumulators kept live
    :param queue_size: in-flight computation cap
    """

    def __init__(self, swiftly_config, facets_config_list, lru_backward=1,
                 queue_size=20):
        self.config = swiftly_config
        self.core = swiftly_config.core
        self.mesh = getattr(swiftly_config, "mesh", None)
        self.stack = _FacetStack(
            facets_config_list, pad_to=_mesh_size(self.mesh)
        )
        self._offs0 = _place(self.core, self.mesh, self.stack.offs0, True)
        self._offs1 = _place(self.core, self.mesh, self.stack.offs1, True)
        self._masks0 = _place(self.core, self.mesh, self.stack.masks0, True)
        self._masks1 = _place(self.core, self.mesh, self.stack.masks1, True)
        self.lru = LRUCache(lru_backward)
        self.queue = FlightQueue(queue_size)
        self._MNAF_BMNAFs = None
        self._finished = False

    def _zeros(self, shape):
        core = self.core
        if core.backend in ("numpy", "native"):
            return np.zeros(shape, dtype=complex)
        import jax.numpy as jnp

        if core.backend == "planar":
            zeros = jnp.zeros(shape + (2,), dtype=core.dtype)
        else:
            zeros = jnp.zeros(shape, dtype=core.dtype)
        if self.mesh is not None:
            zeros = _place(core, self.mesh, zeros, True)
        return zeros

    def add_new_subgrid_task(self, subgrid_config, subgrid_data):
        """Fold one subgrid into the streaming accumulators."""
        if self._finished:
            raise RuntimeError("finish() was already called")
        core, stack = self.core, self.stack
        off0, off1 = subgrid_config.off0, subgrid_config.off1

        if self.mesh is not None and _use_shard_map(self.config):
            NAF_NAFs = sharded.split_subgrid_sharded(
                core, self.mesh, subgrid_data, off0, off1,
                self._offs0, self._offs1,
            )
        else:
            NAF_NAFs = batched.split_subgrid_batch(
                core, subgrid_data, off0, off1, self._offs0, self._offs1
            )

        col = self.lru.get(off0)
        if col is None:
            col = self._zeros(
                (len(stack), core.xM_yN_size, core.yN_size)
            )
        col = batched.accumulate_column_batch(core, NAF_NAFs, off1, col)

        evicted_off0, evicted = self.lru.set(off0, col)
        if evicted is not None:
            self._fold_column(evicted_off0, evicted)
        self.queue.admit([col])
        return col

    def add_new_subgrid_tasks(self, tasks):
        """Fold many (subgrid_config, subgrid_data) pairs, one program per
        column.

        Equivalent to mapping `add_new_subgrid_task`; groups the inputs by
        column offset (off0) and folds each group with a single scanned
        program. Accumulation is linear, so grouping does not change the
        result.
        """
        if self._finished:
            raise RuntimeError("finish() was already called")
        if self.core.backend in ("numpy", "native"):
            for sg_config, data in tasks:
                self.add_new_subgrid_task(sg_config, data)
            return
        core, stack = self.core, self.stack
        groups = {}
        for sg_config, data in tasks:
            groups.setdefault((sg_config.off0, sg_config.size), []).append(
                (sg_config, data)
            )
        for (off0, _size), group in groups.items():
            col = self.lru.get(off0)
            if col is None:
                col = self._zeros((len(stack), core.xM_yN_size, core.yN_size))
            subgrid_data = [d for _, d in group]
            sg_offs = [(sg.off0, sg.off1) for sg, _ in group]
            if self.mesh is not None and _use_shard_map(self.config):
                col = sharded.split_accumulate_sharded(
                    core, self.mesh, subgrid_data, sg_offs,
                    self._offs0, self._offs1, col,
                )
            else:
                col = batched.split_accumulate_batch(
                    core, subgrid_data, sg_offs, self._offs0, self._offs1,
                    col,
                )
            evicted_off0, evicted = self.lru.set(off0, col)
            if evicted is not None:
                self._fold_column(evicted_off0, evicted)
            self.queue.admit([col] * len(group))

    def _fold_column(self, off0, col):
        core, stack = self.core, self.stack
        if self._MNAF_BMNAFs is None:
            self._MNAF_BMNAFs = self._zeros(
                (len(stack), core.yN_size, stack.size)
            )
        self._MNAF_BMNAFs = batched.accumulate_facet_batch(
            core, col, off0, self._offs1, self._masks1, stack.size,
            self._MNAF_BMNAFs,
        )
        self.queue.admit([self._MNAF_BMNAFs])

    def finish(self):
        """Drain accumulators and return the finished facet stack
        [F, yB, yB]."""
        for off0, col in self.lru.pop_all():
            self._fold_column(off0, col)
        if self._MNAF_BMNAFs is None:
            self._MNAF_BMNAFs = self._zeros(
                (len(self.stack), self.core.yN_size, self.stack.size)
            )
        with _metrics.stage("bwd.finish"):
            facets = batched.finish_facets_batch(
                self.core,
                self._MNAF_BMNAFs,
                self._offs0,
                self._masks0,
                self.stack.size,
            )
            self.queue.drain()
        self._finished = True
        return facets[: self.stack.n_real]


def backward_all(swiftly_config, facet_configs, subgrid_tasks):
    """The full subgrid->facet transform as ONE fused program.

    :param subgrid_tasks: list of (SubgridConfig, subgrid_data) pairs
        covering the grid
    :return: finished facet stack [F, yB, yB(, 2)] matching facet_configs

    Single XLA dispatch (scan over subgrid columns); numerically identical
    to streaming the same subgrids through `SwiftlyBackward` (every
    accumulation is a sum of linear contributions). On a mesh the fused
    program runs under shard_map with facet-shard-local accumulation (no
    collectives; "gspmd" mode lets XLA infer the same). Ragged covers
    stay on the fused path via exact zero-data padding; mixed subgrid
    sizes and host backends fall back to the streaming path.
    """
    core = swiftly_config.core
    mesh = getattr(swiftly_config, "mesh", None)
    subgrid_tasks = list(subgrid_tasks)
    groups, rectangular = _group_columns(
        subgrid_tasks, key=lambda item: item[0]
    )
    sizes = {sg.size for sg, _ in subgrid_tasks}
    if len(sizes) != 1 or core.backend in ("numpy", "native"):
        bwd = SwiftlyBackward(swiftly_config, facet_configs)
        bwd.add_new_subgrid_tasks(subgrid_tasks)
        return bwd.finish()
    if not rectangular:
        # Ragged cover: pad short columns with zero-data subgrids —
        # exact, since every accumulation is linear in the subgrid data.
        size = sizes.pop()
        zero_data = np.zeros((size, size), dtype=complex)
        _pad_ragged_columns(
            groups, size,
            make_pad=lambda off0, first: (
                SubgridConfig(off0, first[0].off1, size), zero_data
            ),
        )

    stack = _FacetStack(facet_configs, pad_to=_mesh_size(mesh))
    # nested lists: the batch kernels prep and stack them themselves
    subgrids = [[d for _, d in groups[off0]] for off0 in groups]
    sg_offs = [
        [(sg.off0, sg.off1) for sg, _ in groups[off0]] for off0 in groups
    ]
    offs0 = _place(core, mesh, stack.offs0, True)
    offs1 = _place(core, mesh, stack.offs1, True)
    masks0 = _place(core, mesh, stack.masks0, True)
    masks1 = _place(core, mesh, stack.masks1, True)
    fused_flops = 0
    if _metrics.enabled():
        from .utils.flops import backward_batched_flops

        n_cols = len(groups)
        fused_flops = backward_batched_flops(
            core,
            n_facets=stack.n_real,
            facet_size=stack.size,
            n_columns=n_cols,
            subgrids_per_column=len(next(iter(groups.values()))),
            subgrid_size=subgrid_tasks[0][0].size,
        )
    with _metrics.stage("bwd.fused_backward", flops=fused_flops):
        if mesh is not None and _use_shard_map(swiftly_config):
            facets = sharded.backward_all_sharded(
                core, mesh, subgrids, sg_offs, offs0, offs1,
                masks0, masks1, stack.size,
            )
        else:
            facets = batched.backward_all_batch(
                core, subgrids, sg_offs, offs0, offs1, masks0, masks1,
                stack.size,
            )
    return facets[: stack.n_real]
