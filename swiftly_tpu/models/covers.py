"""Cover generation: how facets/subgrids tile the image and grid planes.

Two families:

* **Full covers** — a regular tiling where every pixel belongs to exactly
  one chunk (mid-point borders between neighbouring offsets, wrapping at
  the image edge). Parity: reference ``make_full_cover_config``
  (/root/reference/src/ska_sdp_exec_swiftly/api_helper.py:213-240).

* **Sparse covers** — irregular facet layouts covering only a circular
  field of view; facets need not tile the whole image. Parity: reference
  scripts/demo_sparse_facet.py:34-181.
"""

from __future__ import annotations

import math

import numpy as np

from .config import FacetConfig, SubgridConfig

__all__ = [
    "make_full_cover",
    "make_full_facet_cover",
    "make_full_subgrid_cover",
    "sparse_fov_cover_offsets",
    "make_sparse_facet_cover",
]


def make_full_cover(N: int, chunk_size: int, cls):
    """Regular 2D tiling of an N x N plane with `chunk_size` chunks.

    Offsets are multiples of chunk_size; each chunk's ownership mask covers
    the pixels closer to its offset than to any neighbour's (borders at
    offset mid-points, wrapping at N).
    """
    offsets = chunk_size * np.arange(math.ceil(N / chunk_size))
    nxt = np.concatenate([offsets[1:], [N + offsets[0]]])
    border = (offsets + nxt) // 2
    half = chunk_size // 2

    def axis_mask(i, off):
        left = (border[i - 1] - off + half) % N
        right = border[i] - off + half
        return [[slice(int(left), int(right))], chunk_size]

    configs = []
    for i0, off0 in enumerate(offsets):
        for i1, off1 in enumerate(offsets):
            configs.append(
                cls(
                    off0,
                    off1,
                    chunk_size,
                    axis_mask(i0, off0),
                    axis_mask(i1, off1),
                )
            )
    return configs


def make_full_subgrid_cover(swiftly_config):
    """Full subgrid tiling of the grid plane for a SwiftlyConfig."""
    return make_full_cover(
        swiftly_config.image_size,
        swiftly_config.max_subgrid_size,
        SubgridConfig,
    )


def make_full_facet_cover(swiftly_config):
    """Full facet tiling of the image plane for a SwiftlyConfig."""
    return make_full_cover(
        swiftly_config.image_size,
        swiftly_config.max_facet_size,
        FacetConfig,
    )


# ---------------------------------------------------------------------------
# Sparse circular-FoV covers
# ---------------------------------------------------------------------------


def _row_offsets(facet_size: int, nfacet: int, N: int):
    """Offsets of `nfacet` facets covering one row, centre-out.

    Odd counts place a facet at offset 0; even counts straddle the centre.
    Negative offsets are expressed as N - off (mod-N convention).
    """
    offs = []
    if nfacet % 2 == 0:
        first = facet_size // 2
        for i in range(nfacet // 2):
            right = first + i * facet_size
            offs.extend([right, N - right])
    else:
        offs.append(0)
        for i in range(1, (nfacet + 1) // 2):
            right = i * facet_size
            offs.extend([right, N - right])
    return offs


def _rows_for_fov(facet_size: int, fov_pixels: int, N: int):
    """(nfacet, off1) per facet row needed to cover a circular FoV.

    Each row's facet count shrinks with distance from the centre following
    the circle's chord length.
    """
    n_rows = math.ceil(fov_pixels / facet_size)
    rows = []

    def chord(off1_up):
        if off1_up == 0:
            return fov_pixels
        return 2 * math.sqrt(
            max((fov_pixels / 2) ** 2 - (off1_up - facet_size / 2) ** 2, 0.0)
        )

    if n_rows % 2 == 0:
        first = facet_size // 2
        for i in range(n_rows // 2):
            up = first + i * facet_size
            width = fov_pixels if i == 0 else chord(up)
            nfacet = math.ceil(width / facet_size)
            rows.extend([(nfacet, up), (nfacet, N - up)])
    else:
        rows.append((n_rows, 0))
        for i in range(1, (n_rows + 1) // 2):
            up = i * facet_size
            nfacet = math.ceil(chord(up) / facet_size)
            rows.extend([(nfacet, up), (nfacet, N - up)])
    return rows


def sparse_fov_cover_offsets(swiftly_config, fov_pixels: int, x0: int = 0, y0: int = 0):
    """(off0, off1) list + mask list for facets covering a circular FoV.

    :param swiftly_config: SwiftlyConfig
    :param fov_pixels: diameter of the field of view, in pixels
    :param x0: FoV centre offset along axis 0
    :param y0: FoV centre offset along axis 1
    :raises ValueError: if any resulting offset is not a multiple of
        facet_off_step (the core's divisibility requirement)
    """
    N = swiftly_config.image_size
    facet_size = swiftly_config.max_facet_size
    offsets = []
    for nfacet, off1 in _rows_for_fov(facet_size, fov_pixels, N):
        for off0 in _row_offsets(facet_size, nfacet, N):
            offsets.append((off0 + x0, off1 + y0))

    step = swiftly_config.facet_off_step
    for off0, off1 in offsets:
        if off0 % step or off1 % step:
            raise ValueError(
                f"Sparse facet offset ({off0},{off1}) not divisible by "
                f"facet offset step {step}"
            )

    full = [[slice(None)], facet_size]
    masks = [(full, full) for _ in offsets]
    return offsets, masks


def make_sparse_facet_cover(facet_size: int, offsets, masks):
    """Build FacetConfigs from (off0, off1) and (mask0, mask1) lists."""
    return [
        FacetConfig(off0, off1, facet_size, m0, m1)
        for (off0, off1), (m0, m1) in zip(offsets, masks)
    ]
