"""Chunk descriptors and the top-level SwiftlyConfig.

`FacetConfig` / `SubgridConfig` describe one chunk of image/grid space by
its per-axis offsets, size, and optional ownership masks (stored sparsely
as slice lists, realised lazily). `SwiftlyConfig` owns the numerical core
(backend-selectable) and exposes the layout accessors.

API parity: reference /root/reference/src/ska_sdp_exec_swiftly/api.py:39-214
(minus the Dask client — on TPU the execution fabric is the device mesh,
configured separately in swiftly_tpu.parallel).
"""

from __future__ import annotations

from ..ops.core import SwiftlyCore
from ..ops.oracle import mask_from_slices

__all__ = ["ChunkConfig", "FacetConfig", "SubgridConfig", "SwiftlyConfig"]


class ChunkConfig:
    """Base descriptor for one facet or subgrid chunk.

    :param off0: chunk mid-point offset along axis 0 (image coordinates)
    :param off1: chunk mid-point offset along axis 1
    :param size: chunk size in pixels (square)
    :param mask0: ownership mask for axis 0 — either a realised 0/1 array,
        or ``[slice_list, mask_size]`` for lazy sparse storage, or None
    :param mask1: same for axis 1
    """

    def __init__(self, off0, off1, size, mask0=None, mask1=None):
        self.off0 = int(off0)
        self.off1 = int(off1)
        self.size = int(size)
        self._mask0 = mask0
        self._mask1 = mask1

    @staticmethod
    def _realise(mask):
        if isinstance(mask, list):
            slices, size = mask
            return mask_from_slices(slices, size)
        return mask

    @property
    def mask0(self):
        """Axis-0 ownership mask (realised on demand)."""
        return self._realise(self._mask0)

    @property
    def mask1(self):
        """Axis-1 ownership mask (realised on demand)."""
        return self._realise(self._mask1)

    def __repr__(self):
        return (
            f"{type(self).__name__}(off0={self.off0}, off1={self.off1}, "
            f"size={self.size})"
        )


class FacetConfig(ChunkConfig):
    """Descriptor of one facet (image-space chunk)."""


class SubgridConfig(ChunkConfig):
    """Descriptor of one subgrid (grid-space chunk)."""


class SwiftlyConfig:
    """Top-level configuration: sizes, PSWF parameter, and the core.

    :param W: PSWF window parameter
    :param fov: field of view (fraction of image covered by usable data)
    :param N: total image size
    :param yB_size: maximum (true) facet size
    :param yN_size: padded facet size (divides N)
    :param xA_size: maximum (true) subgrid size
    :param xM_size: padded subgrid size (divides N)
    :param backend: numerical backend — "jax" (complex XLA), "planar"
        (TPU-native real pairs), or "numpy" (host reference)
    :param dtype: forwarded to the core
    :param mesh: optional jax.sharding.Mesh; when given, the streaming API
        shards facet stacks over the mesh's first axis and facet-sum
        reductions become cross-device collectives
    :param spmd_mode: how mesh collectives are expressed — "shard_map"
        (explicit jax.shard_map + lax.psum, the default) or "gspmd"
        (sharded inputs into jit; XLA infers the collectives)
    """

    def __init__(
        self,
        W: float,
        fov: float,
        N: int,
        yB_size: int,
        yN_size: int,
        xA_size: int,
        xM_size: int,
        backend: str = "jax",
        dtype=None,
        mesh=None,
        spmd_mode: str = "shard_map",
        **_other,
    ):
        if mesh is not None and backend in ("numpy", "native"):
            raise ValueError(
                f"backend={backend!r} runs on the host; a device mesh "
                "requires the 'jax' or 'planar' backend"
            )
        if spmd_mode not in ("shard_map", "gspmd"):
            raise ValueError(f"Unknown spmd_mode: {spmd_mode!r}")
        self.mesh = mesh
        self.spmd_mode = spmd_mode
        self._W = W
        self._fov = fov
        self._N = N
        self._yB_size = yB_size
        self._yN_size = yN_size
        self._xA_size = xA_size
        self._xM_size = xM_size
        self.core = SwiftlyCore(
            W, N, xM_size, yN_size, backend=backend, dtype=dtype
        )

    @property
    def image_size(self):
        """Size of the entire (virtual) image in pixels."""
        return self._N

    @property
    def max_facet_size(self):
        """Maximum true facet size in pixels."""
        return self._yB_size

    @property
    def max_subgrid_size(self):
        """Maximum true subgrid size in pixels."""
        return self._xA_size

    @property
    def pswf_parameter(self):
        """PSWF window parameter W."""
        return self._W

    @property
    def fov(self):
        """Field-of-view fraction."""
        return self._fov

    @property
    def internal_facet_size(self):
        """Padded facet size used internally (yN)."""
        return self._yN_size

    @property
    def internal_subgrid_size(self):
        """Padded subgrid size used internally (xM)."""
        return self._xM_size

    @property
    def contribution_size(self):
        """Per-axis size of one facet<->subgrid contribution block."""
        return self.core.xM_yN_size

    @property
    def facet_off_step(self):
        """All facet offsets must be multiples of this (= N/xM)."""
        return self.core.facet_off_step

    @property
    def subgrid_off_step(self):
        """All subgrid offsets must be multiples of this (= N/yN)."""
        return self.core.subgrid_off_step
