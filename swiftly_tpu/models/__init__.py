"""Transform plans: chunk descriptors, covers, and the parameter catalogue."""

from .catalogue import SWIFT_CONFIGS
from .config import ChunkConfig, FacetConfig, SubgridConfig, SwiftlyConfig
from .covers import (
    make_full_cover,
    make_full_facet_cover,
    make_full_subgrid_cover,
    make_sparse_facet_cover,
    sparse_fov_cover_offsets,
)

__all__ = [
    "SWIFT_CONFIGS",
    "ChunkConfig",
    "FacetConfig",
    "SubgridConfig",
    "SwiftlyConfig",
    "make_full_cover",
    "make_full_facet_cover",
    "make_full_subgrid_cover",
    "make_sparse_facet_cover",
    "sparse_fov_cover_offsets",
]
