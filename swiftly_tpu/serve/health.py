"""Replica health: heartbeat leases and the fleet's health monitor.

A fleet of long-lived serve replicas cannot ask a dead replica whether
it is dead — liveness must be *inferred*. The inference here is the
standard lease protocol (the failover precondition DaggerFFT's
scheduler relies on, arXiv 2601.12209; TPU device processes in practice
get preempted mid-run, arXiv 2002.03260): each replica's pump loop
**beats** its `HealthLease` every iteration, and the monitor grades
replicas by missed beats:

* ``live``      — fewer than ``miss_suspect`` beat intervals missed;
* ``suspect``   — at least ``miss_suspect`` missed: the monitor fires
  an active **probe** (through the ``fleet.health.probe`` fault site,
  so drills can fail probes deterministically). A successful probe
  renews the lease (a slow-but-alive replica is *revived*, not
  failed over — the lease revival race is a non-event by design); a
  failed probe revokes immediately;
* ``revoked``   — ``miss_revoke`` intervals missed (or a probe failed
  while suspect): the replica is dead to the router, and the fleet
  fails its work over. Revocation LATCHES: a zombie replica's late
  beat after revocation is counted (``health.zombie_beats``) but
  ignored — re-admission requires an explicit `HealthLease.revive`
  (the restore path), never a stray heartbeat.

Clocks are injectable so every state machine here is testable without
sleeping; transitions are recorded (bounded), counted via `obs.metrics`
(``health.suspect`` / ``health.revoked`` / ``health.revived``) and
landed on the trace, so a drill artifact shows the detection timeline
next to the kill it reacted to.
"""

from __future__ import annotations

import threading
import time

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace
from ..resilience.faults import fault_point as _fault_point

__all__ = ["LIVE", "REVOKED", "SUSPECT", "HealthLease", "HealthMonitor"]

LIVE = "live"
SUSPECT = "suspect"
REVOKED = "revoked"

_MAX_TRANSITIONS = 256


class HealthLease:
    """One replica's heartbeat lease.

    :param owner: label for metrics/trace (e.g. ``"replica-1"``)
    :param interval_s: expected beat period; staleness is measured in
        units of it
    :param miss_suspect: missed intervals before ``suspect``
    :param miss_revoke: missed intervals before ``revoked``
    :param clock: injectable monotonic clock
    """

    def __init__(self, owner="", interval_s=0.05, miss_suspect=2,
                 miss_revoke=5, clock=time.monotonic):
        if not 0 < miss_suspect <= miss_revoke:
            raise ValueError(
                "need 0 < miss_suspect <= miss_revoke "
                f"(got {miss_suspect}, {miss_revoke})"
            )
        self.owner = owner
        self.interval_s = float(interval_s)
        self.miss_suspect = int(miss_suspect)
        self.miss_revoke = int(miss_revoke)
        self._clock = clock
        self._lock = threading.Lock()
        self.last_beat_t = clock()
        self.beats = 0
        self.zombie_beats = 0
        self._revoked = False

    def beat(self, now=None):
        """Renew the lease; returns False for a zombie beat (the lease
        was already revoked — renewal requires `revive`)."""
        with self._lock:
            if self._revoked:
                self.zombie_beats += 1
                _metrics.count("health.zombie_beats")
                return False
            self.last_beat_t = self._clock() if now is None else now
            self.beats += 1
            return True

    def missed(self, now=None):
        """Beat intervals elapsed since the last renewal."""
        now = self._clock() if now is None else now
        return max(0, int((now - self.last_beat_t) / self.interval_s))

    def state(self, now=None):
        """``live`` / ``suspect`` / ``revoked`` — pure, no side effects
        (revocation itself is the monitor's `revoke` call, which
        latches)."""
        with self._lock:
            if self._revoked:
                return REVOKED
        m = self.missed(now)
        if m >= self.miss_revoke:
            return REVOKED
        if m >= self.miss_suspect:
            return SUSPECT
        return LIVE

    @property
    def revoked(self):
        return self._revoked

    def revoke(self):
        """Latch the lease revoked: beats become zombie beats until
        `revive` (the failover path owns this call)."""
        with self._lock:
            self._revoked = True

    def revive(self, now=None):
        """Explicit re-admission after a restore: clears the latch and
        renews, so the next `state` is ``live``."""
        with self._lock:
            self._revoked = False
            self.last_beat_t = self._clock() if now is None else now

    def __repr__(self):
        return (
            f"HealthLease({self.owner!r}, beats={self.beats}, "
            f"revoked={self._revoked})"
        )


class HealthMonitor:
    """Grades a set of leases and drives suspect-probing.

    :param probe: optional ``fn(owner_key) -> bool`` active liveness
        check, called (through the ``fleet.health.probe`` fault site)
        when a lease turns suspect. True renews the lease; False — or a
        raised exception — revokes it.
    :param clock: injectable monotonic clock
    """

    def __init__(self, probe=None, clock=time.monotonic):
        self.probe = probe
        self._clock = clock
        self._lock = threading.Lock()
        self._leases = {}       # key -> HealthLease
        self._last_state = {}   # key -> last observed state
        self.transitions = []   # [{"t", "owner", "from", "to", "via"}]
        self.dropped_transitions = 0

    def register(self, key, lease):
        with self._lock:
            self._leases[key] = lease
            self._last_state[key] = LIVE
        return lease

    def lease(self, key):
        return self._leases.get(key)

    def unregister(self, key):
        """Retire a lease (the autoscaler's drain path): the monitor
        stops grading it. Without this, a drained replica's idle lease
        would decay to revoked and fire a phantom failover."""
        with self._lock:
            self._leases.pop(key, None)
            self._last_state.pop(key, None)

    def _record(self, now, key, frm, to, via):
        if len(self.transitions) < _MAX_TRANSITIONS:
            self.transitions.append(
                {"t": round(now, 6), "owner": key, "from": frm,
                 "to": to, "via": via}
            )
        else:
            self.dropped_transitions += 1
        _metrics.count(f"health.{to}" if to != LIVE else "health.revived")
        _trace.instant("health.transition", cat="health", owner=key,
                       frm=frm, to=to, via=via)
        _recorder.record("lease", f"health.{key}.{frm}->{to}", via)

    def check(self, now=None):
        """One grading pass; returns the transitions it observed as
        ``[(key, from_state, to_state), ...]``.

        A suspect lease is probed (when a probe fn is installed):
        success renews — the slow replica is revived without failover;
        failure (or a probe exception, including an injected
        ``fleet.health.probe`` fault) revokes immediately rather than
        waiting out ``miss_revoke``.
        """
        now = self._clock() if now is None else now
        out = []
        with self._lock:
            items = list(self._leases.items())
        for key, lease in items:
            state = lease.state(now)
            if state == SUSPECT and self.probe is not None:
                ok = False
                try:
                    _fault_point("fleet.health.probe")
                    ok = bool(self.probe(key))
                except Exception:  # noqa: BLE001 - a failed probe IS data
                    ok = False
                _metrics.count(
                    "health.probe_ok" if ok else "health.probe_failed"
                )
                if ok:
                    lease.beat(now)
                    state = LIVE
                else:
                    state = REVOKED
            if state == REVOKED and not lease.revoked:
                lease.revoke()
            prev = self._last_state.get(key, LIVE)
            if state != prev:
                self._last_state[key] = state
                self._record(now, key, prev, state,
                             via="probe" if self.probe else "lease")
                out.append((key, prev, state))
        return out

    def revive(self, key, now=None):
        """Re-admit a restored replica: lease revived, state live."""
        now = self._clock() if now is None else now
        lease = self._leases[key]
        lease.revive(now)
        prev = self._last_state.get(key, LIVE)
        if prev != LIVE:
            self._last_state[key] = LIVE
            self._record(now, key, prev, LIVE, via="revive")

    def states(self, now=None):
        now = self._clock() if now is None else now
        return {k: v.state(now) for k, v in self._leases.items()}

    def stats(self):
        """JSON-ready health summary for fleet artifacts."""
        with self._lock:
            return {
                "states": self.states(),
                "transitions": list(self.transitions),
                "dropped_transitions": self.dropped_transitions,
                "zombie_beats": sum(
                    l.zombie_beats for l in self._leases.values()
                ),
            }
