"""`ServeFleet`: a self-healing fleet of `SubgridService` replicas.

PR 3's service is one in-process server; its death loses every
in-flight request, and one server is one chip's throughput. The fleet
runs **N replicas** (threads, one per simulated chip — each over its
own prepared forward, the way one process owns one TPU in the
DaggerFFT/TPU-DFT deployments, arXiv 2601.12209 / 2002.03260) behind a
routing front, supervised so that replica death is an *absorbed* event:

* **Routing** — rendezvous (highest-random-weight) hashing of the
  subgrid column ``off0`` over the live replicas. Stable by
  construction: each column has one preferred replica (whose column
  LRU therefore stays hot for it), a dead replica's columns
  redistribute over the survivors without disturbing anyone else's
  assignment, and they return when it is restored. Every routing
  decision passes the ``fleet.route`` fault site (injected route
  faults are retried with the PR-4 backoff).
* **Health** — each replica's pump loop beats a `HealthLease`
  (`serve.health`); the supervisor grades leases every tick, probes
  suspects (``fleet.health.probe`` site), and **revokes** dead ones.
  A revoked replica's lease latches: zombie beats are ignored until an
  explicit restore.
* **Circuit breakers** — one `resilience.breaker.CircuitBreaker` per
  replica. Lease revocation trips it open (and consecutive request
  failures open it the classic way); while open the router skips the
  replica; after the jittered reopen delay, half-open probe requests
  flow and their successes close it.
* **Zero-loss failover** — the fleet keeps a ledger of every admitted
  request. When a replica dies (its pump raises `WorkerKilled` — the
  ``fleet.replica.kill`` site — or its lease is revoked), the
  supervisor re-routes its queued *and* in-flight requests to
  survivors with the PR-4 jittered backoff ladder between attempts.
  Results are bit-identical wherever they run (the engine is
  deterministic), and an admitted deadline-less request is never
  dropped — admission is the only door that sheds.
* **Brownout** — fleet-wide overload policy driven by the PR-5 journey
  decomposition: when the recent queue-wait share of request latency
  crosses ``brownout_share`` (requests spend their life waiting, not
  computing), the fleet steps down a ladder — rung 1 sheds
  lowest-priority submissions at the door with a structured
  ``retry_after_s`` hint; rung 2 degrades every replica to per-request
  dispatch (``max_batch = 1``) so high-priority requests stop queueing
  behind coalesced batches. Both rungs are recorded in the PR-4
  degradation ledger and reversed with hysteresis when pressure clears.
* **Hedged sends** — a request still pending past its p99 budget
  (``hedge_factor`` x the fleet's rolling p99) is duplicated onto a
  second replica; the first completion wins (idempotent), the loser is
  discarded. One hedge per request.

Drive it with ``start()`` (replica pumps + supervisor thread) and
``submit(...).wait()``, or deterministically with ``tick(now)`` and
manual service pumps (tests). ``bench.py --fleet`` is the kill/restore
drill; see docs/serving.md for the architecture walk-through.
"""

from __future__ import annotations

import itertools
import logging
import random
import threading
import time

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace
from ..obs.tower import ControlTower
from ..resilience import degrade as _degrade
from ..resilience.breaker import CircuitBreaker
from ..resilience.faults import WorkerKilled, fault_point as _fault_point
from ..resilience.retry import backoff_delay, retry_transient
from .health import HealthLease, HealthMonitor, REVOKED
from .queue import (
    RequestResult,
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_SHED,
)

__all__ = ["FleetRequest", "Replica", "ServeFleet"]

log = logging.getLogger("swiftly-tpu.fleet")

_FLEET_IDS = itertools.count()
_LAT_RING = 4096  # newest-wins fleet latency samples for the p99 budget


def _rendezvous_score(off0, rid):
    """Deterministic 32-bit mix of (column, replica) — the
    highest-random-weight routing score. Pure integer arithmetic:
    stable across processes and platforms (unlike ``hash()``)."""
    x = (int(off0) * 0x9E3779B1 ^ (int(rid) + 0x85EBCA6B)) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x045D9F3B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def _replica_telemetry(service):
    """Tower-source adapter over one replica's service: the counters/
    stages contract `ControlTower.fleet_telemetry` sums fleet-wide.
    ``.get`` defaults keep it safe over stub services (tests)."""

    def export():
        s = service.stats()
        out = {
            "counters": {
                "served": s.get("n_served", 0),
                "requests": s.get("n_requests", 0),
                "shed": s.get("n_shed", 0),
                "retries": s.get("retries", 0),
                "cache_hits": s.get("cache_hits", 0),
            },
            "p99_ms": s.get("p99_ms", 0.0),
        }
        j = s.get("journey")
        if j:
            stages = {}
            for seg in ("queue", "compute", "transfer"):
                seg_info = j.get(seg)
                if seg_info:
                    stages[f"serve.journey.{seg}"] = {
                        "count": int(j.get("n", 0)),
                        "total_s": float(seg_info.get("total_s", 0.0)),
                    }
            if stages:
                out["stages"] = stages
        return out

    return export


def _fabric_telemetry(fabric):
    """Tower-source adapter over the shared cache fabric."""

    def export():
        s = fabric.stats()
        return {
            "counters": {
                k: s.get(k, 0)
                for k in ("l1_hits", "l2_hits", "misses", "promotions",
                          "l1_evictions", "rolls", "dedup_hits",
                          "dedup_computes")
            },
            "hit_ratio": s.get("hit_ratio", 0.0),
            "stream_version": s.get("stream_version", 0),
            "views": s.get("views", 0),
        }

    return export


class FleetRequest:
    """Client-facing handle for one fleet request.

    Survives failover and hedging: the underlying per-replica
    `SubgridRequest` may be re-issued on another replica, but the
    client holds ONE handle whose completion is idempotent —
    the first terminal result wins, later (hedge-loser / zombie)
    completions are discarded.
    """

    __slots__ = (
        "config", "priority", "req_id", "submit_t", "deadline_t",
        "result", "replica_trail", "_event", "_lock", "_clock",
    )

    def __init__(self, config, priority=0, deadline_s=None,
                 clock=time.monotonic):
        self.config = config
        self.priority = int(priority)
        self.req_id = next(_FLEET_IDS)
        self._clock = clock
        self.submit_t = clock()
        self.deadline_t = (
            None if deadline_s is None
            else self.submit_t + float(deadline_s)
        )
        self.result = None
        self.replica_trail = []  # rids this request was offered to
        self._event = threading.Event()
        self._lock = threading.Lock()

    @property
    def done(self):
        return self.result is not None

    def wait(self, timeout=None):
        """Block until terminal; returns the `RequestResult` (or None
        on wait timeout)."""
        self._event.wait(timeout)
        return self.result

    def _complete(self, result, now=None):
        """First terminal result wins; returns False for losers."""
        with self._lock:
            if self.result is not None:
                return False
            now = self._clock() if now is None else now
            # fleet latency: client submit -> fleet completion (spans
            # failovers and hedges, not just the winning replica's leg)
            result.latency_s = max(0.0, now - self.submit_t)
            self.result = result
        self._event.set()
        return True

    def __repr__(self):
        return (
            f"FleetRequest(#{self.req_id}, off0={self.config.off0}, "
            f"off1={self.config.off1}, prio={self.priority})"
        )


class Replica:
    """One fleet member: a `SubgridService` plus its pump thread,
    health lease and circuit breaker.

    The pump loop is where simulated chip death lands: every iteration
    calls the ``fleet.replica.kill`` fault site and honours the
    `kill()` drill hook; a raised `WorkerKilled` (a BaseException — it
    tears through like a real SIGKILL) marks the replica dead and ends
    the thread. The service object and its prepared forward survive,
    so `restore()` is just a fresh pump thread over warm state.
    """

    def __init__(self, rid, service, lease, breaker, poll_s=0.001):
        self.rid = int(rid)
        self.service = service
        self.lease = lease
        self.breaker = breaker
        self.poll_s = float(poll_s)
        self.dead = False
        self._kill_flag = False
        self._stop = False
        self._thread = None

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(f"replica {self.rid} already running")
        self._stop = False
        self._kill_flag = False
        self.dead = False
        trace_ctx = _trace.current()
        self._thread = threading.Thread(
            target=self._run, args=(trace_ctx,),
            name=f"fleet-replica-{self.rid}", daemon=True,
        )
        self._thread.start()
        return self

    def _run(self, trace_ctx=0):
        _trace.adopt(trace_ctx)
        _trace.name_track(threading.get_native_id(),
                          f"replica-{self.rid}")
        try:
            while not self._stop:
                if self._kill_flag:
                    raise WorkerKilled(
                        f"replica {self.rid} killed (drill hook)"
                    )
                self.lease.beat()
                if len(self.service.queue):
                    # the kill site fires between "holds pending work"
                    # and "serves it" — a kill here strands a real
                    # backlog, the case failover exists for (an idle
                    # replica's death is trivially lossless and would
                    # otherwise win every call-indexed schedule, since
                    # idle pumps spin far faster than serving ones)
                    _fault_point("fleet.replica.kill")
                if self.service.pump_once() == 0:
                    time.sleep(self.poll_s)
        except WorkerKilled as exc:
            # simulated chip death: stop beating, leave the queue for
            # the supervisor's failover sweep
            self.dead = True
            _metrics.count("fleet.replica_deaths")
            _trace.instant("fleet.replica_death", cat="fleet",
                           replica=self.rid, error=str(exc))
            _recorder.record("fleet", "fleet.replica_death",
                             f"replica {self.rid}: {exc}")
            log.warning("replica %d died: %s", self.rid, exc)

    def alive(self):
        return (
            not self.dead
            and self._thread is not None
            and self._thread.is_alive()
        )

    def kill(self):
        """Drill hook: the pump raises `WorkerKilled` on its next
        iteration (equivalent to a ``fleet.replica.kill`` fault)."""
        self._kill_flag = True

    def stop(self, timeout=5.0):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def restore(self):
        """Fresh pump thread over the surviving service state (warm
        forward, warm LRU); the lease/breaker are the caller's to
        revive."""
        if self.alive():
            raise RuntimeError(f"replica {self.rid} is still alive")
        self._thread = None
        return self.start()

    def __repr__(self):
        return (
            f"Replica({self.rid}, dead={self.dead}, "
            f"alive={self.alive()})"
        )


class _Entry:
    """Fleet-side ledger record for one pending request."""

    __slots__ = ("freq", "subs", "reroutes", "not_before", "hedged",
                 "shed_rids", "shed_hints", "admitted")

    def __init__(self, freq):
        self.freq = freq
        self.subs = []         # [(rid, SubgridRequest, is_hedge), ...]
        self.reroutes = 0
        self.not_before = 0.0  # backoff gate for the next reroute
        self.hedged = False
        self.shed_rids = set()
        self.shed_hints = []
        self.admitted = False


class ServeFleet:
    """N supervised `SubgridService` replicas behind one front door.

    :param replica_factory: ``fn(rid) -> SubgridService`` — builds one
        replica's service (typically over its own prepared forward)
    :param n_replicas: fleet size
    :param lease_interval_s / miss_suspect / miss_revoke: heartbeat
        lease grading (see `serve.health.HealthLease`)
    :param breaker_threshold / breaker_reopen_s / breaker_max_reopen_s
        / half_open_probes: per-replica circuit breaker tuning
    :param hedge_budget_s: age past which a pending request is hedged
        onto a second replica; None derives it as ``hedge_factor`` x
        the fleet's rolling p99 (floored at ``hedge_min_s``); 0
        disables hedging
    :param brownout_share: recent queue-wait share of latency that
        triggers the brownout ladder
    :param brownout_min_depth: total queued requests below which
        brownout never triggers (an idle fleet has no overload)
    :param brownout_min_priority: rung-1 sheds submissions with
        ``priority <`` this floor
    :param brownout_escalate_s: seconds at rung 1 before rung 2
        (per-request dispatch)
    :param failover_backoff_s: base of the jittered backoff ladder
        between failover reroute attempts
    :param supervise_interval_s: supervisor thread tick period
    :param seed: seeds the breakers' reopen jitter (deterministic
        drills)
    :param clock: injectable monotonic clock (tests drive `tick(now)`)
    :param hbm_budget_bytes: fleet-wide projected-HBM admission cap
        (None disables). Each pending request prices
        ``request_bytes`` and each distinct pending column per replica
        prices ``column_bytes`` — the unified plan compiler's serve
        pricing (`plan.compile_plan(...).serve`); a submission whose
        projection would cross the cap is shed at the fleet door with
        a structured ``retry_after_s``, before any replica queue is
        touched.
    :param request_bytes / column_bytes: the admission cost model
        (typically ``plan.serve.request_bytes`` /
        ``plan.serve.column_bytes``)
    :param fabric: optional `cache.SharedStreamTier` — the shared cache
        fabric. When set, the replica factory is called as
        ``fn(rid, feed_view)`` and must build its service over that
        view (ONE resident stream copy for the whole fleet; a factory
        that builds its own per-replica cache defeats the fabric), and
        `post_facet_update` rolls the fabric once instead of building N
        feeds.
    :param drain_timeout_s: grace a draining replica (autoscale
        scale-in) gets to finish its backlog before the fleet
        force-revokes its lease and fails the remainder over — the
        zero-loss escape hatch, not the normal path
    :param tower: optional `obs.tower.ControlTower`; one is built on
        the fleet's clock when not given. Every replica registers a
        telemetry source with it, the supervisor tick samples its
        windowed signals ONCE and hands that sample to both the
        brownout ladder and the autoscaler, and its SLOs are evaluated
        every tick.
    """

    def __init__(self, replica_factory, n_replicas=3, *,
                 lease_interval_s=0.05, miss_suspect=2, miss_revoke=5,
                 breaker_threshold=3, breaker_reopen_s=0.5,
                 breaker_max_reopen_s=8.0, half_open_probes=2,
                 hedge_budget_s=None, hedge_factor=2.0, hedge_min_s=0.05,
                 brownout_share=0.6, brownout_min_depth=8,
                 brownout_min_priority=1, brownout_escalate_s=0.25,
                 failover_backoff_s=0.01, failover_backoff_max_s=0.5,
                 supervise_interval_s=0.002, poll_s=0.001, seed=0,
                 clock=time.monotonic, hbm_budget_bytes=None,
                 request_bytes=0, column_bytes=0, fabric=None,
                 drain_timeout_s=30.0, tower=None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self._clock = clock
        self.hbm_budget_bytes = hbm_budget_bytes
        self.request_bytes = int(request_bytes)
        self.column_bytes = int(column_bytes)
        self.hedge_budget_s = hedge_budget_s
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_s = float(hedge_min_s)
        self.brownout_share = float(brownout_share)
        self.brownout_min_depth = int(brownout_min_depth)
        self.brownout_min_priority = int(brownout_min_priority)
        self.brownout_escalate_s = float(brownout_escalate_s)
        self.failover_backoff_s = float(failover_backoff_s)
        self.failover_backoff_max_s = float(failover_backoff_max_s)
        self.supervise_interval_s = float(supervise_interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.fabric = fabric
        # the autoscaler (serve.autoscale.FleetAutoscaler) attaches
        # here; the supervisor tick evaluates it when present
        self.autoscaler = None
        # the control tower: every replica registers a telemetry
        # source; the supervisor tick samples its signals once for the
        # brownout ladder + autoscaler and evaluates its SLOs
        self.tower = tower if tower is not None else ControlTower(
            clock=clock
        )
        self.last_post_mortem = None
        # replica construction state, kept so `add_replica` can scale
        # out after __init__ with the same factory and tuning
        self._replica_factory = replica_factory
        self._lease_kw = dict(
            interval_s=lease_interval_s, miss_suspect=miss_suspect,
            miss_revoke=miss_revoke,
        )
        self._breaker_kw = dict(
            failure_threshold=breaker_threshold,
            reopen_s=breaker_reopen_s,
            max_reopen_s=breaker_max_reopen_s,
            half_open_probes=half_open_probes,
        )
        self._seed = int(seed)
        self._poll_s = float(poll_s)
        self.monitor = HealthMonitor(probe=self._probe, clock=clock)
        self._lock = threading.RLock()
        self._replicas = {}
        self._draining = {}  # rid -> drain start time
        self._retired = []   # final stats rows of drained replicas
        self._next_rid = 0
        for _ in range(int(n_replicas)):
            self._build_replica()
        self._pending = {}  # freq.req_id -> _Entry
        self._counts = {
            "requests": 0, "served": 0, "shed": 0, "expired": 0,
            "quarantined": 0, "failovers": 0, "reroutes": 0,
            "hedges": 0, "hedge_wins": 0, "route_faults": 0,
            "brownout_sheds": 0, "hbm_sheds": 0, "restores": 0,
            "scale_outs": 0, "drains": 0,
        }
        self._lat = []
        self._lat_i = 0
        self._p99_cache = 0.0
        self._p99_dirty = 0
        self._brownout_level = 0
        self._brownout_since = 0.0
        self._brownout_events = []
        self._saved_max_batch = {}
        self._sup_stop = False
        self._sup_thread = None
        # windowed signals: late-bound so instance-attribute overrides
        # (drill hooks) and live replica sets are always honored
        self.tower.register_signal(
            "fleet.queue_share", lambda: self.queue_share()
        )
        self.tower.register_signal(
            "fleet.queued_depth", lambda: float(self.queued_depth())
        )
        self.tower.register_signal(
            "fleet.p99_ms", lambda: self._rolling_p99() * 1e3
        )
        self.tower.register_signal("fleet.shed_rate", self._shed_rate)
        self.tower.register_signal(
            "fleet.brownout_level",
            lambda: float(self._brownout_level),
        )
        self.tower.register_source(
            "fleet", self._fleet_telemetry, kind="fleet"
        )
        if fabric is not None:
            self.tower.register_signal(
                "cache.hit_ratio",
                lambda: fabric.stats().get("hit_ratio", 0.0),
            )
            self.tower.register_source(
                "fabric", _fabric_telemetry(fabric), kind="cache"
            )

    def _shed_rate(self):
        n = self._counts["requests"]
        return (self._counts["shed"] / n) if n else 0.0

    def _fleet_telemetry(self):
        """The fleet's own tower source: door counters (prefixed so
        they never collide with per-replica counter names in the
        fleet-wide totals)."""
        with self._lock:
            counters = {f"fleet.{k}": v for k, v in self._counts.items()}
            counters["fleet.pending"] = len(self._pending)
        counters["fleet.n_replicas"] = len(self._replicas)
        counters["fleet.brownout_level"] = self._brownout_level
        return {"counters": counters}

    # -- topology ------------------------------------------------------------

    def _build_replica(self):
        """Construct and register one replica (service via the stored
        factory — with a fabric, over its feed view — plus lease and
        breaker); returns it. Does NOT start the pump."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            if self.fabric is not None:
                service = self._replica_factory(
                    rid, self.fabric.view(rid)
                )
            else:
                service = self._replica_factory(rid)
            lease = HealthLease(
                owner=f"replica-{rid}", clock=self._clock,
                **self._lease_kw,
            )
            breaker = CircuitBreaker(
                name=f"replica-{rid}",
                rng=random.Random(self._seed + rid + 1),
                clock=self._clock, **self._breaker_kw,
            )
            self.monitor.register(rid, lease)
            replica = Replica(
                rid, service, lease, breaker, poll_s=self._poll_s
            )
            self._replicas[rid] = replica
            self.tower.register_source(
                f"replica-{rid}", _replica_telemetry(service),
                kind="replica",
            )
            return replica

    @property
    def replicas(self):
        return dict(self._replicas)

    @property
    def draining(self):
        """rids currently draining toward retirement (scale-in)."""
        with self._lock:
            return set(self._draining)

    def replica(self, rid):
        return self._replicas[rid]

    def _probe(self, rid):
        replica = self._replicas.get(rid)
        return replica is not None and replica.alive()

    def preferred_replica(self, off0):
        """The rendezvous winner for a column over the FULL fleet
        (health-blind — the router's starting point; drills use it to
        aim traffic at a specific replica)."""
        return max(
            list(self._replicas),
            key=lambda rid: _rendezvous_score(off0, rid),
        )

    # -- routing -------------------------------------------------------------

    def _routable(self, rid, exclude):
        if rid in exclude or rid in self._draining:
            return False
        replica = self._replicas.get(rid)
        if replica is None:
            return False
        return not replica.dead and not replica.lease.revoked

    def _pick(self, off0, exclude, now):
        """The replica one request routes to, or None (every candidate
        excluded, revoked, or breaker-denied). Candidates are tried in
        rendezvous-score order; the breaker gate runs only on actual
        candidates so half-open probe slots are spent on real sends."""
        try:
            retry_transient(
                lambda: _fault_point("fleet.route"),
                site="fleet.route", max_attempts=3,
                base_s=0.001, max_s=0.01,
                on_retry=self._count_route_fault,
            )
        except Exception:  # noqa: BLE001 - exhausted route retries
            self._counts["route_faults"] += 1
            _metrics.count("fleet.route_exhausted")
            return None
        order = sorted(
            (rid for rid in list(self._replicas)
             if self._routable(rid, exclude)),
            key=lambda rid: _rendezvous_score(off0, rid),
            reverse=True,
        )
        for rid in order:
            replica = self._replicas.get(rid)
            if replica is not None and replica.breaker.allow(now):
                return rid
        return None

    def _count_route_fault(self, _attempt, _exc, _delay):
        self._counts["route_faults"] += 1
        _metrics.count("fleet.route_faults")

    # -- submission ----------------------------------------------------------

    def submit(self, config, priority=0, deadline_s=None):
        """Admit one request into the fleet; returns a `FleetRequest`.

        Brownout rung 1 and all-replicas-shed both complete the handle
        immediately with ``status == "shed"`` and an actionable
        ``retry_after_s`` — the fleet door never blocks a client."""
        now = self._clock()
        freq = FleetRequest(
            config, priority=priority, deadline_s=deadline_s,
            clock=self._clock,
        )
        self._counts["requests"] += 1
        _metrics.count("fleet.requests")
        if (
            self._brownout_level >= 1
            and priority < self.brownout_min_priority
        ):
            self._counts["brownout_sheds"] += 1
            self._counts["shed"] += 1
            _metrics.count("fleet.brownout_sheds")
            _trace.instant("fleet.brownout_shed", cat="fleet",
                           request_id=freq.req_id)
            freq._complete(
                RequestResult(
                    STATUS_SHED, shed_reason="brownout",
                    retry_after_s=self._brownout_retry_hint(),
                ),
                now,
            )
            return freq
        if (
            self.hbm_budget_bytes is not None
            and self.projected_fleet_bytes(off0=freq.config.off0)
            > self.hbm_budget_bytes
        ):
            # fleet-wide admission cost cap: the serving-time analogue
            # of the streamed executors' HBM-budgeted sizing, priced
            # by the plan compiler's serve block
            self._counts["hbm_sheds"] += 1
            self._counts["shed"] += 1
            _metrics.count("fleet.hbm_sheds")
            _trace.instant("fleet.hbm_shed", cat="fleet",
                           request_id=freq.req_id)
            freq._complete(
                RequestResult(
                    STATUS_SHED, shed_reason="hbm",
                    retry_after_s=self._brownout_retry_hint(),
                ),
                now,
            )
            return freq
        entry = _Entry(freq)
        self._route_and_send(entry, now)
        if not freq.done:
            with self._lock:
                self._pending[freq.req_id] = entry
        return freq

    def _route_and_send(self, entry, now):
        """Offer one request to replicas in routing order until one
        admits it. Exhaustion sheds a fresh submission (backpressure at
        the fleet door) but only DEFERS an already-admitted request —
        failover never drops admitted work."""
        freq = entry.freq
        tried = set(entry.shed_rids)
        while True:
            rid = self._pick(freq.config.off0, tried, now)
            if rid is None:
                break
            tried.add(rid)
            replica = self._replicas.get(rid)
            if replica is None:  # retired between pick and send
                continue
            deadline_s = (
                None if freq.deadline_t is None
                else max(0.0, freq.deadline_t - self._clock())
            )
            sub = replica.service.submit(
                freq.config, priority=freq.priority,
                deadline_s=deadline_s,
            )
            freq.replica_trail.append(rid)
            res = sub.result
            if res is not None and res.status == STATUS_SHED:
                entry.shed_rids.add(rid)
                if res.retry_after_s is not None:
                    entry.shed_hints.append(res.retry_after_s)
                continue
            if res is not None and not res.ok:
                # expired at a replica door: terminal, surface it
                self._finish(entry, res, rid, False, now)
                return
            entry.subs.append((rid, sub, False))
            entry.admitted = True
            entry.shed_rids.clear()
            return
        hint = min(entry.shed_hints) if entry.shed_hints else 0.05
        if not entry.admitted:
            self._counts["shed"] += 1
            _metrics.count("fleet.shed")
            freq._complete(
                RequestResult(
                    STATUS_SHED, shed_reason="fleet",
                    retry_after_s=hint,
                ),
                now,
            )
            return
        # admitted work: defer with the PR-4 jittered backoff ladder
        delay = max(
            hint,
            backoff_delay(
                entry.reroutes, base_s=self.failover_backoff_s,
                max_s=self.failover_backoff_max_s,
            ),
        )
        entry.reroutes += 1
        self._counts["reroutes"] += 1
        _metrics.count("fleet.reroutes")
        entry.not_before = now + delay
        entry.shed_rids.clear()

    # -- supervision ---------------------------------------------------------

    def tick(self, now=None):
        """One supervision pass: grade health (failing over revoked
        replicas), settle completed sends, re-route abandoned ones,
        hedge laggards, update the brownout ladder. The supervisor
        thread calls this every ``supervise_interval_s``; tests call it
        directly with an explicit ``now``.

        The tower samples every windowed signal ONCE per pass and that
        sample is what the brownout ladder and the autoscaler both
        consume — one clock, one value, no consumer-private
        recomputation (decisions stay bit-identical to when each read
        the raw signal itself, because the sample IS that read)."""
        now = self._clock() if now is None else now
        for rid, _frm, to in self.monitor.check(now):
            if to == REVOKED:
                self._on_revoked(rid, now)
        with self._lock:
            entries = list(self._pending.values())
        for entry in entries:
            self._scan_entry(entry, now)
        sample = self.tower.tick(now)
        self._update_brownout(now, sample)
        self._finalize_drains(now)
        if self.autoscaler is not None:
            try:
                self.autoscaler.tick(now, signals=sample)
            except Exception:  # noqa: BLE001 - policy must not kill ticks
                _metrics.count("fleet.autoscaler_errors")
                log.exception("autoscaler tick failed")

    def _on_revoked(self, rid, now):
        """A replica's lease was revoked: trip its breaker and strand
        its queue (the ledger scan re-routes every abandoned request)."""
        replica = self._replicas.get(rid)
        if replica is None:  # retired while the transition was in flight
            return
        replica.breaker.trip(now, reason="health lease revoked")
        stranded = replica.service.queue.drain()
        _metrics.count("fleet.revocations")
        _trace.instant("fleet.replica_revoked", cat="fleet",
                       replica=rid, stranded=len(stranded))
        _degrade.record(
            "fleet", "replica_revoked",
            f"replica {rid}: lease revoked, {len(stranded)} queued "
            f"request(s) stranded for failover",
        )
        log.warning(
            "replica %d revoked; failing over %d stranded request(s)",
            rid, len(stranded),
        )

    def _scan_entry(self, entry, now):
        freq = entry.freq
        if freq.done:
            with self._lock:
                self._pending.pop(freq.req_id, None)
            return
        if freq.deadline_t is not None and now > freq.deadline_t:
            self._finish(
                entry, RequestResult(STATUS_EXPIRED, error="deadline"),
                None, False, now,
            )
            return
        still = []
        needs_reroute = False
        for rid, sub, is_hedge in entry.subs:
            res = sub.result
            if res is not None:
                if res.ok:
                    self._finish(entry, res, rid, is_hedge, now)
                    return
                if res.status == STATUS_SHED:
                    entry.shed_rids.add(rid)
                    if res.retry_after_s is not None:
                        entry.shed_hints.append(res.retry_after_s)
                    needs_reroute = True
                    continue
                # expired / quarantined: terminal, surface truthfully
                self._finish(entry, res, rid, is_hedge, now)
                return
            replica = self._replicas.get(rid)
            if replica is None or replica.dead or replica.lease.revoked:
                # in-flight on a dead (or retired) replica: abandoned —
                # failover
                self._counts["failovers"] += 1
                _metrics.count("fleet.failover")
                if replica is not None:
                    replica.breaker.record_failure(
                        now, reason="request abandoned by dead replica"
                    )
                _trace.instant("fleet.failover", cat="fleet",
                               request_id=freq.req_id, replica=rid)
                needs_reroute = True
                continue
            still.append((rid, sub, is_hedge))
        entry.subs = still
        if not still:
            if needs_reroute or now >= entry.not_before:
                if now >= entry.not_before:
                    self._route_and_send(entry, now)
            return
        self._maybe_hedge(entry, now)

    def _finish(self, entry, result, rid, is_hedge, now):
        won = entry.freq._complete(result, now)
        with self._lock:
            self._pending.pop(entry.freq.req_id, None)
        if not won:
            return
        status = result.status
        if status == STATUS_OK:
            self._counts["served"] += 1
            _metrics.count("fleet.served")
            if rid is not None:
                winner = self._replicas.get(rid)
                if winner is not None:
                    winner.breaker.record_success(now)
            if is_hedge:
                self._counts["hedge_wins"] += 1
                _metrics.count("fleet.hedge_wins")
            self._observe_latency(result.latency_s)
        elif status == STATUS_SHED:
            self._counts["shed"] += 1
            _metrics.count("fleet.shed")
        elif status == STATUS_EXPIRED:
            self._counts["expired"] += 1
            _metrics.count("fleet.expired")
        else:
            self._counts["quarantined"] += 1
            _metrics.count("fleet.quarantined")

    # -- hedging -------------------------------------------------------------

    def _observe_latency(self, latency_s):
        if len(self._lat) < _LAT_RING:
            self._lat.append(latency_s)
        else:
            self._lat[self._lat_i] = latency_s
            self._lat_i = (self._lat_i + 1) % _LAT_RING
        self._p99_dirty += 1

    def _rolling_p99(self):
        if self._p99_dirty >= 32 or (self._p99_cache == 0.0 and self._lat):
            lat = sorted(self._lat)
            self._p99_cache = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
            self._p99_dirty = 0
        return self._p99_cache

    def _hedge_budget(self):
        if self.hedge_budget_s is not None:
            return self.hedge_budget_s
        if len(self._lat) < 32:
            # too few samples for a trustworthy p99: a cold estimate
            # under-prices the budget and hedges the whole backlog
            return float("inf")
        return max(self.hedge_min_s,
                   self.hedge_factor * self._rolling_p99())

    def _maybe_hedge(self, entry, now):
        budget = self._hedge_budget()
        if budget <= 0 or entry.hedged or len(entry.subs) != 1:
            return
        if now - entry.freq.submit_t <= budget:
            return
        rid0 = entry.subs[0][0]
        rid = self._pick(entry.freq.config.off0, {rid0}, now)
        if rid is None:
            return
        replica = self._replicas.get(rid)
        if replica is None:  # retired between pick and send
            return
        deadline_s = (
            None if entry.freq.deadline_t is None
            else max(0.0, entry.freq.deadline_t - self._clock())
        )
        sub = replica.service.submit(
            entry.freq.config, priority=entry.freq.priority,
            deadline_s=deadline_s,
        )
        entry.freq.replica_trail.append(rid)
        entry.hedged = True
        if sub.result is not None and sub.result.status == STATUS_SHED:
            return  # the hedge was shed; the primary still stands
        entry.subs.append((rid, sub, True))
        self._counts["hedges"] += 1
        _metrics.count("fleet.hedges")
        _trace.instant("fleet.hedge", cat="fleet",
                       request_id=entry.freq.req_id, replica=rid)

    # -- brownout ------------------------------------------------------------

    def queue_share(self, window=256):
        """Recent fleet-wide queue-wait share of request latency (the
        PR-5 journey decomposition aggregated over replicas) — the
        brownout trigger signal."""
        total_q = total = 0.0
        for replica in list(self._replicas.values()):
            q, t = replica.service.recent_journey_totals(window)
            total_q += q
            total += t
        return (total_q / total) if total else 0.0

    def queued_depth(self):
        return sum(
            len(r.service.queue)
            for r in list(self._replicas.values())
        )

    def projected_fleet_bytes(self, off0=None):
        """Projected device cost of everything pending fleet-wide,
        priced by the plan compiler's admission model: pending requests
        x ``request_bytes`` plus each replica's distinct pending
        columns x ``column_bytes``. ``off0`` adds the cost of one more
        request for that column (the admission probe)."""
        total = 0
        extra_col = off0 is not None
        for replica in list(self._replicas.values()):
            if replica.dead or replica.lease.revoked:
                continue
            cols = replica.service.queue.columns()
            total += sum(c.count for c in cols) * self.request_bytes
            total += len(cols) * self.column_bytes
            if extra_col and any(c.off0 == off0 for c in cols):
                extra_col = False  # column already priced somewhere
        if off0 is not None:
            total += self.request_bytes
            if extra_col:
                total += self.column_bytes
        return total

    def _brownout_retry_hint(self):
        hints = [
            r.service.queue.retry_after_hint()
            for r in list(self._replicas.values())
        ]
        return min(hints) if hints else 0.05

    def _set_brownout(self, level, now, share):
        prev = self._brownout_level
        if level == prev:
            return
        self._brownout_level = level
        self._brownout_since = now
        if len(self._brownout_events) < 256:
            self._brownout_events.append(
                {"t": round(now, 6), "from": prev, "to": level,
                 "queue_share": round(share, 4)}
            )
        action = f"brownout_level_{level}"
        _metrics.count(f"fleet.{action}")
        _degrade.record(
            "fleet", action,
            f"queue share {share:.3f} vs threshold "
            f"{self.brownout_share:.3f}",
        )
        if level >= 2 and prev < 2:
            # rung 2: per-request dispatch — coalesced batches stop
            # head-of-line-blocking the high-priority traffic that
            # survived rung 1's shed
            for rid, replica in list(self._replicas.items()):
                self._saved_max_batch[rid] = (
                    replica.service.scheduler.max_batch
                )
                replica.service.scheduler.max_batch = 1
        elif level < 2 and prev >= 2:
            for rid, saved in self._saved_max_batch.items():
                self._replicas[rid].service.scheduler.max_batch = saved
            self._saved_max_batch.clear()

    def _update_brownout(self, now, sample=None):
        if sample is not None and "fleet.queue_share" in sample:
            share = sample["fleet.queue_share"]
            depth = int(sample.get("fleet.queued_depth", 0))
        else:
            share = self.queue_share()
            depth = self.queued_depth()
        overloaded = (
            share > self.brownout_share
            and depth >= self.brownout_min_depth
        )
        level = self._brownout_level
        if overloaded:
            if level == 0:
                self._set_brownout(1, now, share)
            elif (
                level == 1
                and now - self._brownout_since > self.brownout_escalate_s
            ):
                self._set_brownout(2, now, share)
        elif level and (
            share < 0.8 * self.brownout_share
            or depth < max(1, self.brownout_min_depth // 2)
        ):
            # hysteresis: step DOWN one rung at a time
            self._set_brownout(level - 1, now, share)

    @property
    def brownout_level(self):
        return self._brownout_level

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Start every replica pump plus the supervisor thread."""
        for replica in list(self._replicas.values()):
            replica.start()
        self._sup_stop = False
        trace_ctx = _trace.current()
        self._sup_thread = threading.Thread(
            target=self._sup_run, args=(trace_ctx,),
            name="fleet-supervisor", daemon=True,
        )
        self._sup_thread.start()
        return self

    def _sup_run(self, trace_ctx=0):
        _trace.adopt(trace_ctx)
        _trace.name_track(threading.get_native_id(), "fleet-supervisor")
        while not self._sup_stop:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - supervisor must survive
                _metrics.count("fleet.supervisor_errors")
                log.exception("fleet supervisor tick failed")
            time.sleep(self.supervise_interval_s)

    def drain(self, timeout=None):
        """Block until no fleet request is pending (the supervisor —
        thread or caller-driven ticks — completes them); returns True
        when drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if not self._pending:
                    return True
            if self._sup_thread is None:
                self.tick()
            if deadline is not None and time.monotonic() > deadline:
                with self._lock:
                    return not self._pending
            time.sleep(0.002)

    def post_facet_update(self, engine, new_facet_tasks, **update_kw):
        """Fleet-wide incremental facet update: run the
        `delta.IncrementalForward` update ONCE (one delta stream + one
        cache patch, or its degradation ladder), then propagate the
        patched feed, the new stream version AND the new facet stack to
        every replica's service. Replica pumps keep serving while the
        engine patches: the spill cache is marked mid-patch for the
        whole rewrite window (`utils.spill.SpillCache.begin_patch`), so
        a live feed's lookups raise and requests fall back to compute
        at the version they were admitted under — a partially-patched
        row can never serve. Each replica then drains its own in-flight
        requests before adopting the feed and rebuilding its forward
        over the new stack, so version pinning holds per replica; there
        is no fleet-wide stop-the-world and no cache flush.
        """
        report = engine.update(new_facet_tasks, **update_kw)
        if self.fabric is not None:
            # ONE fabric roll: the shared L2 adopts the engine's new
            # stream version (index rebuilt only on replay), every
            # replica view is re-pointed in place and its hot-row L1
            # cleared iff the version moved — no per-replica re-record
            # and still exactly one resident stream copy
            self.fabric.roll(report)
            for rid, replica in sorted(self.replicas.items()):
                replica.service.post_facet_update(
                    report=report, feed=self.fabric.view(rid),
                    new_facet_tasks=engine.facet_tasks,
                )
        else:
            for replica in list(self._replicas.values()):
                # a fresh feed per replica: feeds carry per-feed
                # stale/hit state and the captured version, so replicas
                # must not share one object — and each replica adopts
                # the new stack into ITS OWN forward (forwards are
                # per-pump-thread state)
                replica.service.post_facet_update(
                    report=report, feed=engine.feed(),
                    new_facet_tasks=engine.facet_tasks,
                )
        self._counts["facet_updates"] = (
            self._counts.get("facet_updates", 0) + 1
        )
        _metrics.count("fleet.facet_updates")
        _trace.instant(
            "fleet.facet_update", cat="fleet",
            stream_version=report.get("stream_version"),
            mode=report.get("mode"),
        )
        return report

    # -- elasticity ----------------------------------------------------------

    def add_replica(self):
        """Scale out: one more replica from the stored factory and
        tuning, pump started iff the fleet is running. With a cache
        fabric attached the newcomer's service is built over a feed
        VIEW of the one resident stream — scale-out costs an L1, never
        a stream copy. Returns the new rid."""
        replica = self._build_replica()
        if self._sup_thread is not None:
            replica.start()
        self._counts["scale_outs"] += 1
        _metrics.count("fleet.scale_outs")
        _trace.instant("fleet.scale_out", cat="fleet",
                       replica=replica.rid)
        log.info("scale-out: replica %d joins (%d replicas)",
                 replica.rid, len(self._replicas))
        return replica.rid

    def begin_drain(self, rid):
        """Initiate zero-loss scale-in for one replica: routing stops
        immediately (`_routable`), its queued and in-flight work
        completes (or fails over), and a later supervision pass retires
        the pump (`_finalize_drains`). Non-blocking and idempotent —
        the autoscaler calls this from inside the supervisor tick."""
        with self._lock:
            if rid not in self._replicas:
                raise KeyError(f"no replica {rid}")
            if rid in self._draining:
                return
            self._draining[rid] = self._clock()
        _metrics.count("fleet.drains_begun")
        _trace.instant("fleet.drain_begin", cat="fleet", replica=rid)
        log.info("drain: replica %d stops taking traffic", rid)

    def _inflight_on(self, rid):
        """Pending fleet requests with a live sub on this replica — a
        racy snapshot; the drain path re-checks every pass."""
        with self._lock:
            entries = list(self._pending.values())
        return sum(
            1
            for entry in entries
            for sub_rid, _sub, _hedge in list(entry.subs)
            if sub_rid == rid
        )

    def _finalize_drains(self, now):
        """Retire draining replicas whose work is gone; force the
        failover path on laggards past ``drain_timeout_s`` so scale-in
        can never wedge the fleet (the requests still complete
        elsewhere — zero loss, slower)."""
        with self._lock:
            items = list(self._draining.items())
        for rid, since in items:
            replica = self._replicas.get(rid)
            if replica is None:
                with self._lock:
                    self._draining.pop(rid, None)
                continue
            if replica.dead or replica.lease.revoked:
                # the health path already failed its work over
                self._retire(rid, reason="dead_during_drain")
                continue
            if (
                len(replica.service.queue) == 0
                and self._inflight_on(rid) == 0
            ):
                self._retire(rid, reason="drained")
                continue
            if now - since > self.drain_timeout_s:
                log.warning(
                    "drain of replica %d exceeded %.1fs; forcing "
                    "failover", rid, self.drain_timeout_s,
                )
                _metrics.count("fleet.drains_forced")
                _recorder.record("fleet", "fleet.drain_forced",
                                 f"replica {rid} past "
                                 f"{self.drain_timeout_s:.1f}s")
                if _recorder.enabled():
                    # a forced drain is a post-mortem trigger: snapshot
                    # the black box for the drill artifact to stamp
                    self.last_post_mortem = _recorder.post_mortem(
                        "forced_drain", reason=f"replica {rid}"
                    )
                # revoke the lease: the monitor's next pass strands the
                # queue and the ledger scan re-routes every sub
                replica.lease.revoke()

    def _retire(self, rid, reason="drained"):
        """Remove one replica from the fleet: pump stopped, lease
        unregistered (so its silence can't fire a phantom failover),
        fabric view dropped, final serving counters kept in the
        retired ledger."""
        with self._lock:
            replica = self._replicas.pop(rid, None)
            self._draining.pop(rid, None)
        if replica is None:
            return
        replica.stop(timeout=2.0)
        self.monitor.unregister(rid)
        self.tower.unregister_source(f"replica-{rid}")
        if self.fabric is not None:
            self.fabric.drop_view(rid)
        s = replica.service.stats()
        self._retired.append({
            "id": rid, "reason": reason,
            "served": s["n_served"], "requests": s["n_requests"],
            "shed": s["n_shed"],
        })
        self._counts["drains"] += 1
        _metrics.count("fleet.drains")
        _trace.instant("fleet.replica_retired", cat="fleet",
                       replica=rid, reason=reason)
        _recorder.record("fleet", "fleet.replica_retired",
                         f"replica {rid}: {reason}")
        log.info("drain: replica %d retired (%s; %d replicas left)",
                 rid, reason, len(self._replicas))

    def drain_replica(self, rid, timeout=None):
        """Blocking convenience over `begin_drain`: returns True once
        the replica is retired, False on timeout. Drives supervision
        itself when no supervisor thread is running."""
        self.begin_drain(rid)
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            with self._lock:
                if rid not in self._replicas:
                    return True
            if self._sup_thread is None:
                self.tick()
            if deadline is not None and time.monotonic() > deadline:
                with self._lock:
                    return rid not in self._replicas
            time.sleep(0.002)

    def kill_replica(self, rid):
        """Drill hook: simulated chip death for one replica."""
        self._replicas[rid].kill()

    def restore_replica(self, rid):
        """Bring a dead replica back: fresh pump thread over its warm
        service state, lease revived. Its breaker is deliberately NOT
        reset — half-open probe traffic is what re-earns trust."""
        replica = self._replicas[rid]
        replica.restore()
        self.monitor.revive(rid)
        self._counts["restores"] += 1
        _metrics.count("fleet.restores")
        _trace.instant("fleet.replica_restored", cat="fleet",
                       replica=rid)
        _recorder.record("fleet", "fleet.replica_restored",
                         f"replica {rid}")
        return replica

    def stop(self, timeout=10.0):
        """Stop the supervisor and every replica pump (drain first if
        in-flight work matters)."""
        self._sup_stop = True
        if self._sup_thread is not None:
            self._sup_thread.join(timeout)
            self._sup_thread = None
        for replica in list(self._replicas.values()):
            replica.stop(timeout)

    # -- export --------------------------------------------------------------

    def stats(self, wall_s=None):
        """JSON-ready fleet block (the ``bench.py --fleet`` artifact):
        counters, rolling latency quantiles, per-replica serving stats
        (+ QPS when ``wall_s`` is given), breaker/health transition
        trails, and the brownout ledger."""
        lat = sorted(self._lat)

        def q(p):
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        per_replica = []
        for rid, replica in sorted(self.replicas.items()):
            s = replica.service.stats()
            row = {
                "id": rid,
                "dead": replica.dead,
                "alive": replica.alive(),
                "lease_state": replica.lease.state(),
                "breaker_state": replica.breaker.state,
                "served": s["n_served"],
                "requests": s["n_requests"],
                "shed": s["n_shed"],
                "p99_ms": s["p99_ms"],
            }
            if wall_s:
                row["qps"] = round(s["n_served"] / wall_s, 2)
            per_replica.append(row)
        with self._lock:
            pending = len(self._pending)
            draining = sorted(self._draining)
            retired = list(self._retired)
        out = {
            "n_replicas": len(self._replicas),
            # with a fabric every replica serves a VIEW over the one
            # recorded stream; without one, each factory-built service
            # owns whatever feed it was given
            "stream_copies": (
                1 if self.fabric is not None else len(self._replicas)
            ),
            "draining": draining,
            "retired": retired,
            **{k: v for k, v in self._counts.items()},
            "pending": pending,
            "p50_ms": round(q(0.50) * 1e3, 3),
            "p99_ms": round(q(0.99) * 1e3, 3),
            "queue_share": round(self.queue_share(), 4),
            "brownout": {
                "level": self._brownout_level,
                "sheds": self._counts["brownout_sheds"],
                "events": list(self._brownout_events),
            },
            "admission": {
                "hbm_budget_bytes": self.hbm_budget_bytes,
                "request_bytes": self.request_bytes,
                "column_bytes": self.column_bytes,
                "hbm_sheds": self._counts["hbm_sheds"],
                "projected_bytes": self.projected_fleet_bytes(),
            },
            "breakers": {
                str(rid): r.breaker.stats()
                for rid, r in sorted(self.replicas.items())
            },
            "health": self.monitor.stats(),
            "per_replica": per_replica,
        }
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.stats()
        return out
