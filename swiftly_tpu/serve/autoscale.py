"""`FleetAutoscaler`: elastic replica count from the journey signal.

The PR 5 journey decomposition tells a fleet *where* latency lives;
the autoscaler turns its aggregate — `ServeFleet.queue_share`, the
recent queue-wait share of request latency — into replica-count policy
over a ``[min_replicas, max_replicas]`` band:

* **scale out** — queue share at/above ``up_share`` with real backlog
  (``queued_depth >= min_queue_depth``) for ``hold_ticks`` consecutive
  evaluations: requests are spending their lives waiting, so add a
  replica (`ServeFleet.add_replica` — with a cache fabric attached the
  newcomer gets a feed VIEW over the one resident stream, so scale-out
  costs an L1, not a stream copy);
* **scale in** — queue share at/below ``down_share`` AND a near-empty
  fleet queue for ``hold_ticks`` evaluations: drain the least-loaded
  replica through the PR 6 zero-loss path (`ServeFleet.begin_drain`:
  routing stops, its backlog completes or fails over, then the pump
  retires — an admitted request is never dropped by scale-in).

Hysteresis is structural: ``down_share`` sits well below ``up_share``
(the band between them is dead zone), actions need ``hold_ticks``
consecutive signals, and ``cooldown_s`` separates consecutive actions —
one zipf burst cannot flap the fleet. Drive it by attaching to the
fleet (``fleet.autoscaler = scaler`` — the supervisor tick evaluates
it) or call `tick` directly with an injected clock (tests, bench).

PR 15: the signals now come from the fleet's `obs.tower.ControlTower`.
Attached to a fleet, the supervisor tick passes the tower's per-tick
sample into `tick(signals=...)` — the SAME values the brownout ladder
read that tick; a direct `tick()` call samples the tower on demand (or
falls back to the raw fleet methods when no tower exists). Decisions
are bit-identical either way — the tower sample IS
``fleet.queue_share()``/``queued_depth()`` read once.
"""

from __future__ import annotations

import logging
import time

from ..obs import metrics as _metrics
from ..obs import recorder as _recorder
from ..obs import trace as _trace

__all__ = ["FleetAutoscaler"]

log = logging.getLogger("swiftly-tpu.autoscale")

_MAX_EVENTS = 256


class FleetAutoscaler:
    """Queue-share-driven replica band controller for a `ServeFleet`.

    :param fleet: the `ServeFleet` to scale
    :param min_replicas / max_replicas: the replica band (inclusive)
    :param up_share: queue-wait share of latency at/above which
        pressure accumulates toward a scale-out
    :param down_share: share at/below which idleness accumulates toward
        a drain; must sit below ``up_share`` (the hysteresis dead zone)
    :param min_queue_depth: fleet-wide queued requests required before
        a scale-out (share alone can be noisy on a near-idle fleet)
    :param hold_ticks: consecutive one-sided evaluations required
        before acting
    :param cooldown_s: minimum seconds between actions (lets the last
        action's effect reach the signal before the next decision)
    :param clock: injectable monotonic clock (defaults to the fleet's)
    """

    def __init__(self, fleet, *, min_replicas=1, max_replicas=8,
                 up_share=0.6, down_share=0.15, min_queue_depth=8,
                 hold_ticks=3, cooldown_s=0.5, clock=None):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas "
                f"(got {min_replicas}, {max_replicas})"
            )
        if not 0.0 <= down_share < up_share:
            raise ValueError(
                "need 0 <= down_share < up_share (the gap is the "
                f"hysteresis dead zone; got {down_share}, {up_share})"
            )
        self.fleet = fleet
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_share = float(up_share)
        self.down_share = float(down_share)
        self.min_queue_depth = int(min_queue_depth)
        self.hold_ticks = int(hold_ticks)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock or getattr(fleet, "_clock", time.monotonic)
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_action_t = None
        self.events = []
        self._counts = {"ticks": 0, "scale_outs": 0, "drains": 0,
                        "held_by_band": 0, "held_by_cooldown": 0}
        tower = getattr(fleet, "tower", None)
        if tower is not None:
            tower.register_source(
                "autoscaler",
                lambda: {"counters": dict(self._counts),
                         "band": [self.min_replicas, self.max_replicas]},
                kind="controller",
            )

    # -- policy --------------------------------------------------------------

    def tick(self, now=None, signals=None):
        """One policy evaluation; returns ``"scale_out"``, ``"drain"``
        or None. Safe to call from the fleet supervisor (scale-in is
        initiated, not awaited — `ServeFleet.begin_drain` retires the
        replica on a later supervision pass once its work is gone).

        ``signals`` is the tower's per-tick sample (the supervisor
        passes the one it already took this tick); absent, the fleet's
        tower is sampled on demand, and a tower-less fleet falls back
        to reading the raw signals directly."""
        now = self._clock() if now is None else now
        self._counts["ticks"] += 1
        if signals is None:
            tower = getattr(self.fleet, "tower", None)
            if tower is not None:
                signals = tower.sample(now)
        if signals is not None and "fleet.queue_share" in signals:
            share = signals["fleet.queue_share"]
            depth = int(signals.get("fleet.queued_depth", 0))
        else:
            share = self.fleet.queue_share()
            depth = self.fleet.queued_depth()
        n = len(self.fleet.replicas)
        if share >= self.up_share and depth >= self.min_queue_depth:
            self._up_ticks += 1
            self._down_ticks = 0
        elif (
            share <= self.down_share
            and depth <= max(1, self.min_queue_depth // 4)
        ):
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            # dead zone: both streaks reset — hysteresis demands an
            # unbroken one-sided signal
            self._up_ticks = 0
            self._down_ticks = 0
        if (
            self._last_action_t is not None
            and now - self._last_action_t < self.cooldown_s
        ):
            self._counts["held_by_cooldown"] += 1
            return None
        if self._up_ticks >= self.hold_ticks:
            self._up_ticks = 0
            if n >= self.max_replicas:
                self._counts["held_by_band"] += 1
                return None
            rid = self.fleet.add_replica()
            self._acted(now, "scale_out", rid, share, depth, n + 1)
            return "scale_out"
        if self._down_ticks >= self.hold_ticks:
            self._down_ticks = 0
            if n <= self.min_replicas:
                self._counts["held_by_band"] += 1
                return None
            rid = self._drain_candidate()
            if rid is None:
                return None
            self.fleet.begin_drain(rid)
            self._acted(now, "drain", rid, share, depth, n - 1)
            return "drain"
        return None

    def _drain_candidate(self):
        """The least-loaded live, non-draining replica (smallest queue,
        ties to the highest rid — later scale-outs drain first, so the
        core fleet keeps its warm forwards)."""
        best = None
        for rid, replica in self.fleet.replicas.items():
            if replica.dead or replica.lease.revoked:
                continue
            if rid in getattr(self.fleet, "draining", ()):
                continue
            load = len(replica.service.queue)
            if best is None or (load, -rid) < (best[1], -best[0]):
                best = (rid, load)
        return None if best is None else best[0]

    def _acted(self, now, action, rid, share, depth, n_after):
        self._last_action_t = now
        self._counts["scale_outs" if action == "scale_out" else
                     "drains"] += 1
        if len(self.events) < _MAX_EVENTS:
            self.events.append(
                {"t": round(now, 6), "action": action, "replica": rid,
                 "queue_share": round(share, 4), "depth": depth,
                 "n_replicas": n_after}
            )
        _metrics.count(f"autoscale.{action}")
        _trace.instant(f"autoscale.{action}", cat="fleet", replica=rid,
                       queue_share=round(share, 4), depth=depth)
        _recorder.record("autoscale", f"autoscale.{action}",
                         f"replica {rid} share={share:.3f} "
                         f"depth={depth} -> {n_after}")
        log.info(
            "autoscale %s: replica %d (share=%.3f depth=%d -> %d "
            "replicas)", action, rid, share, depth, n_after,
        )

    # -- export --------------------------------------------------------------

    def stats(self):
        """JSON-ready autoscaler block for fleet artifacts."""
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_share": self.up_share,
            "down_share": self.down_share,
            "hold_ticks": self.hold_ticks,
            "cooldown_s": self.cooldown_s,
            **self._counts,
            "events": list(self.events),
        }
