"""Column-locality-aware coalescing scheduler.

The engine's cost shape makes the scheduling policy: computing a
subgrid costs one column extraction (``extract_columns_batch`` over the
whole facet stack — the dominant term) plus one small finish per
subgrid, and the extraction is shared by *every* subgrid with the same
column offset ``off0``. "Large-Scale DFT on TPUs" (arXiv:2002.03260)
wins throughput by keeping device programs batched and dense even when
demand is sparse; here that means ragged arrival order must be
re-shaped into dense per-column programs. So the scheduler:

1. **times out** nothing itself (the queue owns deadlines) but serves
   *urgent* columns first — any column holding a request whose deadline
   is within ``urgency_s`` of now, earliest deadline first (EDF among
   the urgent);
2. otherwise prefers **hot** columns — columns whose intermediates are
   still resident in the forward's LRU (`SwiftlyForward.lru`): those
   requests skip the extraction entirely;
3. otherwise picks the column maximising ``(max priority, pending
   count, age)`` — the densest batch the queue can offer.

Batches are **bucket-padded** to the next power of two (by repeating
the first request's config; the padded rows are computed and discarded)
so the stacked column program compiles O(log max_batch) distinct shapes
instead of one per batch size — on a real TPU each new shape is a
multi-second XLA compile, which would otherwise be paid on the latency
path. Padding by repetition is exact: each vmap lane is independent,
so the real rows are bit-identical with or without the pads (pinned by
tests/test_serve.py).

`plan_fused` additionally groups a multi-column take with
`api._group_columns` and pads ragged columns with
`api._pad_ragged_columns` — the same exact zero-mask padding the fused
whole-cover programs use — for services that trade per-request latency
for one fused dispatch over several columns.
"""

from __future__ import annotations

from ..api import _group_columns, _pad_ragged_columns
from ..plan.model import bucket_shape as _bucket

__all__ = ["CoalescingScheduler"]


class CoalescingScheduler:
    """Pick-next-column policy + batch shaping for `SubgridService`.

    :param max_batch: cap on requests per column dispatch (overflow
        stays queued for the next pump)
    :param bucket_pad: pad batches to bucketed sizes to bound the
        number of compiled program shapes
    :param urgency_s: deadline head-start — a column holding a request
        due within this many seconds preempts locality/density order;
        None disables deadline preemption
    :param bucket_sizes: explicit ascending dispatch shapes (e.g. a
        compiled plan's ``serve.bucket_sizes``); None keeps the
        power-of-two default (`plan.model.bucket_shape` — the single
        definition the old local ``_bucket`` fork duplicated)
    """

    def __init__(self, max_batch=64, bucket_pad=True, urgency_s=None,
                 bucket_sizes=None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_batch = int(max_batch)
        self.bucket_pad = bool(bucket_pad)
        self.urgency_s = urgency_s
        self.bucket_sizes = (
            None if bucket_sizes is None
            else sorted(int(b) for b in bucket_sizes)
        )

    # -- column selection ---------------------------------------------------

    def pick_column(self, summaries, hot_columns, now):
        """The next column to serve, or None when nothing is pending.

        :param summaries: `AdmissionQueue.columns()` snapshot
        :param hot_columns: set of off0 whose intermediates are LRU-hot
        """
        if not summaries:
            return None
        if self.urgency_s is not None:
            urgent = [
                s for s in summaries
                if s.min_deadline_t is not None
                and s.min_deadline_t - now <= self.urgency_s
            ]
            if urgent:
                return min(urgent, key=lambda s: s.min_deadline_t).off0
        hot = [s for s in summaries if s.off0 in hot_columns]
        pool = hot or summaries
        # densest batch wins; priority breaks ties, then age (oldest
        # arrival first) so no column starves under a steady hot stream
        best = max(
            pool,
            key=lambda s: (s.max_priority, s.count, -s.oldest_submit_t),
        )
        return best.off0

    def pick_columns(self, summaries, hot_columns, now, k):
        """Up to ``k`` columns for one fused multi-column dispatch:
        the `pick_column` winner plus the next densest columns."""
        first = self.pick_column(summaries, hot_columns, now)
        if first is None:
            return []
        rest = sorted(
            (s for s in summaries if s.off0 != first),
            key=lambda s: (-s.max_priority, -s.count, s.oldest_submit_t),
        )
        return [first] + [s.off0 for s in rest[: max(0, k - 1)]]

    # -- batch shaping ------------------------------------------------------

    def plan_batch(self, requests):
        """Order one column's take and shape its dispatch.

        :return: ``(configs, n_pad)`` — the config list to hand to the
            stacked column program (real requests first, then ``n_pad``
            bucket-padding repeats of the first config whose output rows
            are discarded).
        """
        configs = [r.config for r in requests]
        n_pad = 0
        if self.bucket_pad and len(configs) > 1:
            if self.bucket_sizes is not None:
                target = next(
                    (b for b in self.bucket_sizes if b >= len(configs)),
                    self.bucket_sizes[-1],
                )
                target = min(target, self.max_batch)
            else:
                target = min(_bucket(len(configs)), self.max_batch)
            n_pad = max(0, target - len(configs))
            configs = configs + [configs[0]] * n_pad
        return configs, n_pad

    def plan_fused(self, requests):
        """Shape a multi-column take for one fused dispatch.

        Groups by column with `api._group_columns` and pads ragged
        columns to rectangular with `api._pad_ragged_columns` (exact
        zero-mask entries). Returns ``(configs, rows)``: the flat
        config list (pads included) and, per request, the row index its
        result lands in. Raises ValueError on mixed subgrid sizes —
        the fused stacked output needs one size (callers fall back to
        per-column batches).
        """
        groups, rectangular = _group_columns(
            enumerate(requests),
            key=lambda item: item[1].config,
            require_one_size=True,
        )
        # _pad_ragged_columns works on (index, SubgridConfig) items
        cfg_groups = {
            off0: [(i, r.config) for i, r in col]
            for off0, col in groups.items()
        }
        if not rectangular:
            _pad_ragged_columns(
                cfg_groups, requests[0].config.size
            )
        configs, rows = [], {}
        for col in cfg_groups.values():
            for i, cfg in col:
                if i is not None:
                    rows[i] = len(configs)
                configs.append(cfg)
        return configs, [rows[i] for i in range(len(requests))]
