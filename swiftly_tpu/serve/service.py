"""`SubgridService`: a long-lived, fault-isolated subgrid server.

Wraps a prepared `SwiftlyForward` (facets uploaded once, column LRU
warm across requests) behind an `AdmissionQueue` and a
`CoalescingScheduler`, and serves individual subgrid requests arriving
over time — the ROADMAP's serving workload, where the batch drivers
enumerate a full cover instead. One pump cycle:

1. time out overdue requests (queue deadlines / the service-wide
   ``timeout_s``) — a request never occupies a dispatch after its
   caller stopped waiting;
2. pick the next column (urgency > LRU locality > batch density, see
   `CoalescingScheduler`) and take up to ``max_batch`` of its requests;
3. serve what it can from the optional **cache feed** (a
   `parallel.streamed.CachedColumnFeed` over a recorded subgrid
   stream) — a feed hit is one host-RAM row read, no device dispatch;
   a feed *eviction* falls through to the compute path (the
   spill-replay fallback: a capacity miss degrades to recomputation,
   never to an error);
4. compute the rest as ONE stacked column program
   (`SwiftlyForward.get_subgrid_tasks` — bit-identical to per-request
   ``get_subgrid_task``, pinned by tests), bucket-padded so compile
   shapes stay bounded;
5. on a batch failure, **isolate**: retry each request singly (up to
   ``max_retries``); a request that keeps failing is *quarantined*
   with a structured error result — one poisoned config (bad mask,
   impossible offsets) can never wedge the queue behind it.

Fused multi-column dispatch (``fuse_columns > 1``) trades per-request
latency for fewer dispatches via `SwiftlyForward.all_subgrids` (the
`_group_columns` + `_pad_ragged_columns` whole-cover path).

SLO instrumentation: per-request latency histogram (p50/p99 via
``obs.metrics.observe("serve.request", ...)`` plus the service's own
quantile ring for metrics-off runs), queue-depth gauge, shed/coalesce/
cache counters, and ``stats()`` — the JSON-ready block ``bench.py
--serve`` stamps into its artifact (``p50_ms``/``p99_ms``/
``shed_rate``/``coalesce_hit_rate``).

Threading: ``pump_once``/``serve`` for synchronous (test/bench) use;
``start()``/``stop()`` run the pump on a background worker so client
threads just ``submit(...).wait()``. Timeouts are enforced at
scheduling boundaries — an already-dispatched device program is never
preempted (XLA offers no cancellation), so a timed-out request's
compute may still run to completion; its result is discarded.
"""

from __future__ import annotations

import logging
import threading
import time

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import degrade as _degrade
from ..resilience.faults import fault_point as _fault_point
from ..resilience.retry import backoff_delay as _backoff_delay
from ..resilience.retry import is_oom as _is_oom
from .queue import (
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_QUARANTINED,
    STATUS_SHED,
    AdmissionQueue,
    RequestResult,
    SubgridRequest,
)
from .scheduler import CoalescingScheduler

__all__ = ["SubgridService", "projected_request_bytes",
           "projected_column_bytes"]

log = logging.getLogger("swiftly-tpu.serve")

_LATENCY_RING = 65536  # newest-wins latency samples kept for quantiles


# The admission cost model moved into the unified plan compiler
# (`plan.model` — one pricing shared with the fleet's fleet-wide
# admission cap and `compile_plan`'s serve block); these names stay as
# the serve-facing aliases.
from ..plan.model import (  # noqa: E402 - after the docstring's imports
    projected_column_bytes,
    projected_request_bytes,
)


def _quantile(sorted_samples, q):
    if not sorted_samples:
        return 0.0
    i = min(len(sorted_samples) - 1, int(q * len(sorted_samples)))
    return sorted_samples[i]


class SubgridService:
    """Serve individual subgrid requests through a shared forward.

    :param fwd: a prepared `SwiftlyForward` (its facet stack and column
        LRU are the service's working set)
    :param queue: `AdmissionQueue`; default bounds depth at 256 with
        the cost model priced from ``fwd`` when ``hbm_budget_bytes``
        is given
    :param scheduler: `CoalescingScheduler`; default coalesces up to 64
        requests per column dispatch with bucket padding
    :param cache_feed: optional recorded-stream feed (an object with
        ``lookup(config) -> row | None``, raising LookupError when the
        looked-up entry was evicted) — e.g.
        `parallel.streamed.CachedColumnFeed`
    :param timeout_s: service-wide per-request deadline applied at
        submit (min'd with the request's own ``deadline_s``)
    :param max_retries: single-request retry attempts after a batch
        failure before quarantine
    :param retry_backoff_s: base of the capped jittered exponential
        backoff between single-request retries (cap 16x the base; 0
        disables). Instant retries against a struggling device are a
        thundering herd — the backoff decorrelates them, and the total
        slept is reported as ``retry_backoff_s`` in ``stats()``.
    :param fuse_columns: columns per dispatch; > 1 uses the fused
        whole-cover program (`all_subgrids`) over several columns
    :param slo_ms: latency SLO — served requests slower than this are
        counted as violations in ``stats()``
    :param fault_injector: test/chaos hook ``fn(requests, attempt)``
        called before each dispatch (attempt 0 = coalesced batch,
        >= 1 = isolated retries); an exception it raises is handled
        exactly like a compute failure
    :param cover_columns: optional sparse-cover column list — an
        iterable of served ``off0`` values (e.g. the streamed-sparse
        bench path's partial-FoV cover). A request for any other
        column is shed at the door with reason ``outside_cover``: a
        partial-FoV service has no facet data for it, so computing
        would silently return zeros. None (default) serves every
        column.
    """

    def __init__(self, fwd, queue=None, scheduler=None, cache_feed=None,
                 timeout_s=None, max_retries=2, retry_backoff_s=0.005,
                 fuse_columns=1, slo_ms=None, fault_injector=None,
                 hbm_budget_bytes=None, max_depth=256,
                 cover_columns=None):
        self.fwd = fwd
        self.cover_columns = (
            None if cover_columns is None
            else {int(c) for c in cover_columns}
        )
        # the current facet-stack version (bumped by
        # `post_facet_update`); every admitted request is stamped with
        # it so the cache feed serves only version-matching requests.
        # Adopted from the feed at construction — a feed recorded at
        # version v would otherwise version-gate EVERY request onto
        # the compute path
        self.stream_version = int(
            getattr(cache_feed, "stream_version", 0)
        )
        if queue is None:
            queue = AdmissionQueue(
                max_depth=max_depth,
                hbm_budget_bytes=hbm_budget_bytes,
                request_bytes=projected_request_bytes(fwd.config),
                column_bytes=projected_column_bytes(fwd),
            )
        self.queue = queue
        self.scheduler = scheduler or CoalescingScheduler()
        self.cache_feed = cache_feed
        self.timeout_s = timeout_s
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.fuse_columns = int(fuse_columns)
        self.slo_ms = slo_ms
        self.fault_injector = fault_injector
        self.quarantined = []  # [(request, error_repr), ...]
        self._backoff_slept_s = 0.0
        self._counts = {
            "requests": 0, "served": 0, "shed": 0, "expired": 0,
            "quarantined": 0, "retries": 0, "batches": 0,
            "batch_failures": 0, "batch_splits": 0, "coalesced": 0,
            "cache_hits": 0, "cache_fallbacks": 0, "slo_violations": 0,
            "facet_updates": 0, "version_fallbacks": 0,
        }
        self._shed_reasons = {}
        self._latencies = []
        self._lat_i = 0
        # journey ring, parallel to _latencies: (queue_s, compute_s,
        # transfer_s) per served request — the p99 decomposition
        self._journeys = []
        self._jour_i = 0
        self._pump_lock = threading.Lock()
        self._cond = threading.Condition()
        self._stop = False
        self._thread = None

    # -- submission ---------------------------------------------------------

    def submit(self, config, priority=0, deadline_s=None):
        """Admit one request; returns a `SubgridRequest` whose result is
        set on completion. A shed request returns already-completed
        (``status == "shed"``) — admission never blocks."""
        if deadline_s is None:
            deadline_s = self.timeout_s
        elif self.timeout_s is not None:
            deadline_s = min(deadline_s, self.timeout_s)
        req = SubgridRequest(config, priority=priority,
                            deadline_s=deadline_s)
        req.stream_version = self.stream_version
        self._counts["requests"] += 1
        _metrics.count("serve.requests")
        if (
            self.cover_columns is not None
            and int(config.off0) not in self.cover_columns
        ):
            # sparse-cover locality: this service holds no facet data
            # for the column — shed with a structured reason instead
            # of computing a silent zero
            self._counts["shed"] += 1
            self._shed_reasons["outside_cover"] = (
                self._shed_reasons.get("outside_cover", 0) + 1
            )
            _metrics.count("serve.shed")
            _metrics.count("serve.shed.outside_cover")
            _trace.instant("serve.shed", cat="serve",
                           request_id=req.req_id,
                           reason="outside_cover")
            req._complete(
                RequestResult(STATUS_SHED, shed_reason="outside_cover")
            )
            return req
        admitted, reason = self.queue.offer(req)
        if not admitted:
            if reason == "expired":
                # dead on arrival is a deadline outcome, not backpressure
                self._counts["expired"] += 1
                _metrics.count("serve.expired")
                req._complete(
                    RequestResult(STATUS_EXPIRED, error="deadline")
                )
                return req
            self._counts["shed"] += 1
            self._shed_reasons[reason] = (
                self._shed_reasons.get(reason, 0) + 1
            )
            _metrics.count("serve.shed")
            _metrics.count(f"serve.shed.{reason}")
            _trace.instant("serve.shed", cat="serve",
                           request_id=req.req_id, reason=reason)
            req._complete(
                RequestResult(
                    STATUS_SHED, shed_reason=reason,
                    retry_after_s=self.queue.retry_after_hint(),
                )
            )
            return req
        with self._cond:
            self._cond.notify()
        return req

    def serve(self, configs, priority=0, deadline_s=None):
        """Submit many configs and serve to completion; returns the
        requests in input order (synchronous pump unless the worker
        thread is running)."""
        reqs = [
            self.submit(c, priority=priority, deadline_s=deadline_s)
            for c in configs
        ]
        if self._thread is None:
            while self.pump_once():
                pass
        for r in reqs:
            r.wait()
        return reqs

    # -- the pump -----------------------------------------------------------

    def pump_once(self, now=None):
        """Serve one coalesced dispatch; returns the number of requests
        it brought to a terminal state (0 = nothing pending)."""
        with self._pump_lock:
            now = time.perf_counter() if now is None else now
            handled = 0
            for req in self.queue.take_expired(now):
                self._finish(
                    req, RequestResult(STATUS_EXPIRED, error="deadline")
                )
                self._counts["expired"] += 1
                _metrics.count("serve.expired")
                handled += 1
            summaries = self.queue.columns()
            if not summaries:
                return handled
            hot = set(self.fwd.lru.keys())
            if self.fuse_columns > 1:
                offs = self.scheduler.pick_columns(
                    summaries, hot, now, self.fuse_columns
                )
                requests = []
                for off0 in offs:
                    requests.extend(
                        self.queue.take(off0, limit=self.scheduler.max_batch)
                    )
            else:
                off0 = self.scheduler.pick_column(summaries, hot, now)
                requests = self.queue.take(
                    off0, limit=self.scheduler.max_batch
                )
            if not requests:
                return handled
            remaining = requests
            if self.cache_feed is not None:
                remaining = self._serve_from_feed(requests)
            if remaining:
                self._execute(remaining)
            return handled + len(requests)

    def _serve_from_feed(self, requests):
        """Serve what the recorded-stream feed holds; returns the
        requests that still need compute (feed misses AND evictions —
        the eviction fallback is the serving-path twin of the spill
        cache's degrade-to-replay contract)."""
        remaining = []
        feed_version = getattr(
            self.cache_feed, "stream_version", self.stream_version
        )
        for req in requests:
            if (
                req.stream_version is not None
                and req.stream_version != feed_version
            ):
                # version pinning: this request was admitted under a
                # different facet-stack version than the feed serves —
                # never hand it another version's rows; the compute
                # path serves it against the forward it was admitted to
                self._counts["version_fallbacks"] += 1
                _metrics.count("serve.version_fallbacks")
                remaining.append(req)
                continue
            try:
                with _metrics.stage("serve.cache_feed"):
                    row = self.cache_feed.lookup(req.config)
            except LookupError:
                # indexed but evicted: fall back to the compute path
                self._counts["cache_fallbacks"] += 1
                _metrics.count("serve.cache_fallbacks")
                row = None
            if row is None:
                remaining.append(req)
                continue
            self._counts["cache_hits"] += 1
            _metrics.count("serve.cache_hits")
            req.compute_t = time.perf_counter()  # feed read ≙ compute
            self._finish(
                req,
                RequestResult(
                    STATUS_OK, data=row, path="cache",
                    batch_size=len(requests),
                ),
            )
        return remaining

    def _dedup_key(self, requests):
        """Single-flight identity of one coalesced dispatch, or None
        when dedup does not apply (no fabric-backed feed, or a fused
        multi-column batch). The key is the exact request multiset —
        offsets, sizes AND mask content at the admitted stream version —
        so only genuinely identical concurrent batches collapse;
        near-miss batches (a hedge's singleton vs the primary's
        coalesced batch) stay independent dispatches."""
        fabric = getattr(self.cache_feed, "fabric", None)
        if fabric is None:
            return None
        if len({r.config.off0 for r in requests}) != 1:
            return None
        return (
            "batch", self.stream_version,
            tuple(fabric.request_key(r.config) for r in requests),
        )

    def _execute(self, requests, _split_depth=0):
        """One coalesced dispatch for the taken requests, with
        batch-failure isolation. A fused-batch OOM first steps down the
        degradation ladder — split the batch in half and dispatch each
        half (smaller transients) — before per-request isolation.

        With a fabric-backed feed (`cache.SharedStreamTier` view),
        identical concurrent dispatches across replicas collapse
        through the fabric's single-flight registry: the first replica
        in computes, the rest adopt its (bit-identical) rows. A
        leader's failure never propagates to followers — they fall back
        to computing independently inside `single_flight`."""
        self._counts["batches"] += 1
        _metrics.count("serve.batches")

        def dispatch():
            _fault_point("serve.dispatch")
            if self.fault_injector is not None:
                self.fault_injector(requests, 0)
            with _metrics.stage("serve.batch"):
                if self.fuse_columns > 1:
                    configs, rows = self.scheduler.plan_fused(requests)
                    flat = self.fwd.all_subgrids(configs)
                    return [flat[r] for r in rows]
                configs, _n_pad = self.scheduler.plan_batch(requests)
                return self.fwd.get_subgrid_tasks(configs)[
                    : len(requests)
                ]

        try:
            key = (
                self._dedup_key(requests) if _split_depth == 0 else None
            )
            if key is not None:
                results = self.cache_feed.single_flight(key, dispatch)
            else:
                results = dispatch()
        except Exception as exc:
            self._counts["batch_failures"] += 1
            _metrics.count("serve.batch_failures")
            if _is_oom(exc) and len(requests) > 1 and _split_depth < 4:
                self._counts["batch_splits"] += 1
                _metrics.count("serve.batch_splits")
                _degrade.record(
                    "serve", "batch_split",
                    f"{len(requests)} requests OOM'd; splitting",
                )
                log.warning(
                    "coalesced batch of %d OOM'd (%s); splitting in half",
                    len(requests), type(exc).__name__,
                )
                mid = len(requests) // 2
                self._execute(requests[:mid], _split_depth + 1)
                self._execute(requests[mid:], _split_depth + 1)
                return
            log.warning(
                "coalesced batch of %d failed (%s: %s); isolating",
                len(requests), type(exc).__name__, exc,
            )
            self._retry_singly(requests, exc)
            return
        coalesced = len(requests) > 1
        t_compute = time.perf_counter()
        for req in requests:
            req.compute_t = t_compute
        if coalesced:
            self._counts["coalesced"] += len(requests)
            _metrics.count("serve.coalesce.hits", len(requests))
        for req, data in zip(requests, results):
            self._finish(
                req,
                RequestResult(
                    STATUS_OK, data=data, path="coalesced",
                    batch_size=len(requests), coalesced=coalesced,
                ),
            )

    def _retry_singly(self, requests, batch_exc):
        """Per-request isolation after a batch failure: each request
        retries alone; persistent failures are quarantined so the rest
        of the queue keeps flowing."""
        for req in requests:
            last_err = batch_exc
            served = False
            for attempt in range(1, self.max_retries + 1):
                if self.retry_backoff_s > 0:
                    # capped jittered exponential backoff: retrying
                    # instantly against a struggling device synchronises
                    # the herd; the slept total is reported in stats()
                    delay = _backoff_delay(
                        attempt - 1, base_s=self.retry_backoff_s,
                        max_s=16 * self.retry_backoff_s,
                    )
                    self._backoff_slept_s += delay
                    time.sleep(delay)
                req.retries += 1
                self._counts["retries"] += 1
                _metrics.count("serve.retries")
                try:
                    if self.fault_injector is not None:
                        self.fault_injector([req], attempt)
                    data = self.fwd.get_subgrid_task(req.config)
                except Exception as exc:  # noqa: BLE001 - isolation layer
                    last_err = exc
                    continue
                req.compute_t = time.perf_counter()
                self._finish(
                    req,
                    RequestResult(
                        STATUS_OK, data=data, path="retry",
                        batch_size=1, retries=req.retries,
                    ),
                )
                served = True
                break
            if not served:
                err = f"{type(last_err).__name__}: {last_err}"
                self.quarantined.append((req, err))
                self._counts["quarantined"] += 1
                _metrics.count("serve.quarantined")
                _trace.instant("serve.quarantine", cat="serve",
                               request_id=req.req_id, error=err)
                log.error(
                    "request %r quarantined after %d retries: %s",
                    req, req.retries, err,
                )
                self._finish(
                    req,
                    RequestResult(
                        STATUS_QUARANTINED, error=err,
                        retries=req.retries,
                    ),
                )

    def _finish(self, req, result):
        now = time.perf_counter()
        result.latency_s = max(0.0, now - req.submit_t)
        if result.ok:
            self._counts["served"] += 1
            _metrics.count("serve.served")
            _metrics.observe("serve.request", result.latency_s)
            if req.take_t is not None and req.compute_t is not None:
                # contiguous timestamp diffs: the three segments sum to
                # latency_s EXACTLY (same `now`, monotonic clock) — the
                # p99-outlier decomposition contract
                result.journey = {
                    "queue_s": req.take_t - req.submit_t,
                    "compute_s": req.compute_t - req.take_t,
                    "transfer_s": now - req.compute_t,
                }
                if len(self._journeys) < _LATENCY_RING:
                    self._journeys.append(result.journey)
                else:
                    self._journeys[self._jour_i] = result.journey
                    self._jour_i = (self._jour_i + 1) % _LATENCY_RING
                self._trace_journey(req, result, now)
            if len(self._latencies) < _LATENCY_RING:
                self._latencies.append(result.latency_s)
            else:
                self._latencies[self._lat_i] = result.latency_s
                self._lat_i = (self._lat_i + 1) % _LATENCY_RING
            if (
                self.slo_ms is not None
                and result.latency_s * 1e3 > self.slo_ms
            ):
                self._counts["slo_violations"] += 1
                _metrics.count("serve.slo_violations")
        req._complete(result)

    @staticmethod
    def _trace_journey(req, result, now):
        """Emit the request journey onto the trace as one per-request
        track: an umbrella ``serve.journey`` span with the queue /
        compute / transfer segments as children — Perfetto shows one
        row per request, and trace_report decomposes p99 outliers."""
        if not _trace.enabled():
            return
        tid = _trace.JOURNEY_TID_BASE + (req.req_id % (1 << 20))
        root = _trace.add_span(
            "serve.journey", req.submit_t, now, cat="serve", tid=tid,
            request_id=req.req_id, path=result.path,
            batch_size=result.batch_size, retries=result.retries,
        )
        for name, t0, t1 in (
            ("serve.journey.queue", req.submit_t, req.take_t),
            ("serve.journey.compute", req.take_t, req.compute_t),
            ("serve.journey.transfer", req.compute_t, now),
        ):
            _trace.add_span(name, t0, t1, cat="serve", tid=tid,
                            parent=root, request_id=req.req_id)

    # -- worker thread ------------------------------------------------------

    def start(self):
        """Run the pump on a background worker; clients just submit."""
        if self._thread is not None:
            raise RuntimeError("service already started")
        self._stop = False
        # contextvars do not flow into Thread targets: hand the worker
        # the CALLER's span context so pump spans nest under the run
        # (not as orphan roots) in a recorded trace
        trace_ctx = _trace.current()
        self._thread = threading.Thread(
            target=self._run, args=(trace_ctx,),
            name="subgrid-service", daemon=True,
        )
        self._thread.start()
        return self

    def _run(self, trace_ctx=0):
        _trace.adopt(trace_ctx)
        while True:
            n = self.pump_once()
            if n:
                continue
            with self._cond:
                if self._stop and not len(self.queue):
                    return
                self._cond.wait(timeout=0.02)

    def stop(self, drain=True, timeout=None):
        """Stop the worker; with ``drain`` the queue is served empty
        first, otherwise pending requests are shed."""
        if self._thread is None:
            return
        if not drain:
            for req in self.queue.drain():
                self._counts["shed"] += 1
                _metrics.count("serve.shed")
                req._complete(
                    RequestResult(STATUS_SHED, shed_reason="shutdown")
                )
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        self._thread = None

    # -- incremental facet updates ------------------------------------------

    def post_facet_update(self, engine=None, new_facet_tasks=None, *,
                          report=None, feed=None, fwd=None, **update_kw):
        """Admit a new facet stack and serve from the patched cache.

        Two calling shapes:

        * ``post_facet_update(engine, new_facet_tasks)`` — run the
          `delta.IncrementalForward` update here (delta-stream + cache
          patch, or its degradation ladder) and adopt its feed;
        * ``post_facet_update(report=..., feed=..., fwd=...,
          new_facet_tasks=...)`` — adopt a pre-computed update (the
          fleet runs ``engine.update`` ONCE and propagates the result
          to every replica this way).

        In-flight requests are pinned to the version they were admitted
        under: the queue is DRAINED before the cache rows move, so
        every pending request completes against the facet stack it was
        admitted to; requests submitted after this returns carry the
        new version and are served from the patched rows. No cache
        flush — the feed swap is the only serving-path change.

        The compute FALLBACK moves with the update too: the forward is
        rebuilt over the new stack (an explicit ``fwd=``, or
        ``self.fwd.adopt_facet_tasks`` over the engine's adopted /
        passed ``new_facet_tasks``), so a new-version request that
        misses the feed — a config outside the recorded cover, an
        evicted disk entry, a stale-feed LookupError — is computed
        against the NEW facet data, never silently served stale.
        """
        if engine is None and report is None:
            raise ValueError(
                "post_facet_update needs an engine (to run the update) "
                "or a pre-computed report"
            )
        # drain: in-flight requests complete at their admitted version
        # BEFORE any cache row is patched out from under them (the
        # worker thread contends on _pump_lock, so its pumps drain too)
        while self.pump_once():
            pass
        with self._pump_lock:
            if engine is not None:
                report = engine.update(new_facet_tasks, **update_kw)
                if feed is None:
                    feed = engine.feed()
                if new_facet_tasks is None:
                    new_facet_tasks = engine.facet_tasks
            if fwd is not None:
                self.fwd = fwd
            elif report.get("mode") != "noop" and new_facet_tasks is not None:
                if hasattr(self.fwd, "adopt_facet_tasks"):
                    self.fwd.adopt_facet_tasks(new_facet_tasks)
                else:
                    log.warning(
                        "forward %s cannot adopt the new facet stack "
                        "(no adopt_facet_tasks); compute fallbacks "
                        "would serve the superseded stack — pass fwd= "
                        "explicitly", type(self.fwd).__name__,
                    )
            if feed is not None and self.cache_feed is not None:
                self.cache_feed = feed
            self.stream_version = int(
                report.get("stream_version", self.stream_version + 1)
            )
            self._counts["facet_updates"] += 1
            _metrics.count("serve.facet_updates")
            _trace.instant(
                "serve.facet_update", cat="serve",
                stream_version=self.stream_version,
                mode=report.get("mode"),
                changed_facets=report.get("changed_facets"),
            )
        return report

    # -- SLO export ---------------------------------------------------------

    def stats(self):
        """JSON-ready serving metrics (the ``bench.py --serve``
        artifact block): request counts, shed/coalesce/cache rates,
        latency quantiles in ms, SLO attainment."""
        c = dict(self._counts)
        lat = sorted(self._latencies)
        served = c["served"]
        requests = c["requests"]
        out = {
            "n_requests": requests,
            "n_served": served,
            "n_shed": c["shed"],
            "n_expired": c["expired"],
            "n_quarantined": c["quarantined"],
            "n_batches": c["batches"],
            "batch_failures": c["batch_failures"],
            "batch_splits": c["batch_splits"],
            "retries": c["retries"],
            "retry_backoff_s": round(self._backoff_slept_s, 4),
            "cache_hits": c["cache_hits"],
            "cache_fallbacks": c["cache_fallbacks"],
            "stream_version": self.stream_version,
            "facet_updates": c["facet_updates"],
            "version_fallbacks": c["version_fallbacks"],
            "shed_rate": round(c["shed"] / requests, 4) if requests else 0.0,
            "shed_reasons": dict(self._shed_reasons),
            "coalesce_hit_rate": (
                round(c["coalesced"] / served, 4) if served else 0.0
            ),
            "mean_batch": (
                round(served / c["batches"], 2) if c["batches"] else 0.0
            ),
            "p50_ms": round(_quantile(lat, 0.50) * 1e3, 3),
            "p99_ms": round(_quantile(lat, 0.99) * 1e3, 3),
            "max_ms": round((lat[-1] if lat else 0.0) * 1e3, 3),
            "journey": self._journey_stats(),
        }
        if self.slo_ms is not None:
            out["slo_ms"] = self.slo_ms
            out["slo_violations"] = c["slo_violations"]
            out["slo_attainment"] = (
                round(1.0 - c["slo_violations"] / served, 4)
                if served else 1.0
            )
        return out

    def recent_journey_totals(self, window=256):
        """``(queue_s_total, total_s)`` over the most recent served
        journeys — the fleet brownout signal (`serve.fleet` divides the
        aggregates across replicas: a queue share near 1 means requests
        spend their life waiting, not computing)."""
        js = list(self._journeys)[-window:]
        if not js:
            return 0.0, 0.0
        total = sum(
            j["queue_s"] + j["compute_s"] + j["transfer_s"] for j in js
        )
        return sum(j["queue_s"] for j in js), total

    def _journey_stats(self):
        """The request-journey decomposition block: per-segment p50/p99
        and each segment's share of total served wall — where a p99
        latency regression LIVES (queue wait vs compute vs transfer),
        not just that it happened."""
        if not self._journeys:
            return None
        total = sum(
            j["queue_s"] + j["compute_s"] + j["transfer_s"]
            for j in self._journeys
        )
        out = {"n": len(self._journeys)}
        for seg in ("queue_s", "compute_s", "transfer_s"):
            vals = sorted(j[seg] for j in self._journeys)
            seg_total = sum(vals)
            key = seg[:-2]  # "queue_s" -> "queue"
            out[key] = {
                "p50_ms": round(_quantile(vals, 0.50) * 1e3, 3),
                "p99_ms": round(_quantile(vals, 0.99) * 1e3, 3),
                "total_s": round(seg_total, 6),
                "share": round(seg_total / total, 4) if total else 0.0,
            }
        return out
