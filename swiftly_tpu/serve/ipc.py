"""Versioned length-prefixed wire protocol for the process fleet.

`serve.procfleet` runs each replica as a separate OS process; this
module is the only thing that crosses the boundary. The frame format
is deliberately boring — a fixed 16-byte header followed by a pickled
payload — because every interesting failure mode of a wire protocol is
in the *edges*, and those are pinned down here:

* **Versioned.** The header carries ``WIRE_VERSION``; a peer speaking
  a different version is rejected with `VersionMismatch` (fatal, not
  retried) instead of mis-parsing its frames.
* **Length-prefixed and bounded.** Payload length is declared up
  front and capped at ``MAX_FRAME_BYTES``; an oversized declaration is
  rejected (`FrameTooLarge`) before a single payload byte is read, so
  a corrupt length cannot make the reader allocate unboundedly or
  stall draining garbage.
* **Checksummed.** A CRC32 over the payload rejects torn or bit-
  flipped frames (`BadChecksum`) instead of unpickling garbage.
* **Never hangs.** Every socket read and write runs under a deadline
  (`sock.settimeout` re-armed per chunk with the *remaining* budget);
  expiry raises `WireDeadline`, which subclasses `TimeoutError` so the
  PR-4 retry ladder (`resilience.retry.is_transient`) classifies it
  transient. A peer that dies mid-frame surfaces as `TruncatedFrame`
  (a `ConnectionError` — transient for connect-time retries, but a
  *stream* that truncates is unrecoverable: framing cannot resync, so
  callers drop the connection).

Error taxonomy (all under `WireError`):

====================  ==========================  =====================
error                 meaning                     retry classification
====================  ==========================  =====================
`WireDeadline`        deadline expired mid-read   transient (TimeoutError)
`TruncatedFrame`      peer closed mid-frame       transient (ConnectionError)
`BadMagic`            stream desynced / garbage   fatal
`BadChecksum`         payload corrupt             fatal
`FrameTooLarge`       length over the cap         fatal
`VersionMismatch`     peer speaks other version   fatal
====================  ==========================  =====================

Accounting (`obs.metrics`, zero-cost when disabled): ``ipc.frames_sent``
/ ``ipc.frames_received`` / ``ipc.bytes_sent`` / ``ipc.bytes_received``
volume counters, ``ipc.bad_frames`` (+ ``ipc.bad_frames.<reason>``),
``ipc.version_mismatches`` and ``ipc.deadline_expired``.

Payloads are pickled: both ends of the socket are this repo's own
processes (the parent spawns the workers), never an untrusted peer.
"""

from __future__ import annotations

import pickle
import socket
import struct
import time
import zlib

from ..obs import metrics as _metrics

__all__ = [
    "FRAME_CONTROL",
    "FRAME_DRAIN",
    "FRAME_ERROR",
    "FRAME_HEARTBEAT",
    "FRAME_HELLO",
    "FRAME_REQUEST",
    "FRAME_RESULT",
    "FRAME_TELEMETRY",
    "HEADER_BYTES",
    "MAX_FRAME_BYTES",
    "WIRE_VERSION",
    "BadChecksum",
    "BadMagic",
    "FrameStream",
    "FrameTooLarge",
    "TruncatedFrame",
    "VersionMismatch",
    "WireDeadline",
    "WireError",
    "connect_unix",
    "recv_frame",
    "send_frame",
]

# Header: magic, version, frame type, flags, payload length, payload CRC32.
_MAGIC = b"SWFT"
_HEADER = struct.Struct("!4sHBBII")
HEADER_BYTES = _HEADER.size  # 16

WIRE_VERSION = 1

# A serve result is one subgrid row (~hundreds of KiB); 64 MiB is far
# above any legitimate frame and far below "allocate until the OOM
# killer arrives".
MAX_FRAME_BYTES = 64 * 1024 * 1024

FRAME_HELLO = 1
FRAME_REQUEST = 2
FRAME_RESULT = 3
FRAME_HEARTBEAT = 4
FRAME_DRAIN = 5
FRAME_ERROR = 6
FRAME_CONTROL = 7
FRAME_TELEMETRY = 8

_FRAME_TYPES = frozenset((
    FRAME_HELLO, FRAME_REQUEST, FRAME_RESULT, FRAME_HEARTBEAT,
    FRAME_DRAIN, FRAME_ERROR, FRAME_CONTROL, FRAME_TELEMETRY,
))


class WireError(Exception):
    """Base class for every structured wire failure."""


class WireDeadline(WireError, TimeoutError):
    """Deadline expired before the frame finished — transient."""


class TruncatedFrame(WireError, ConnectionError):
    """Peer closed the stream mid-frame."""


class BadMagic(WireError):
    """Stream desynced: header does not start with the magic."""


class BadChecksum(WireError):
    """Payload CRC mismatch — torn or corrupted frame."""


class FrameTooLarge(WireError):
    """Declared payload length exceeds ``MAX_FRAME_BYTES``."""


class VersionMismatch(WireError):
    """Peer speaks a different ``WIRE_VERSION``."""


def _bad(exc_cls, reason, detail):
    """Count and build a fatal frame rejection."""
    _metrics.count("ipc.bad_frames")
    _metrics.count(f"ipc.bad_frames.{reason}")
    if exc_cls is VersionMismatch:
        _metrics.count("ipc.version_mismatches")
    return exc_cls(detail)


_RECV_CHUNK = 256 * 1024


class FrameStream:
    """Stateful frame reader over one socket.

    A deadline that expires mid-frame must NOT desync the stream: the
    bytes already read are a frame prefix the next call has to resume
    from. This object keeps that partial buffer, so `recv_frame` can
    expire (`WireDeadline`, transient) any number of times and still
    hand over exactly the frames the peer sent. Use ONE `FrameStream`
    per connection for its whole life — constructing a second one
    abandons the first one's partial bytes.

    Fatal frame errors (`BadMagic`, `BadChecksum`, `FrameTooLarge`,
    `VersionMismatch`) leave the stream position undefined by nature —
    length-prefixed framing cannot resynchronise after corruption —
    so callers must drop the connection after any of them.
    """

    def __init__(self, sock):
        self.sock = sock
        self._buf = bytearray()

    def _fill(self, need, deadline_t, what):
        while len(self._buf) < need:
            remaining = deadline_t - time.monotonic()
            if remaining <= 0:
                _metrics.count("ipc.deadline_expired")
                raise WireDeadline(
                    f"wire read deadline expired with "
                    f"{len(self._buf)}/{need} bytes of {what}")
            self.sock.settimeout(remaining)
            try:
                chunk = self.sock.recv(_RECV_CHUNK)
            except socket.timeout:
                # spurious early wake (or exact expiry): loop back —
                # the remaining-budget check above judges the deadline
                continue
            except OSError as exc:
                raise TruncatedFrame(
                    f"socket failed with {len(self._buf)}/{need} bytes "
                    f"of {what}: {exc}") from exc
            if not chunk:
                raise TruncatedFrame(
                    f"peer closed with {len(self._buf)}/{need} bytes "
                    f"of {what}")
            self._buf += chunk

    def recv_frame(self, deadline_s=1.0):
        """Receive one frame; returns ``(frame_type, flags, payload)``.

        Every byte is read under the deadline; malformed frames raise
        the structured `WireError` subclasses documented in the module
        header — this call can fail, but it cannot hang and it cannot
        return garbage.
        """
        deadline_t = time.monotonic() + deadline_s
        self._fill(HEADER_BYTES, deadline_t, "header")
        magic, version, ftype, flags, length, crc = _HEADER.unpack(
            bytes(self._buf[:HEADER_BYTES]))
        if magic != _MAGIC:
            raise _bad(BadMagic, "magic", f"bad magic {magic!r}")
        if version != WIRE_VERSION:
            raise _bad(
                VersionMismatch, "version",
                f"peer wire version {version}, expected {WIRE_VERSION}")
        if ftype not in _FRAME_TYPES:
            raise _bad(BadMagic, "frame_type",
                       f"unknown frame type {ftype}")
        if length > MAX_FRAME_BYTES:
            raise _bad(
                FrameTooLarge, "oversized",
                f"declared payload {length} bytes > cap "
                f"{MAX_FRAME_BYTES}")
        self._fill(HEADER_BYTES + length, deadline_t, "payload")
        payload = bytes(self._buf[HEADER_BYTES:HEADER_BYTES + length])
        del self._buf[:HEADER_BYTES + length]
        if zlib.crc32(payload) != crc:
            raise _bad(BadChecksum, "checksum", "payload CRC mismatch")
        try:
            obj = pickle.loads(payload) if length else None
        except Exception as exc:
            raise _bad(BadChecksum, "payload",
                       f"payload undecodable: {exc}")
        _metrics.count("ipc.frames_received")
        _metrics.count("ipc.bytes_received", HEADER_BYTES + length)
        return ftype, flags, obj


def recv_frame(sock, deadline_s=1.0):
    """One-shot `FrameStream.recv_frame` for tests and short-lived
    connections. A long-lived connection MUST keep one `FrameStream`
    instead: this wrapper forgets partial bytes between calls."""
    return FrameStream(sock).recv_frame(deadline_s)


def encode_frame(ftype, payload_obj=None, flags=0, version=WIRE_VERSION):
    """Encode one frame to bytes (``version`` overridable for tests)."""
    payload = b"" if payload_obj is None else pickle.dumps(
        payload_obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameTooLarge(
            f"payload {len(payload)} bytes > cap {MAX_FRAME_BYTES}")
    header = _HEADER.pack(
        _MAGIC, version, ftype, flags, len(payload), zlib.crc32(payload))
    return header + payload


def send_frame(sock, ftype, payload_obj=None, deadline_s=1.0, flags=0):
    """Send one frame, every byte under the deadline."""
    data = encode_frame(ftype, payload_obj, flags=flags)
    deadline_t = time.monotonic() + deadline_s
    sent = 0
    view = memoryview(data)
    while sent < len(data):
        remaining = deadline_t - time.monotonic()
        if remaining <= 0:
            _metrics.count("ipc.deadline_expired")
            raise WireDeadline(
                f"wire send deadline expired with "
                f"{len(data) - sent}/{len(data)} bytes left")
        sock.settimeout(remaining)
        try:
            sent += sock.send(view[sent:])
        except socket.timeout:
            continue  # remaining-budget check above judges the deadline
        except OSError as exc:
            raise TruncatedFrame(f"peer closed mid-send: {exc}") from exc
    _metrics.count("ipc.frames_sent")
    _metrics.count("ipc.bytes_sent", len(data))
    return len(data)


def connect_unix(path, deadline_s=5.0):
    """Connect to a unix socket, retrying while the peer boots.

    A worker that has not yet bound its socket surfaces as
    ``FileNotFoundError`` / ``ConnectionRefusedError`` — both OSErrors,
    both transient under `resilience.retry.is_transient` — so this
    loops the PR-4 jittered-backoff ladder until the deadline.
    """
    from ..resilience.retry import backoff_delay

    deadline_t = time.monotonic() + deadline_s
    attempt = 0
    while True:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.settimeout(max(0.05, deadline_t - time.monotonic()))
            sock.connect(path)
            return sock
        except OSError:
            sock.close()
            if time.monotonic() >= deadline_t:
                raise
            time.sleep(min(backoff_delay(attempt, base_s=0.02, max_s=0.25),
                           max(0.0, deadline_t - time.monotonic())))
            attempt += 1
