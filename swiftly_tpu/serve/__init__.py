"""On-demand subgrid serving: scheduler, batcher, SLO instrumentation.

The batch drivers (`bench.py`, `scripts/demo_api.py`) enumerate a full
cover; this package serves *individual subgrid requests arriving over
time* — the ROADMAP's "heavy traffic" workload — while keeping device
programs batched and dense (the TPU-DFT throughput discipline of
arXiv:2002.03260 applied to ragged demand):

* `serve.queue.AdmissionQueue` — bounded admission with backpressure:
  depth cap plus a projected-HBM cost model; overload sheds at the
  door instead of growing latency without bound;
* `serve.scheduler.CoalescingScheduler` — groups pending requests by
  subgrid column (``off0``) so ONE ``extract_columns_batch`` + one
  stacked column program serves every subgrid in the column; prefers
  LRU-hot columns (locality) and preempts for urgent deadlines;
* `serve.service.SubgridService` — the long-lived server: wraps a
  prepared `SwiftlyForward` (+ optional recorded-stream cache feed),
  enforces per-request timeouts, isolates and retries batch failures,
  quarantines poisoned requests, and exports latency SLO metrics
  (p50/p99, shed rate, coalesce-hit rate) through ``obs``;
* `serve.health` — heartbeat `HealthLease` per replica plus the
  `HealthMonitor` that grades them (live → suspect → revoked, with
  active probes through the ``fleet.health.probe`` fault site);
* `serve.fleet.ServeFleet` — N supervised service replicas behind a
  rendezvous-hashed column router with per-replica circuit breakers
  (`resilience.breaker`), zero-loss failover, journey-driven brownout
  and hedged sends — the self-healing serve fleet ``bench.py --fleet``
  drills. Attach a `cache.SharedStreamTier` and the replicas serve
  per-replica L1 views over ONE resident recorded stream;
* `serve.procfleet.ProcessFleet` — the same serving contract across
  REAL process boundaries: each replica a separate OS process behind
  a front-door router, speaking `serve.ipc`'s versioned
  length-prefixed wire frames; heartbeats on the wire, cross-process
  L2 through the shared spill directory, and a supervisor that reaps
  and restarts killed workers (``bench.py --procfleet`` lands a real
  ``SIGKILL -9`` mid-burst and proves zero loss);
* `serve.autoscale.FleetAutoscaler` — queue-share-driven elastic
  replica count over a ``[min, max]`` band with hysteresis: scale out
  via `ServeFleet.add_replica` (a fabric view, not a stream copy) and
  scale in through the zero-loss drain path.

Entry points: build a `SwiftlyForward`, wrap it in a `SubgridService`,
then ``submit(config).wait()`` (worker-thread mode via ``start()``) or
``serve([...])`` / ``pump_once()`` (synchronous). ``bench.py --serve``
replays a zipf-over-columns workload through this stack and stamps the
SLO block into its artifact. See docs/serving.md.
"""

from .autoscale import FleetAutoscaler
from .fleet import FleetRequest, Replica, ServeFleet
from .health import (
    LIVE,
    REVOKED,
    SUSPECT,
    HealthLease,
    HealthMonitor,
)
from .queue import (
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_QUARANTINED,
    STATUS_SHED,
    AdmissionQueue,
    RequestResult,
    SubgridRequest,
)
from .procfleet import ProcessFleet, SharedSpillReader, make_worker_spec
from .scheduler import CoalescingScheduler
from .service import (
    SubgridService,
    projected_column_bytes,
    projected_request_bytes,
)

__all__ = [
    "AdmissionQueue",
    "CoalescingScheduler",
    "FleetAutoscaler",
    "FleetRequest",
    "HealthLease",
    "HealthMonitor",
    "LIVE",
    "ProcessFleet",
    "Replica",
    "RequestResult",
    "REVOKED",
    "ServeFleet",
    "SharedSpillReader",
    "SubgridRequest",
    "SubgridService",
    "SUSPECT",
    "STATUS_EXPIRED",
    "STATUS_OK",
    "STATUS_QUARANTINED",
    "STATUS_SHED",
    "make_worker_spec",
    "projected_column_bytes",
    "projected_request_bytes",
]
