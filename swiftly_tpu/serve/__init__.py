"""On-demand subgrid serving: scheduler, batcher, SLO instrumentation.

The batch drivers (`bench.py`, `scripts/demo_api.py`) enumerate a full
cover; this package serves *individual subgrid requests arriving over
time* — the ROADMAP's "heavy traffic" workload — while keeping device
programs batched and dense (the TPU-DFT throughput discipline of
arXiv:2002.03260 applied to ragged demand):

* `serve.queue.AdmissionQueue` — bounded admission with backpressure:
  depth cap plus a projected-HBM cost model; overload sheds at the
  door instead of growing latency without bound;
* `serve.scheduler.CoalescingScheduler` — groups pending requests by
  subgrid column (``off0``) so ONE ``extract_columns_batch`` + one
  stacked column program serves every subgrid in the column; prefers
  LRU-hot columns (locality) and preempts for urgent deadlines;
* `serve.service.SubgridService` — the long-lived server: wraps a
  prepared `SwiftlyForward` (+ optional recorded-stream cache feed),
  enforces per-request timeouts, isolates and retries batch failures,
  quarantines poisoned requests, and exports latency SLO metrics
  (p50/p99, shed rate, coalesce-hit rate) through ``obs``.

Entry points: build a `SwiftlyForward`, wrap it in a `SubgridService`,
then ``submit(config).wait()`` (worker-thread mode via ``start()``) or
``serve([...])`` / ``pump_once()`` (synchronous). ``bench.py --serve``
replays a zipf-over-columns workload through this stack and stamps the
SLO block into its artifact. See docs/serving.md.
"""

from .queue import (
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_QUARANTINED,
    STATUS_SHED,
    AdmissionQueue,
    RequestResult,
    SubgridRequest,
)
from .scheduler import CoalescingScheduler
from .service import (
    SubgridService,
    projected_column_bytes,
    projected_request_bytes,
)

__all__ = [
    "AdmissionQueue",
    "CoalescingScheduler",
    "RequestResult",
    "SubgridRequest",
    "SubgridService",
    "STATUS_EXPIRED",
    "STATUS_OK",
    "STATUS_QUARANTINED",
    "STATUS_SHED",
    "projected_column_bytes",
    "projected_request_bytes",
]
