"""Bounded admission queue for on-demand subgrid serving.

The serving path admits requests arriving over time, so unlike the
batch drivers it must say NO: an unbounded queue under sustained
overload grows until the host (and the projected device working set)
is exhausted, and every queued request's latency grows with it.
`AdmissionQueue` therefore *sheds at the door* — a request is either
admitted (and will be scheduled) or rejected immediately with a
``shed`` result the client can retry against another replica (the
result's ``retry_after_s`` hint, priced from the queue's observed
drain rate by `retry_after_hint`, tells it *when*; the fleet router in
`serve.fleet` acts on it) — on two budgets:

* **depth** — at most ``max_depth`` requests pending (the classic
  bounded-queue latency cap: past it, added queue depth only adds
  waiting time, never throughput);
* **projected HBM cost** — each pending request prices its subgrid
  output and each *distinct pending column* prices one set of column
  intermediates (the ``extract_columns_batch`` product the coalescing
  batcher will materialise); when the projection exceeds
  ``hbm_budget_bytes`` the queue sheds even below ``max_depth``. This
  is the serving-time analogue of the streamed executors'
  HBM-budgeted group sizing.

Requests are keyed by subgrid column offset (``off0``) because that is
the unit the scheduler coalesces on; the queue itself imposes no order
beyond arrival — ordering policy lives in
`serve.scheduler.CoalescingScheduler`.

All entry points are lock-guarded: submissions may come from many
client threads while a pump (or the service's worker thread) drains.
"""

from __future__ import annotations

import itertools
import threading
import time

from ..obs import metrics as _metrics

__all__ = [
    "AdmissionQueue",
    "RequestResult",
    "SubgridRequest",
    "STATUS_OK",
    "STATUS_SHED",
    "STATUS_EXPIRED",
    "STATUS_QUARANTINED",
]

# Terminal request states. Every submitted request ends in exactly one.
STATUS_OK = "ok"                    # served; `data` holds the subgrid
STATUS_SHED = "shed"                # rejected at admission (backpressure)
STATUS_EXPIRED = "expired"          # deadline/timeout passed before service
STATUS_QUARANTINED = "quarantined"  # kept failing after retries; isolated

_REQ_IDS = itertools.count()


class RequestResult:
    """Terminal outcome of one request.

    :param status: one of the ``STATUS_*`` constants
    :param data: the subgrid array (``STATUS_OK`` only) — a device array
        row when computed, a host row when served from a cache feed
    :param error: repr of the final exception (failure statuses)
    :param path: how the request was served — ``"coalesced"`` (column
        batch program), ``"cache"`` (spill-cache feed), ``"retry"``
        (isolated per-request fallback after a batch failure)
    :param batch_size: number of requests the serving dispatch carried
    :param coalesced: True when the request shared its column program
        with at least one other request
    :param retry_after_s: structured backpressure hint on ``shed``
        results — seconds after which a retry (against this or another
        replica) is likely to be admitted, priced from the queue's
        observed drain rate (`AdmissionQueue.retry_after_hint`)

    ``journey`` (set by the service on served requests) decomposes
    ``latency_s`` into contiguous segments
    ``{"queue_s", "compute_s", "transfer_s"}`` that SUM to it exactly:
    admission→taken (queue wait), taken→dispatch-landed (coalesce +
    compute), dispatch-landed→completion (d2h/result materialisation +
    completion bookkeeping).
    """

    __slots__ = (
        "status", "data", "error", "latency_s", "path", "batch_size",
        "coalesced", "retries", "shed_reason", "journey",
        "retry_after_s",
    )

    def __init__(self, status, data=None, error=None, latency_s=0.0,
                 path=None, batch_size=0, coalesced=False, retries=0,
                 shed_reason=None, journey=None, retry_after_s=None):
        self.status = status
        self.data = data
        self.error = error
        self.latency_s = latency_s
        self.path = path
        self.batch_size = batch_size
        self.coalesced = coalesced
        self.retries = retries
        self.shed_reason = shed_reason
        self.journey = journey
        self.retry_after_s = retry_after_s

    @property
    def ok(self):
        return self.status == STATUS_OK

    def __repr__(self):
        extra = f", path={self.path}" if self.path else ""
        if self.error:
            extra += f", error={self.error}"
        return (
            f"RequestResult({self.status}, latency_s="
            f"{self.latency_s:.4f}{extra})"
        )


class SubgridRequest:
    """One in-flight subgrid request.

    Completion is signalled through an event so clients on other
    threads can ``wait()``; the pump thread calls ``_complete`` exactly
    once. Deadlines are absolute (``perf_counter`` timebase), derived
    from the relative ``deadline_s`` at submit time.
    """

    __slots__ = (
        "config", "req_id", "priority", "submit_t", "deadline_t",
        "retries", "result", "_event", "take_t", "compute_t",
        "stream_version",
    )

    def __init__(self, config, priority=0, deadline_s=None, now=None):
        self.config = config
        self.req_id = next(_REQ_IDS)
        self.priority = int(priority)
        self.submit_t = time.perf_counter() if now is None else now
        self.deadline_t = (
            None if deadline_s is None else self.submit_t + float(deadline_s)
        )
        self.retries = 0
        self.result = None
        self._event = threading.Event()
        # the facet-stack version this request was admitted under
        # (stamped by `SubgridService.submit`); the cache feed only
        # serves version-matching requests, so an update mid-queue can
        # never hand a request rows from a different stack than the
        # one it was admitted against
        self.stream_version = None
        # journey marks (set by the queue/pump): when the request left
        # the queue and when its compute landed — with submit_t and the
        # completion time these decompose end-to-end latency into
        # queue-wait / compute / transfer segments that sum exactly
        self.take_t = None
        self.compute_t = None

    def expired(self, now):
        return self.deadline_t is not None and now > self.deadline_t

    @property
    def done(self):
        return self.result is not None

    def wait(self, timeout=None):
        """Block until the request reaches a terminal state; returns the
        `RequestResult` (or None on wait timeout)."""
        self._event.wait(timeout)
        return self.result

    def _complete(self, result):
        self.result = result
        self._event.set()

    def __repr__(self):
        return (
            f"SubgridRequest(#{self.req_id}, off0={self.config.off0}, "
            f"off1={self.config.off1}, prio={self.priority})"
        )


class _ColumnSummary:
    """Scheduler-facing snapshot of one pending column."""

    __slots__ = ("off0", "count", "max_priority", "min_deadline_t",
                 "oldest_submit_t")

    def __init__(self, off0, count, max_priority, min_deadline_t,
                 oldest_submit_t):
        self.off0 = off0
        self.count = count
        self.max_priority = max_priority
        self.min_deadline_t = min_deadline_t
        self.oldest_submit_t = oldest_submit_t


class AdmissionQueue:
    """Bounded, column-keyed admission queue with cost-aware shedding.

    :param max_depth: pending-request cap (admission sheds past it)
    :param hbm_budget_bytes: projected-device-cost cap; None disables
    :param request_bytes: per-request projected output bytes (one
        finished subgrid)
    :param column_bytes: per-distinct-pending-column projected bytes
        (the column intermediates the batcher materialises once per
        column program)
    """

    def __init__(self, max_depth=256, hbm_budget_bytes=None,
                 request_bytes=0, column_bytes=0):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        self.max_depth = int(max_depth)
        self.hbm_budget_bytes = hbm_budget_bytes
        self.request_bytes = int(request_bytes)
        self.column_bytes = int(column_bytes)
        self._lock = threading.Lock()
        self._cols = {}  # off0 -> [SubgridRequest, ...] in arrival order
        self._depth = 0
        # observed drain rate (requests/s leaving via take, EWMA over
        # inter-take gaps) — prices the retry_after_s shed hint
        self._drain_rate = 0.0
        self._last_take_t = None
        self._taken_total = 0

    def __len__(self):
        with self._lock:
            return self._depth

    def _projected_bytes(self, depth, n_cols):  # caller holds the lock
        return depth * self.request_bytes + n_cols * self.column_bytes

    def projected_bytes(self):
        """Projected device cost of the current pending set."""
        with self._lock:
            return self._projected_bytes(self._depth, len(self._cols))

    def offer(self, request, now=None):
        """Admit or shed one request.

        :return: ``(True, None)`` when admitted, else ``(False, reason)``
            with reason in ``("expired", "depth", "hbm")``. The caller
            owns completing a shed request with the matching result.
        """
        now = time.perf_counter() if now is None else now
        with self._lock:
            if request.expired(now):
                return False, "expired"
            if self._depth + 1 > self.max_depth:
                return False, "depth"
            if self.hbm_budget_bytes is not None:
                n_cols = len(self._cols)
                if request.config.off0 not in self._cols:
                    n_cols += 1
                if (
                    self._projected_bytes(self._depth + 1, n_cols)
                    > self.hbm_budget_bytes
                ):
                    return False, "hbm"
            self._cols.setdefault(request.config.off0, []).append(request)
            self._depth += 1
            _metrics.gauge("serve.queue_depth", self._depth)
            _metrics.gauge_max("serve.queue_depth_peak", self._depth)
            return True, None

    def columns(self):
        """Snapshot of pending columns for the scheduler, as a list of
        per-column summaries (count, max priority, earliest deadline,
        oldest arrival)."""
        with self._lock:
            out = []
            for off0, reqs in self._cols.items():
                deadlines = [
                    r.deadline_t for r in reqs if r.deadline_t is not None
                ]
                out.append(
                    _ColumnSummary(
                        off0,
                        len(reqs),
                        max(r.priority for r in reqs),
                        min(deadlines) if deadlines else None,
                        min(r.submit_t for r in reqs),
                    )
                )
            return out

    def take(self, off0, limit=None, now=None):
        """Remove and return up to ``limit`` requests of one column,
        highest priority first (FIFO within a priority). Each taken
        request's ``take_t`` journey mark is stamped here — the end of
        its queue-wait segment."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            reqs = self._cols.get(off0)
            if not reqs:
                return []
            # stable sort: arrival order already holds, so equal
            # priorities keep FIFO
            reqs.sort(key=lambda r: -r.priority)
            if limit is None or limit >= len(reqs):
                taken = reqs
                del self._cols[off0]
            else:
                taken = reqs[:limit]
                self._cols[off0] = reqs[limit:]
            self._depth -= len(taken)
            for r in taken:
                r.take_t = now
            if self._last_take_t is not None and now > self._last_take_t:
                inst = len(taken) / (now - self._last_take_t)
                self._drain_rate = (
                    inst if self._drain_rate == 0.0
                    else 0.8 * self._drain_rate + 0.2 * inst
                )
            self._last_take_t = now
            self._taken_total += len(taken)
            _metrics.gauge("serve.queue_depth", self._depth)
            return taken

    def take_expired(self, now=None):
        """Remove and return every pending request whose deadline has
        passed (the pump times them out before scheduling work)."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            expired = []
            for off0 in list(self._cols):
                keep = []
                for r in self._cols[off0]:
                    (expired if r.expired(now) else keep).append(r)
                if keep:
                    self._cols[off0] = keep
                else:
                    del self._cols[off0]
            self._depth -= len(expired)
            if expired:
                _metrics.gauge("serve.queue_depth", self._depth)
            return expired

    def retry_after_hint(self, now=None):
        """Seconds after which a shed client's retry is likely to be
        admitted: the current backlog priced at the observed drain rate
        (clamped to [0.01, 5.0]; 0.05 before any drain has been
        observed). The structured half of the shed contract — the
        docstring's "retry against another replica" made actionable
        for a router instead of a blind client backoff guess."""
        with self._lock:
            depth = self._depth
            rate = self._drain_rate
        if rate <= 0.0:
            return 0.05
        return min(5.0, max(0.01, (depth + 1) / rate))

    def drain(self):
        """Remove and return everything pending (service shutdown)."""
        with self._lock:
            out = [r for reqs in self._cols.values() for r in reqs]
            self._cols = {}
            self._depth = 0
            _metrics.gauge("serve.queue_depth", 0)
            return out
